"""`multiprocessing.Pool` drop-in over ray_tpu tasks.

Counterpart of the reference's `ray.util.multiprocessing`
(`util/multiprocessing/pool.py`: Pool whose `map`/`apply_async`/`imap`
fan out as Ray tasks instead of local fork workers). Chunking matches the
stdlib contract; AsyncResult wraps an ObjectRef list.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class TimeoutError(Exception):
    pass


def _chunks(it: Iterable, size: int):
    it = iter(it)
    while True:
        chunk = list(itertools.islice(it, size))
        if not chunk:
            return
        yield chunk


@ray_tpu.remote
def _run_chunk(fn, chunk, star: bool, with_kwargs: bool):
    if with_kwargs:
        return [fn(*a, **kw) for a, kw in chunk]
    if star:
        return [fn(*args) for args in chunk]
    return [fn(x) for x in chunk]


class AsyncResult:
    """multiprocessing.pool.AsyncResult lookalike over ObjectRefs."""

    def __init__(self, refs: List, single: bool = False,
                 callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None):
        self._refs = refs
        self._single = single
        if callback or error_callback:
            def run_cb():
                try:
                    val = self.get()
                except BaseException as e:
                    if error_callback:
                        error_callback(e)
                else:
                    if callback:
                        callback(val)
            threading.Thread(target=run_cb, daemon=True).start()

    def get(self, timeout: Optional[float] = None) -> Any:
        try:
            parts = ray_tpu.get(self._refs, timeout=timeout)
        except ray_tpu.exceptions.GetTimeoutError as e:
            raise TimeoutError(str(e)) from None
        out = [x for chunk in parts for x in chunk]
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        try:
            self.get(timeout=0)
            return True
        except BaseException:
            return False


class Pool:
    """Process pool on the cluster. `processes` bounds parallelism hints
    only — scheduling is the cluster scheduler's job."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._processes = processes or int(
            ray_tpu.cluster_resources().get("CPU", 1))
        self._closed = False
        # initializer runs inside each task via a wrapper (stdlib runs it
        # once per worker; with task reuse this is per-chunk — documented
        # deviation, same as the reference's pool)
        self._initializer = initializer
        self._initargs = initargs

    def _check(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _wrap(self, fn):
        init, initargs = self._initializer, self._initargs
        if init is None:
            return fn

        def wrapped(*a, **kw):
            init(*initargs)
            return fn(*a, **kw)
        return wrapped

    def _chunksize(self, n: int, chunksize: Optional[int]) -> int:
        if chunksize:
            return chunksize
        # stdlib heuristic: divide work into ~4 chunks per process
        return max(1, n // (self._processes * 4) or 1)

    # -- apply ---------------------------------------------------------------

    def apply(self, fn, args: tuple = (), kwds: dict | None = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn, args: tuple = (), kwds: dict | None = None,
                    callback=None, error_callback=None) -> AsyncResult:
        self._check()
        ref = _run_chunk.remote(self._wrap(fn), [(args, kwds or {})],
                                False, True)
        return AsyncResult([ref], single=True, callback=callback,
                           error_callback=error_callback)

    # -- map -----------------------------------------------------------------

    def map(self, fn, iterable: Iterable, chunksize: int | None = None):
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable: Iterable,
                  chunksize: int | None = None, callback=None,
                  error_callback=None) -> AsyncResult:
        self._check()
        items = list(iterable)
        cs = self._chunksize(len(items), chunksize)
        fn = self._wrap(fn)
        refs = [_run_chunk.remote(fn, c, False, False)
                for c in _chunks(items, cs)]
        return AsyncResult(refs, callback=callback,
                           error_callback=error_callback)

    def starmap(self, fn, iterable: Iterable,
                chunksize: int | None = None):
        self._check()
        items = list(iterable)
        cs = self._chunksize(len(items), chunksize)
        fn = self._wrap(fn)
        refs = [_run_chunk.remote(fn, c, True, False)
                for c in _chunks(items, cs)]
        return AsyncResult(refs).get()

    def imap(self, fn, iterable: Iterable, chunksize: int | None = None):
        """Ordered lazy iterator."""
        self._check()
        items = list(iterable)
        cs = chunksize or 1
        fn = self._wrap(fn)
        refs = [_run_chunk.remote(fn, c, False, False)
                for c in _chunks(items, cs)]
        for ref in refs:
            for x in ray_tpu.get(ref):
                yield x

    def imap_unordered(self, fn, iterable: Iterable,
                       chunksize: int | None = None):
        """Yield chunks as they complete."""
        self._check()
        items = list(iterable)
        cs = chunksize or 1
        fn = self._wrap(fn)
        pending = [_run_chunk.remote(fn, c, False, False)
                   for c in _chunks(items, cs)]
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1,
                                          timeout=None)
            for x in ray_tpu.get(ready[0]):
                yield x

    # -- lifecycle -----------------------------------------------------------

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
        return False
