"""Opt-in tracing spans (OpenTelemetry-style, dependency-free).

Counterpart of the reference's `ray.util.tracing`
(`util/tracing/tracing_helper.py`: lazy OpenTelemetry proxy, spans around
task submit/execute, enabled via `ray.init(_tracing_startup_hook=...)`).
OpenTelemetry isn't in this image, so spans are recorded in-process with
the OTel span shape (name, trace/span ids, start/end ns, attributes,
parent) and exported as JSON — loadable by OTel collectors' file receiver
or converted to chrome://tracing. Task-level spans come for free from the
task-event recorder (ray_tpu.timeline); this module adds *application*
spans inside tasks/actors with cross-process parent propagation via the
runtime context.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid
from typing import Dict, List, Optional

_enabled = False
_lock = threading.Lock()
_spans: List[dict] = []
_current = threading.local()

# Retention: the span list is a ring — a long-running engine must not
# grow driver memory without bound. Overflow evictions are counted so a
# truncated export is observable, never silent.
DEFAULT_MAX_SPANS = 10_000
_max_spans = int(os.environ.get("RAY_TPU_TRACING_MAX_SPANS",
                                DEFAULT_MAX_SPANS))
_dropped = 0


def set_max_spans(cap: int) -> None:
    """Configure the span ring's capacity (evicting oldest if needed)."""
    global _max_spans, _dropped
    with _lock:
        _max_spans = max(1, int(cap))
        while len(_spans) > _max_spans:
            _spans.pop(0)
            _dropped += 1


def max_spans() -> int:
    return _max_spans


def dropped_spans() -> int:
    """Spans evicted from the ring since process start (or clear)."""
    return _dropped


def _record(s: dict) -> None:
    global _dropped
    with _lock:
        _spans.append(s)
        while len(_spans) > _max_spans:
            _spans.pop(0)
            _dropped += 1


def enable_tracing() -> None:
    """Turn span recording on in this process (workers inherit via the
    RAY_TPU_TRACING env var set by the driver's worker env)."""
    global _enabled
    _enabled = True
    os.environ["RAY_TPU_TRACING"] = "1"


def tracing_enabled() -> bool:
    return _enabled or os.environ.get("RAY_TPU_TRACING") == "1"


def _new_id(nbytes: int) -> str:
    return uuid.uuid4().hex[:nbytes * 2]


@contextlib.contextmanager
def span(name: str, attributes: Optional[Dict] = None):
    """Record one span; nests under the active span of this thread."""
    if not tracing_enabled():
        yield None
        return
    parent = getattr(_current, "span", None)
    s = {
        "name": name,
        "trace_id": parent["trace_id"] if parent else _new_id(16),
        "span_id": _new_id(8),
        "parent_span_id": parent["span_id"] if parent else None,
        "start_ns": time.time_ns(),
        "end_ns": None,
        "attributes": dict(attributes or {}),
        "status": "OK",
        "process": os.getpid(),
    }
    _current.span = s
    try:
        yield s
    except BaseException as e:
        s["status"] = "ERROR"
        s["attributes"]["exception"] = repr(e)
        raise
    finally:
        s["end_ns"] = time.time_ns()
        _current.span = parent
        _record(s)


def capture_context() -> Optional[dict]:
    """The calling thread's active span, for handing to another thread
    (`_current` is a threading.local — a worker thread spawned by a
    request does NOT inherit the submitter's span without this)."""
    return getattr(_current, "span", None)


def attach_context(ctx: Optional[dict]):
    """Make `ctx` (from `capture_context()` on the submitting thread)
    the calling thread's active span, so spans this thread opens nest
    under the submitter's. Returns a token for `detach_context`."""
    prev = getattr(_current, "span", None)
    _current.span = ctx
    return prev


def detach_context(token) -> None:
    """Restore the context that was active before `attach_context`."""
    _current.span = token


def get_spans() -> List[dict]:
    with _lock:
        return list(_spans)


def clear_spans() -> None:
    global _dropped
    with _lock:
        _spans.clear()
        _dropped = 0


def export_json(path: str) -> int:
    """Write this process's spans as a JSON list; returns the count."""
    spans = get_spans()
    with open(path, "w") as f:
        json.dump(spans, f)
    return len(spans)


def spans_to_chrome_trace(spans: Optional[List[dict]] = None) -> List[dict]:
    """Convert to chrome://tracing 'X' events (merge with ray_tpu.timeline
    output for one combined view)."""
    out = []
    for s in (spans if spans is not None else get_spans()):
        end = s["end_ns"] or time.time_ns()
        out.append({
            "name": s["name"], "cat": "span", "ph": "X",
            "ts": s["start_ns"] / 1e3, "dur": (end - s["start_ns"]) / 1e3,
            "pid": s["process"], "tid": s["trace_id"][:8],
            "args": s["attributes"],
        })
    return out
