"""Opt-in tracing spans (OpenTelemetry-style, dependency-free).

Counterpart of the reference's `ray.util.tracing`
(`util/tracing/tracing_helper.py`: lazy OpenTelemetry proxy, spans around
task submit/execute, enabled via `ray.init(_tracing_startup_hook=...)`).
OpenTelemetry isn't in this image, so spans are recorded in-process with
the OTel span shape (name, trace/span ids, start/end ns, attributes,
parent) and exported as JSON — loadable by OTel collectors' file receiver
or converted to chrome://tracing.

Cross-process propagation is explicit, not ambient: the submitting
client stamps `propagation_context()` — a minimal `{trace_id, span_id}`
dict — onto `TaskSpec.trace_ctx` (`_private/worker.py` submit paths);
the executing worker `attach_context`s it and opens a `task.execute`
span (`_private/worker_main.py`), so spans opened inside the task nest
under the submitter's. The serve plane rides the same rails: the HTTP
proxy opens a root span per request and attaches it around the handle
call, handle→replica is an actor-method task (stamped like any other),
and the replica's context flows into the engine caller thread via
`contextvars` (`Replica._invoke` copies the context), where the
`FlightRecorder` parents its request spans under it. Workers drain
their span rings back to the head — piggybacked on `TaskDone` and on
the periodic metrics flush — and the head `ingest()`s them into its own
ring, so `export_json` / the node's "timeline" verb emit ONE merged
cluster trace instead of per-process fragments.

The active-span slot is a `contextvars.ContextVar`: it flows into
asyncio tasks and (via `contextvars.copy_context().run`) into executor
threads, which a `threading.local` cannot do.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import json
import os
import threading
import time
import uuid
from typing import Dict, List, Optional

# Enablement is a cached process-local flag, refreshed only by
# enable_tracing()/_enable_local() (the SetTracing broadcast) and read
# from the RAY_TPU_TRACING env var once at import — spawned workers
# inherit the driver's env, and live ones get the broadcast. The off
# path of span() must stay a couple of attribute reads; an os.environ
# lookup per call is already too expensive for the <1% task-overhead
# contract scale_bench enforces.
_enabled = os.environ.get("RAY_TPU_TRACING") == "1"
_lock = threading.Lock()
_current: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_active_span", default=None)

# Retention: the span store is a ring — a long-running engine must not
# grow driver memory without bound. Overflow evictions are counted so a
# truncated export is observable, never silent. The ring is a deque so
# eviction is O(1) (a list's pop(0) made every overflowing record O(n)).
DEFAULT_MAX_SPANS = 10_000
_max_spans = int(os.environ.get("RAY_TPU_TRACING_MAX_SPANS",
                                DEFAULT_MAX_SPANS))
_spans: "collections.deque[dict]" = collections.deque(maxlen=_max_spans)
_dropped = 0

# Human-readable lane for this process in merged chrome traces
# ("driver", "worker:<id>", ...); falls back to the pid.
_proc_label: Optional[str] = None


def set_process_label(label: str) -> None:
    """Name this process's lane in merged chrome-trace exports."""
    global _proc_label
    _proc_label = label


def process_label() -> str:
    return _proc_label or f"pid-{os.getpid()}"


def set_max_spans(cap: int) -> None:
    """Configure the span ring's capacity (evicting oldest if needed)."""
    global _max_spans, _spans, _dropped
    with _lock:
        _max_spans = max(1, int(cap))
        old = _spans
        _spans = collections.deque(maxlen=_max_spans)
        while len(old) > _max_spans:
            old.popleft()
            _dropped += 1
        _spans.extend(old)


def max_spans() -> int:
    return _max_spans


def dropped_spans() -> int:
    """Spans evicted from the ring since process start (or clear)."""
    return _dropped


def _record(s: dict) -> None:
    global _dropped
    with _lock:
        if len(_spans) == _max_spans:
            _dropped += 1        # deque(maxlen) evicts silently; count it
        _spans.append(s)


def enable_tracing() -> None:
    """Turn span recording on cluster-wide: in this process, in workers
    spawned later (they inherit the RAY_TPU_TRACING env var), and — when
    a session is live — in already-running workers via a control-plane
    broadcast (protocol.SetTracing)."""
    global _enabled
    _enabled = True
    os.environ["RAY_TPU_TRACING"] = "1"
    try:
        from ray_tpu._private import worker as _worker
        if _worker.is_initialized():
            _worker._global_client.control("enable_tracing")
    except Exception:
        pass   # no session yet: env inheritance covers future workers


def tracing_enabled() -> bool:
    """True when span recording is on in this process — set by
    `enable_tracing()`, the SetTracing broadcast, or the inherited
    RAY_TPU_TRACING env var (read once at import)."""
    return _enabled


def _enable_local() -> None:
    """Process-local enable (the receiving end of the broadcast)."""
    global _enabled
    _enabled = True
    os.environ["RAY_TPU_TRACING"] = "1"


def _new_id(nbytes: int) -> str:
    return uuid.uuid4().hex[:nbytes * 2]


def _make_span(name: str, parent: Optional[dict],
               attributes: Optional[Dict]) -> dict:
    return {
        "name": name,
        "trace_id": parent["trace_id"] if parent else _new_id(16),
        "span_id": _new_id(8),
        "parent_span_id": parent["span_id"] if parent else None,
        "start_ns": time.time_ns(),
        "end_ns": None,
        "attributes": dict(attributes or {}),
        "status": "OK",
        "process": os.getpid(),
        "proc": process_label(),
        "thread": threading.current_thread().name,
    }


class _NullSpan:
    """Reusable no-op context manager: the tracing-off fast path of
    `span()`. A contextlib generator costs microseconds per call even
    when it yields immediately; this is two slotted method calls."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def span(name: str, attributes: Optional[Dict] = None):
    """Record one span; nests under the active span of this context.
    With tracing off this is a flag read + a shared null context."""
    if not _enabled:
        return _NULL_SPAN
    return _live_span(name, attributes)


@contextlib.contextmanager
def _live_span(name: str, attributes: Optional[Dict]):
    parent = _current.get()
    s = _make_span(name, parent, attributes)
    _current.set(s)
    try:
        yield s
    except BaseException as e:
        s["status"] = "ERROR"
        s["attributes"]["exception"] = repr(e)
        raise
    finally:
        s["end_ns"] = time.time_ns()
        _current.set(parent)
        _record(s)


def start_span(name: str, attributes: Optional[Dict] = None,
               parent: Optional[dict] = None):
    """Manual span start for code that can't wrap its body in a `with`
    (async request handlers, cross-thread hops). Unlike `span()` this
    does NOT gate on `tracing_enabled()` — callers open one exactly when
    a propagated context proves the trace is live (or they checked
    themselves). Returns (span, token) for `end_span`."""
    s = _make_span(name, parent if parent is not None else _current.get(),
                   attributes)
    token = _current.get()
    _current.set(s)
    return s, token


def end_span(s: dict, token, error: Optional[str] = None) -> None:
    """Close a span from `start_span` and restore the prior context."""
    if error:
        s["status"] = "ERROR"
        s["attributes"]["exception"] = error
    s["end_ns"] = time.time_ns()
    _current.set(token)
    _record(s)


def capture_context() -> Optional[dict]:
    """The active span (or attached remote context) of this execution
    context, for handing to another thread/task explicitly."""
    return _current.get()


def propagation_context(span_dict: Optional[dict] = None) -> Optional[dict]:
    """Minimal wire-format context — `{"trace_id", "span_id"}` — for
    stamping onto a TaskSpec. Reads the active span when `span_dict` is
    not given; returns None when no trace is active (nothing is stamped,
    nothing is recorded: the disabled path stays one ContextVar read)."""
    s = span_dict if span_dict is not None else _current.get()
    if s is None:
        return None
    return {"trace_id": s["trace_id"], "span_id": s["span_id"]}


def attach_context(ctx: Optional[dict]):
    """Make `ctx` (a span or a `propagation_context()` dict from the
    submitter) the calling context's active span, so spans opened here
    nest under the submitter's. Returns a token for `detach_context`."""
    prev = _current.get()
    _current.set(ctx)
    return prev


def detach_context(token) -> None:
    """Restore the context that was active before `attach_context`."""
    _current.set(token)


def get_spans() -> List[dict]:
    with _lock:
        return list(_spans)


def drain_spans() -> List[dict]:
    """Atomically remove and return all buffered spans (the worker→head
    collection hop: drained spans ride TaskDone / the metrics flush up
    to the head, which `ingest()`s them)."""
    with _lock:
        if not _spans:
            return []
        out = list(_spans)
        _spans.clear()
        return out


def ingest(spans: List[dict]) -> int:
    """Head side of the drain: append spans produced by another process
    into this ring (same cap + dropped accounting). Returns the count."""
    global _dropped
    if not spans:
        return 0
    with _lock:
        for s in spans:
            if isinstance(s, dict):
                if len(_spans) == _max_spans:
                    _dropped += 1
                _spans.append(s)
    return len(spans)


def clear_spans() -> None:
    global _dropped
    with _lock:
        _spans.clear()
        _dropped = 0


def export_json(path: str) -> int:
    """Write this process's spans as a JSON list; returns the count. On
    the head, workers' drained spans are already merged into the ring,
    so this is the whole-cluster trace."""
    spans = get_spans()
    with open(path, "w") as f:
        json.dump(spans, f)
    return len(spans)


def probe_disabled_overhead_ns(iters: int = 20_000) -> float:
    """Per-call cost (ns) of the tracing-OFF hot path: `span()` with
    recording disabled. scale_bench compares this against measured task
    latency to assert the always-compiled-in instrumentation costs <1%."""
    global _enabled
    prev_enabled, prev_env = _enabled, os.environ.pop("RAY_TPU_TRACING",
                                                      None)
    _enabled = False
    try:
        t0 = time.perf_counter_ns()
        for _ in range(iters):
            with span("overhead-probe"):
                pass
        dt = time.perf_counter_ns() - t0
    finally:
        _enabled = prev_enabled
        if prev_env is not None:
            os.environ["RAY_TPU_TRACING"] = prev_env
    return dt / max(1, iters)


def spans_to_chrome_trace(spans: Optional[List[dict]] = None) -> List[dict]:
    """Convert to chrome://tracing 'X' events (merge with ray_tpu.timeline
    output for one combined view). Lanes are real process identities —
    pid = the producing process's label ("driver", "worker:<id>"), tid =
    the producing thread (or a span-supplied lane) — so a merged
    multi-process trace separates correctly instead of scattering one
    lane per trace id. The trace id rides in args for filtering."""
    out = []
    for s in (spans if spans is not None else get_spans()):
        end = s["end_ns"] or time.time_ns()
        out.append({
            "name": s["name"], "cat": s.get("cat", "span"), "ph": "X",
            "ts": s["start_ns"] / 1e3, "dur": (end - s["start_ns"]) / 1e3,
            "pid": s.get("proc") or s["process"],
            "tid": s.get("lane") or s.get("thread") or "main",
            "args": {**s["attributes"], "trace_id": s["trace_id"],
                     "span_id": s["span_id"]},
        })
    return out
