"""General pubsub channels over the cluster control store.

Counterpart of the reference's pubsub framework
(`src/ray/pubsub/publisher.h:307` long-poll Publisher/SubscriberState +
`_private/gcs_pubsub.py`): named channels live on the head; publishers
append, subscribers long-poll from their cursor. Any session member —
driver, worker, client driver, CLI attach — can publish or subscribe,
which is what the reference uses for log/error/actor-event fanout.

    pub = Publisher("alerts")
    pub.publish({"sev": "warn", "msg": "thermal"})

    sub = Subscriber("alerts")
    for msg in sub.poll(timeout=10):
        ...
"""

from __future__ import annotations

from typing import Any, List


def _control():
    from ray_tpu._private.worker import get_client
    return get_client().control


class Publisher:
    def __init__(self, channel: str):
        self.channel = channel

    def publish(self, message: Any) -> int:
        """Append to the channel; returns the message's sequence number.
        Messages must be picklable; the head retains the last
        PUBSUB_RING_MESSAGES per channel."""
        return _control()("pubsub_publish",
                          {"channel": self.channel, "message": message})


class Subscriber:
    """Cursor-tracking subscriber: each poll returns only messages newer
    than the last batch seen (a fresh subscriber starts at the ring's
    current tail unless `from_start=True`)."""

    def __init__(self, channel: str, from_start: bool = False):
        self.channel = channel
        if from_start:
            self._cursor = 0
        else:
            last, _ = _control()("pubsub_poll",
                                 {"channel": channel, "after": 1 << 62,
                                  "timeout": 0.0})
            self._cursor = last

    def poll(self, timeout: float = 30.0) -> List[Any]:
        """Long-poll: block up to `timeout` for new messages."""
        # the client-side deadline (honored by transports that have one)
        # sits strictly above the server-side poll; the head additionally
        # caps attach-worker polls below ATTACH_CONTROL_TIMEOUT_S so an
        # idle channel returns an empty batch instead of racing the
        # transport timeout into a spurious ConnectionError
        last, msgs = _control()(
            "pubsub_poll",
            {"channel": self.channel, "after": self._cursor,
             "timeout": timeout},
            timeout=timeout + 10.0)
        if msgs:
            self._cursor = last
        return msgs
