"""Application metrics API: Counter / Gauge / Histogram.

Counterpart of `ray.util.metrics` (`python/ray/util/metrics.py:150,215,290`)
over the reference's OpenCensus pipeline (`src/ray/stats/metric.h:103` →
per-node metrics agent → Prometheus scrape). Here each process keeps a
registry; worker processes flush snapshots to the driver over the control
channel (the metrics-agent hop), and the driver aggregates across
processes. `render_prometheus` emits the text exposition format the
dashboard's /metrics endpoint serves.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ray_tpu._private.constants import \
    METRICS_FLUSH_PERIOD_S as _FLUSH_PERIOD_S

DEFAULT_HISTOGRAM_BOUNDARIES = [
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000]


class _Registry:
    def __init__(self):
        self.lock = threading.Lock()
        self.metrics: dict[str, "Metric"] = {}
        self._flusher_started = False

    def register(self, metric: "Metric"):
        with self.lock:
            existing = self.metrics.get(metric.name)
            if existing is not None and type(existing) is not type(metric):
                raise ValueError(
                    f"metric {metric.name!r} already registered as "
                    f"{type(existing).__name__}")
            self.metrics[metric.name] = metric
        self._ensure_flusher()

    def snapshot(self) -> list[dict]:
        with self.lock:
            return [m._snapshot() for m in self.metrics.values()]

    def _ensure_flusher(self):
        """Workers push snapshots to the driver periodically (the
        worker → metrics-agent hop in the reference)."""
        if self._flusher_started:
            return
        from ray_tpu._private import worker as _worker
        client = _worker._global_client
        if client is None or client.mode != "worker":
            return
        self._flusher_started = True
        wid = getattr(client.rt, "worker_id", "worker")

        def _loop():
            while True:
                time.sleep(_FLUSH_PERIOD_S)
                try:
                    # module-level snapshot(): runs collect hooks so a
                    # worker-resident engine's gauges refresh per flush
                    client.control("push_metrics", (wid, snapshot()))
                    _push_spans(client, wid)
                except Exception:
                    return  # driver gone; session over

        threading.Thread(target=_loop, name="ray_tpu-metrics-flush",
                         daemon=True).start()

    def flush_now(self):
        from ray_tpu._private import worker as _worker
        client = _worker._global_client
        if client is not None and client.mode == "worker":
            try:
                wid = getattr(client.rt, "worker_id", "worker")
                client.control("push_metrics", (wid, snapshot()))
                _push_spans(client, wid)
            except Exception:
                pass


def _push_spans(client, wid: str) -> None:
    """Piggyback the tracing span drain on the metrics flush — the
    worker→head collection hop for spans that are not tied to a task
    completion (actor-resident engines, long-lived replicas)."""
    from ray_tpu.util import tracing as _tracing
    if not _tracing.tracing_enabled():
        return
    spans = _tracing.drain_spans()
    import sys as _sys
    if "ray_tpu.util.telemetry" in _sys.modules:
        from ray_tpu.util import telemetry as _telemetry
        spans += _telemetry.drain_recorder_spans()
    if spans:
        client.control("push_spans", (wid, spans))


_registry = _Registry()


def _check_tags(declared: Tuple[str, ...], given: Optional[Dict[str, str]],
                default: Optional[Dict[str, str]]):
    tags = dict(default or {})
    if given:
        tags.update(given)
    extra = set(tags) - set(declared)
    missing = set(declared) - set(tags)
    if extra or missing:
        raise ValueError(
            f"tag keys mismatch: declared {declared}, got {sorted(tags)}")
    return tuple(sorted(tags.items()))


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        if not name:
            raise ValueError("metric name is required")
        if isinstance(tag_keys, str) or not all(
                isinstance(k, str) for k in tag_keys):
            raise TypeError("tag_keys must be a tuple of strings")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: dict = {}
        self._lock = threading.Lock()
        self._series: dict[tuple, float] = {}
        _registry.register(self)

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _snapshot(self) -> dict:
        with self._lock:
            return {"name": self.name, "type": type(self).__name__.lower(),
                    "description": self.description,
                    "series": dict(self._series)}


class Counter(Metric):
    """Monotonically increasing value (util/metrics.py:150)."""

    def inc(self, value: float = 1.0, tags: Optional[Dict] = None):
        if value <= 0:
            raise ValueError("Counter.inc requires value > 0")
        key = _check_tags(self.tag_keys, tags, self._default_tags)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value


class Gauge(Metric):
    """Last-set value (util/metrics.py:215)."""

    def set(self, value: float, tags: Optional[Dict] = None):
        key = _check_tags(self.tag_keys, tags, self._default_tags)
        with self._lock:
            self._series[key] = float(value)


class Histogram(Metric):
    """Bucketed observations (util/metrics.py:290)."""

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[list] = None,
                 tag_keys: Tuple[str, ...] = ()):
        self.boundaries = sorted(boundaries or DEFAULT_HISTOGRAM_BOUNDARIES)
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: Optional[Dict] = None):
        key = _check_tags(self.tag_keys, tags, self._default_tags)
        with self._lock:
            buckets, total, count = self._series.get(
                key, ([0] * (len(self.boundaries) + 1), 0.0, 0))
            buckets = list(buckets)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    buckets[i] += 1
                    break
            else:
                buckets[-1] += 1
            self._series[key] = (buckets, total + value, count + 1)

    def _snapshot(self) -> dict:
        with self._lock:
            return {"name": self.name, "type": "histogram",
                    "description": self.description,
                    "boundaries": list(self.boundaries),
                    "series": {k: (list(v[0]), v[1], v[2])
                               for k, v in self._series.items()}}


# Pull-style collectors: hooks run at the top of every snapshot (scrape
# or worker flush), BEFORE the registry lock is taken, so a hook may
# freely create/register/set metrics. util.telemetry uses this to
# refresh engine/train gauges from their stats() dicts at scrape time.
_collect_hooks: list[Callable[[], None]] = []


def add_collect_hook(fn: Callable[[], None]) -> None:
    if fn not in _collect_hooks:
        _collect_hooks.append(fn)


def remove_collect_hook(fn: Callable[[], None]) -> None:
    if fn in _collect_hooks:
        _collect_hooks.remove(fn)


def snapshot() -> list[dict]:
    """This process's metrics."""
    for hook in list(_collect_hooks):
        try:
            hook()
        except Exception:
            pass   # a broken collector must not break the scrape
    return _registry.snapshot()


def flush() -> None:
    """Push this worker's metrics to the driver immediately."""
    _registry.flush_now()


def ensure_flusher() -> None:
    """Start the worker→driver flush loop even if no Metric exists in
    this process yet. Collect-hook-only sources (register_stats_source)
    create their metrics lazily at the first snapshot — which only the
    flusher takes in a worker, so they must be able to start it. Span
    collection piggybacks on the same loop, and a process can produce
    spans without ever creating a metric (the HTTP proxy opens request
    spans but owns no counters) — worker_main calls this at startup so
    every worker has a drain heartbeat."""
    _registry._ensure_flusher()


def merge_snapshots(snapshots: list[list[dict]]) -> list[dict]:
    """Aggregate per-process snapshots (driver side): counters/histograms
    sum across processes; gauges keep the last writer."""
    out: dict[str, dict] = {}
    for snap in snapshots:
        for m in snap:
            cur = out.get(m["name"])
            if cur is None:
                out[m["name"]] = {**m, "series": dict(m["series"])}
                continue
            for key, val in m["series"].items():
                if m["type"] == "counter":
                    cur["series"][key] = cur["series"].get(key, 0.0) + val
                elif m["type"] == "histogram":
                    prev = cur["series"].get(key)
                    if prev is None:
                        cur["series"][key] = val
                    else:
                        cur["series"][key] = (
                            [a + b for a, b in zip(prev[0], val[0])],
                            prev[1] + val[1], prev[2] + val[2])
                else:
                    cur["series"][key] = val
    return list(out.values())


def _esc(value) -> str:
    """Escape a label value per the prometheus exposition format."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_OK = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def sanitize_name(name: str, label: bool = False) -> str:
    """Map an arbitrary string onto the Prometheus metric-name charset
    ``[a-zA-Z_:][a-zA-Z0-9_:]*`` (labels additionally exclude ':').
    Application code is free to name metrics 'engine0/ttft ms'; the
    exposition must not emit that verbatim or the scrape is rejected."""
    ok = _LABEL_OK if label else _NAME_OK
    if name and ok.match(name):
        return name
    bad = r"[^a-zA-Z0-9_]" if label else r"[^a-zA-Z0-9_:]"
    out = re.sub(bad, "_", name or "_")
    if not re.match(r"[a-zA-Z_]" if label else r"[a-zA-Z_:]", out[0]):
        out = "_" + out
    return out


def format_float(v) -> str:
    """Canonical float formatting for `le` bucket labels and values —
    Go strconv style ('0.001', '1.0', '+Inf'), round-trippable with
    float(); never repr() (whose output for numpy scalars / ints is not
    a Prometheus float)."""
    v = float(v)
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return f"{int(v)}.0"
    return repr(v)


def _labels(pairs) -> str:
    if not pairs:
        return ""
    return ("{" + ",".join(
        f'{sanitize_name(str(k), label=True)}="{_esc(v)}"'
        for k, v in pairs) + "}")


def render_prometheus(metrics: list[dict]) -> str:
    """Prometheus text exposition of an aggregated snapshot."""
    lines = []
    for m in metrics:
        name = sanitize_name("ray_tpu_" + m["name"])
        lines.append(f"# HELP {name} {_esc(m['description'])}")
        lines.append(f"# TYPE {name} {m['type']}")
        for key, val in m["series"].items():
            label = _labels(key)
            if m["type"] == "histogram":
                buckets, total, count = val
                cum = 0
                for i, b in enumerate(m["boundaries"]):
                    cum += buckets[i]
                    lines.append(
                        f"{name}_bucket"
                        f"{_labels(key + (('le', format_float(b)),))}"
                        f" {cum}")
                cum += buckets[-1]
                lines.append(
                    f"{name}_bucket{_labels(key + (('le', '+Inf'),))} {cum}")
                lines.append(f"{name}_sum{label} {total}")
                lines.append(f"{name}_count{label} {count}")
            else:
                lines.append(f"{name}{label} {val}")
    return "\n".join(lines) + "\n"
