"""Runtime context (reference: `python/ray/runtime_context.py`)."""

from __future__ import annotations

from ray_tpu._private import worker as _worker


class RuntimeContext:
    def __init__(self, client):
        self._client = client

    @property
    def is_initialized(self) -> bool:
        return _worker.is_initialized()

    def get_task_id(self) -> str | None:
        if self._client.mode == "worker":
            return self._client.rt.current_task_id()
        return None

    def get_actor_id(self) -> str | None:
        if self._client.mode == "worker":
            return self._client.rt.actor_id
        return None

    def get_worker_id(self) -> str | None:
        if self._client.mode == "worker":
            return self._client.rt.worker_id
        return "driver"

    def get_node_id(self) -> str:
        return "node_local"

    def get_assigned_resources(self) -> dict:
        return {}


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_worker.get_client())
