"""Device mesh construction — the TPU-native parallelism substrate.

The reference has no model-partitioning layer at all (SURVEY.md §2.4: TP/PP/
SP/EP absent); its parallelism is orchestration (N workers x DDP over NCCL,
`train/torch/config.py:113`). On TPU, partitioning belongs to the compiler:
one `jax.sharding.Mesh` with named axes replaces every bolt-on. This module
standardizes the axis vocabulary and mesh construction for the whole
framework (train/tune/serve/rl all build meshes through here).

Axis names (any subset, in logical-outer to logical-inner order):

- ``data``    pure data parallelism (gradient psum over ICI/DCN)
- ``fsdp``    data parallelism with parameter/optimizer sharding (ZeRO-3
              equivalent, but expressed as a PartitionSpec, not a wrapper)
- ``tensor``  tensor parallelism (megatron-style sharded matmuls)
- ``seq``     sequence/context parallelism (ring attention over ICI)
- ``expert``  expert parallelism for MoE layers
- ``pipe``    pipeline stages
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_ORDER = ("pipe", "data", "fsdp", "seq", "expert", "tensor")


@dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape. Sizes of -1 are inferred from the device
    count (at most one -1). Axes of size 1 are kept (harmless to XLA and
    they make PartitionSpecs stable across scale changes)."""

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    pipe: int = 1

    def sizes(self) -> dict:
        return {"pipe": self.pipe, "data": self.data, "fsdp": self.fsdp,
                "seq": self.seq, "expert": self.expert,
                "tensor": self.tensor}

    def resolve(self, n_devices: int) -> dict:
        sizes = self.sizes()
        unknown = [k for k, v in sizes.items() if v == -1]
        if len(unknown) > 1:
            raise ValueError(f"at most one axis may be -1, got {unknown}")
        known = math.prod(v for v in sizes.values() if v != -1)
        if unknown:
            if n_devices % known:
                raise ValueError(
                    f"cannot infer {unknown[0]}: {n_devices} devices not "
                    f"divisible by {known}")
            sizes[unknown[0]] = n_devices // known
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {math.prod(sizes.values())} devices, "
                f"have {n_devices}")
        return sizes

    def build(self, devices=None) -> Mesh:
        """Construct the Mesh. Axis order puts `tensor` innermost so tensor-
        parallel collectives ride the fastest ICI links, and `pipe`/`data`
        outermost (DCN-friendly) — the scaling-book layout recipe."""
        if devices is None:
            devices = jax.devices()
        sizes = self.resolve(len(devices))
        shape = tuple(sizes[a] for a in AXIS_ORDER)
        try:
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=np.asarray(devices))
        except (ValueError, AssertionError):
            # Fallback (CPU meshes, odd topologies): row-major reshape.
            dev_array = np.asarray(devices).reshape(shape)
        return Mesh(dev_array, AXIS_ORDER)

    def build_multislice(self, num_slices: int, devices=None) -> Mesh:
        """Multi-slice (DCN) mesh: the OUTER factor of the `data` (or,
        when data==1, `pipe`) axis spans slices, so gradient psums do a
        hierarchical reduce (in-slice over ICI, then one cross-slice hop
        over DCN) while every model axis (fsdp/seq/expert/tensor) stays
        inside a slice — the scaling-book multi-pod recipe. On real
        multi-slice TPU runtimes this delegates to
        `mesh_utils.create_hybrid_device_mesh` (slice-aware placement);
        elsewhere (CPU simulation, single-slice) devices are grouped
        into `num_slices` contiguous blocks, which preserves the
        collective structure the compiler sees."""
        if devices is None:
            devices = jax.devices()
        n = len(devices)
        if n % num_slices:
            raise ValueError(
                f"{n} devices cannot split into {num_slices} slices")
        sizes = self.resolve(n)
        dcn_axis = "data" if sizes["data"] % num_slices == 0 \
            else "pipe"
        if sizes[dcn_axis] % num_slices:
            raise ValueError(
                f"neither data={sizes['data']} nor pipe={sizes['pipe']} "
                f"divides into {num_slices} slices (the DCN axis must)")
        ici_sizes = dict(sizes)
        ici_sizes[dcn_axis] //= num_slices
        ici_shape = tuple(ici_sizes[a] for a in AXIS_ORDER)
        dcn_shape = tuple(num_slices if a == dcn_axis else 1
                          for a in AXIS_ORDER)
        try:
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=np.asarray(devices))
        except (ValueError, AssertionError, KeyError, AttributeError):
            # No slice metadata (CPU sim / single-slice): contiguous
            # blocks of n/num_slices devices play the slices, stacked
            # along the DCN axis.
            per = n // num_slices
            blocks = [
                np.asarray(devices[i * per:(i + 1) * per]).reshape(
                    ici_shape)
                for i in range(num_slices)
            ]
            axis = AXIS_ORDER.index(dcn_axis)
            dev_array = np.concatenate(blocks, axis=axis)
        return Mesh(dev_array, AXIS_ORDER)


def single_device_mesh() -> Mesh:
    """A 1-device mesh so the same pjit code paths run everywhere."""
    return MeshSpec(data=1).build(jax.devices()[:1])


def dp_mesh(n: int | None = None) -> Mesh:
    devs = jax.devices() if n is None else jax.devices()[:n]
    return MeshSpec(data=-1).build(devs)


# Mesh axes that shard the batch dimension: anything data-like.
BATCH_AXES = ("data", "fsdp")
