"""Pipeline parallelism: GPipe-style microbatching over the ``pipe`` axis.

Absent from the reference (SURVEY.md §2.4 — no pipeline parallelism
anywhere); TPU-native version expresses stages as a sharded leading
dimension and moves activations between neighboring mesh positions with
`jax.lax.ppermute`, so the schedule compiles to ICI neighbor transfers that
overlap with stage compute.

Schedule: M microbatches through S stages takes M + S - 1 ticks; every
device runs the stage function every tick (bubbles compute on garbage and
are masked out), which keeps the program SPMD — the XLA-friendly tradeoff.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel.sharding import shard_map


def stack_stage_params(per_stage_params: list):
    """Stack a list of per-stage pytrees into one pytree with a leading
    stage axis (shard it over ``pipe``)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def _pipe_loop(stacked_params, x_micro, stage_fn, axis_name):
    """Inside shard_map. stacked_params leaves: [1, ...] (this stage's
    slice); x_micro: [M, mb, ...] microbatches (replicated)."""
    params = jax.tree.map(lambda p: p[0], stacked_params)
    s_count = lax.psum(1, axis_name)
    s = lax.axis_index(axis_name)
    m = x_micro.shape[0]
    perm = [(i, i + 1) for i in range(s_count - 1)]  # forward, no wrap

    out_buf = jnp.zeros(
        (m,) + jax.eval_shape(stage_fn, params, x_micro[0]).shape,
        x_micro.dtype)
    act0 = jnp.zeros_like(x_micro[0])

    def tick(carry, t):
        act_in, out_buf = carry
        mb_idx = jnp.clip(t, 0, m - 1)
        inp = jnp.where(s == 0, x_micro[mb_idx], act_in)
        act_out = stage_fn(params, inp)
        out_idx = jnp.clip(t - (s_count - 1), 0, m - 1)
        is_out = jnp.logical_and(t >= s_count - 1, s == s_count - 1)
        out_buf = jnp.where(
            is_out, out_buf.at[out_idx].set(act_out), out_buf)
        act_next = lax.ppermute(act_out, axis_name, perm)
        return (act_next, out_buf), None

    (_, out_buf), _ = lax.scan(tick, (act0, out_buf),
                               jnp.arange(m + s_count - 1))
    # Only the last stage holds real outputs; psum broadcasts them (other
    # stages contribute zeros).
    return lax.psum(out_buf, axis_name)


def pipeline_apply(stage_fn, per_stage_params: list, x, *,
                   mesh: Mesh, num_microbatches: int,
                   axis_name: str = "pipe",
                   batch_spec: P | None = None):
    """Run `x` through S pipeline stages of `stage_fn`.

    stage_fn(params, microbatch) -> microbatch-shaped output; every stage
    must be shape-preserving in v1 (transformer blocks are).

    `batch_spec` partitions the microbatched input/output
    [M, mb, ...] across OTHER mesh axes (e.g. P(None, "data") combines
    pipeline with data parallelism: each pipe rank streams its data
    shard); default fully replicated.
    """
    s_count = mesh.shape.get(axis_name, 1)
    if len(per_stage_params) != max(s_count, 1):
        raise ValueError(
            f"{len(per_stage_params)} stages vs mesh {axis_name}="
            f"{s_count}")
    if x.shape[0] % num_microbatches:
        raise ValueError("batch not divisible by num_microbatches")
    if s_count == 1:
        out = x
        for p in per_stage_params:
            out = stage_fn(p, out)
        return out

    stacked = stack_stage_params(per_stage_params)
    x_micro = x.reshape((num_microbatches, x.shape[0] // num_microbatches)
                        + x.shape[1:])

    io_spec = P() if batch_spec is None else batch_spec
    param_specs = jax.tree.map(lambda _: P(axis_name), stacked)
    fn = shard_map(
        functools.partial(_pipe_loop, stage_fn=stage_fn,
                          axis_name=axis_name),
        mesh=mesh,
        in_specs=(param_specs, io_spec),
        out_specs=io_spec,
        check_vma=False)
    out_micro = fn(stacked, x_micro)
    return out_micro.reshape(x.shape[:1] + out_micro.shape[2:])
