"""Ring attention: sequence/context parallelism over an ICI ring.

Net-new capability relative to the reference, which has no sequence
parallelism at all (SURVEY.md §5.7) — it scales sequence *count*, not
length. Here long sequences shard over the mesh's ``seq`` axis; K/V blocks
rotate around the ring via `jax.lax.ppermute` while each device accumulates
flash-attention-style running softmax statistics, so peak memory per device
is O(T/n) and communication overlaps compute on ICI.

Algorithm (Liu et al., Ring Attention; blockwise softmax from
Rabe & Staats / FlashAttention):

    for step in 0..n-1:
        score  = q_local @ k_ring.T          # [B,H,Tq,Tk] on MXU
        m_new  = max(m, rowmax(score))
        o      = o * exp(m - m_new) + exp(score - m_new) @ v_ring
        l      = l * exp(m - m_new) + rowsum(exp(score - m_new))
        (k_ring, v_ring) <- ppermute(+1 on the ring)

Causal masking uses global positions reconstructed from the ring step, so
the result is exactly equal to full attention on the gathered sequence.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.sharding import shard_map

NEG_INF = -1e30


def _block_attn(q, k, v, m, l, o, q_off, k_off, causal):
    """One blockwise-softmax accumulation step. q:[B,Tq,H,D] k/v:[B,Tk,H,D]
    m,l:[B,H,Tq] o:[B,Tq,H,D]; offsets are global token positions."""
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    scale = qf.shape[-1] ** -0.5
    # [B,H,Tq,Tk]
    score = jnp.einsum("bqhd,bkhd->bhqk", qf * scale, kf)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        qpos = q_off + jnp.arange(tq)[:, None]        # [Tq,1]
        kpos = k_off + jnp.arange(tk)[None, :]        # [1,Tk]
        score = jnp.where(qpos >= kpos, score, NEG_INF)
    m_new = jnp.maximum(m, score.max(axis=-1))        # [B,H,Tq]
    # exp moves: correction for previous accumulator, probs for this block
    corr = jnp.exp(m - m_new)                         # [B,H,Tq]
    p = jnp.exp(score - m_new[..., None])             # [B,H,Tq,Tk]
    l_new = l * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _ring_attn_sharded(q, k, v, axis_name: str, causal: bool):
    """Runs inside shard_map: q,k,v are the local sequence shards
    [B, T_local, H, D]."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    m0 = jnp.full((b, h, t_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_local), jnp.float32)
    o0 = jnp.zeros((b, t_local, h, d), jnp.float32)
    q_off = idx * t_local
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, s):
        k_blk, v_blk, m, l, o = carry
        # K/V block currently held came from rank (idx - s) mod n.
        src = (idx - s) % n
        k_off = src * t_local
        m, l, o = _block_attn(q, k_blk, v_blk, m, l, o, q_off, k_off,
                              causal)
        # Rotate AFTER compute; XLA overlaps the ppermute with the next
        # iteration's einsum when possible.
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m, l, o), None

    (k_fin, v_fin, m, l, o), _ = jax.lax.scan(
        step, (k, v, m0, l0, o0), jnp.arange(n))
    del k_fin, v_fin
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, causal: bool = False,
                   axis_name: str = "seq"):
    """Sequence-parallel attention over `axis_name` of `mesh`.

    Args are global arrays [B, T, H, D] (sharded or not — shard_map
    partitions by the specs). Returns [B, T, H, D] sharded the same way.
    """
    if mesh.shape.get(axis_name, 1) == 1:
        # No ring: plain (still blockwise-stable) attention.
        m0 = jnp.full(
            (q.shape[0], q.shape[2], q.shape[1]), NEG_INF, jnp.float32)
        l0 = jnp.zeros_like(m0)
        o0 = jnp.zeros(q.shape, jnp.float32)
        m, l, o = _block_attn(q, k, v, m0, l0, o0, 0, 0, causal)
        return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)

    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(_ring_attn_sharded, axis_name=axis_name,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def reference_attention(q, k, v, *, causal: bool = False):
    """O(T^2)-memory reference for tests."""
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    scale = qf.shape[-1] ** -0.5
    score = jnp.einsum("bqhd,bkhd->bhqk", qf * scale, kf)
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        score = jnp.where(mask, score, NEG_INF)
    p = jax.nn.softmax(score, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vf)
    return out.astype(q.dtype)
