"""Logical-axis sharding rules: PartitionSpecs from readable names.

Parameters and activations are annotated with *logical* axis names
("embed", "mlp", "heads", "batch", "length"); a rule table maps logical
axes to mesh axes. This is the t5x/flax-partitioning idiom, exposed here as
the framework's single sharding vocabulary — the TPU-native replacement for
everything the reference delegates to DDP/FSDP wrappers
(`train/torch/train_loop_utils.py:75-101`).
"""

from __future__ import annotations

from typing import Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Default rule table for transformer-family models. Each logical axis maps
# to a mesh axis (or None = replicated). Tuples shard one logical axis over
# several mesh axes.
DEFAULT_RULES: dict[str, object] = {
    "batch": ("data", "fsdp"),   # batch sharded over all data-like axes
    "length": "seq",             # sequence/context parallelism
    "embed": "fsdp",             # ZeRO-3-style parameter sharding
    "mlp": "tensor",             # megatron column/row parallel
    "heads": "tensor",
    "kv": None,
    "vocab": "tensor",
    "expert": "expert",
    "stage": "pipe",
}


def shard_map(f, *, mesh: Mesh, in_specs, out_specs, check_vma=True):
    """shard_map across jax versions: newer releases expose
    ``jax.shard_map(..., check_vma=)``; 0.4.x has
    ``jax.experimental.shard_map.shard_map(..., check_rep=)`` (the same
    knob under its old name). Every shard_map in the tree goes through
    here so the version probe lives in one place."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def _mesh_axes(mesh: Mesh) -> set:
    return set(mesh.axis_names)


def logical_to_spec(logical_axes: Sequence[str | None],
                    rules: dict | None = None,
                    mesh: Mesh | None = None) -> PartitionSpec:
    """Map logical axis names to a PartitionSpec via the rule table.

    Mesh axes that don't exist on `mesh` (or have size 1) still produce valid
    specs — XLA treats sharding over a size-1 axis as replication, which is
    what makes one model definition portable from 1 chip to a pod.
    """
    rules = DEFAULT_RULES if rules is None else rules
    present = _mesh_axes(mesh) if mesh is not None else None
    used = set()
    out = []
    for ax in logical_axes:
        if ax is None:
            out.append(None)
            continue
        target = rules.get(ax)
        if target is None:
            out.append(None)
            continue
        axes = (target,) if isinstance(target, str) else tuple(target)
        axes = tuple(a for a in axes
                     if (present is None or a in present) and a not in used)
        used.update(axes)
        if not axes:
            out.append(None)
        elif len(axes) == 1:
            out.append(axes[0])
        else:
            out.append(axes)
    return PartitionSpec(*out)


def named_sharding(mesh: Mesh, *logical_axes, rules: dict | None = None
                   ) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(logical_axes, rules, mesh))


def tree_shardings(mesh: Mesh, logical_tree, rules: dict | None = None):
    """Map a pytree of logical-axis tuples to NamedShardings."""
    return jax.tree.map(
        lambda axes: NamedSharding(
            mesh, logical_to_spec(axes, rules, mesh)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) or x is None)


def constrain(x, mesh: Mesh, *logical_axes, rules: dict | None = None):
    """In-jit sharding constraint by logical names (replaces the reference's
    nothing — XLA propagates the rest)."""
    return jax.lax.with_sharding_constraint(
        x, named_sharding(mesh, *logical_axes, rules=rules))


def shard_batch(batch, mesh: Mesh):
    """Host->device: place a host batch sharded over the data-like axes."""
    spec = logical_to_spec(("batch",), mesh=mesh)

    def place(arr):
        ndim_spec = PartitionSpec(*(list(spec) + [None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(mesh, ndim_spec))
    return jax.tree.map(place, batch)


def fused_xent_specs(mesh: Mesh, rules: dict | None = None
                     ) -> tuple[PartitionSpec, PartitionSpec,
                                PartitionSpec]:
    """(x, embed, targets) PartitionSpecs for ops.fused_xent's
    vocab-parallel shard_map.

    Activations and targets follow the batch/length rules; the embedding
    keeps its vocab sharding but replicates d_model (each shard reduces
    its local vocab rows to a partial log-sum-exp and partial target
    logit, then one psum over the vocab mesh axis combines them — the
    only cross-shard traffic the fused loss needs is two [B, T] f32
    arrays, vs. the dense path's [B, T, V] logits collective)."""
    x_spec = logical_to_spec(("batch", "length", None), rules, mesh)
    t_spec = logical_to_spec(("batch", "length"), rules, mesh)
    e_spec = logical_to_spec(("vocab", None), rules, mesh)
    return x_spec, e_spec, t_spec


def kv_cache_specs(mesh: Mesh, rules: dict | None = None):
    """PartitionSpec pytree for a decode KV cache {"k", "v"} of
    [L, slots, max_len, H, D]: slots ride the data axes (each data shard
    serves its own sequences), heads ride the tensor axis (matching the
    wq/wk/wv column split, so the cache rows a tensor shard writes are
    the rows it attends over — no cross-shard traffic in decode). Layer
    stack, cache length and head_dim stay replicated."""
    from ray_tpu.models.gpt import kv_cache_logical_axes
    return {name: logical_to_spec(axes, rules, mesh)
            for name, axes in kv_cache_logical_axes().items()}


def kv_cache_shardings(mesh: Mesh, rules: dict | None = None
                       ) -> dict[str, NamedSharding]:
    """NamedShardings for `kv_cache_specs` — what
    `models.gpt.init_kv_cache(mesh=...)` places the cache with."""
    return {name: NamedSharding(mesh, spec)
            for name, spec in kv_cache_specs(mesh, rules).items()}


def kv_pool_specs(mesh: Mesh, rules: dict | None = None, *,
                  quantized: bool = False):
    """PartitionSpec pytree for a paged KV block pool {"k", "v"} of
    [L, n_blocks, block_size, H, D]: heads ride the tensor axis (same
    wq/wk/wv column-split alignment as the unpaged cache — the blocks a
    tensor shard writes hold the heads it attends over). The block axis
    is replicated: the allocator hands any physical block to any
    sequence, so blocks cannot be pinned to data shards the way whole
    slot rows were. With ``quantized`` (an int8 pool) the pytree grows
    {"k_scale", "v_scale"} of [L, n_blocks, block_size, H]: the head
    axis shards with its payload rows — each tensor shard dequantizes
    from scales it already owns — and blocks stay replicated."""
    from ray_tpu.models.gpt import kv_pool_logical_axes
    return {name: logical_to_spec(axes, rules, mesh)
            for name, axes in kv_pool_logical_axes(quantized).items()}


def kv_pool_shardings(mesh: Mesh, rules: dict | None = None, *,
                      quantized: bool = False
                      ) -> dict[str, NamedSharding]:
    """NamedShardings for `kv_pool_specs` — what
    `models.gpt.init_kv_pool(mesh=...)` places the pool with."""
    return {name: NamedSharding(mesh, spec)
            for name, spec in kv_pool_specs(
                mesh, rules, quantized=quantized).items()}


def replicated(mesh: Mesh):
    return NamedSharding(mesh, PartitionSpec())


def engine_io_shardings(mesh: Mesh) -> dict[str, NamedSharding]:
    """Shardings for the inference engine's per-step host inputs
    (current tokens, speculation windows, positions, block tables,
    temperatures). All replicated: they are tiny int32/f32 vectors the
    scheduler rebuilds every tick, and every shard of the paged pool
    needs the full batch's tables — but routing them through explicit
    device_put keeps each step's transfer off XLA's implicit-transfer
    path and makes the engine's placement auditable."""
    rep = NamedSharding(mesh, PartitionSpec())
    return {name: rep
            for name in ("tokens", "window", "pos", "tables", "temps")}


# -- PartitionSpec (de)serialization for checkpoint manifests ---------------
#
# Mesh axis NAMES are stable across scale changes (MeshSpec keeps size-1
# axes for exactly this reason), so a spec recorded at save time can be
# re-applied to a mesh with a different device count at restore time —
# the elastic-resume path in train/ft.py. Sizes are not recorded: only
# names travel, and `valid_spec_for` re-validates them against the mesh
# that exists at restore.

def spec_to_json(spec) -> list:
    """PartitionSpec -> JSON-serializable list (None | str | [str, ...]
    per dim)."""
    out = []
    for entry in tuple(spec):
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:
            out.append(list(entry))
    return out


def spec_from_json(entries) -> PartitionSpec:
    """Inverse of `spec_to_json`."""
    out = []
    for entry in entries:
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:
            out.append(tuple(entry))
    return PartitionSpec(*out)


def valid_spec_for(mesh: Mesh, spec, shape) -> PartitionSpec:
    """Re-validate a recorded PartitionSpec against a (possibly different)
    mesh: axes that don't exist on `mesh`, are already used by an earlier
    dim, or don't divide the dim evenly are dropped (replicated) — the
    same degrade-to-replication contract as `logical_to_spec`, applied at
    restore time."""
    present = _mesh_axes(mesh)
    used: set = set()
    out = []
    entries = list(tuple(spec))[:len(shape)]
    for dim, entry in zip(shape, entries):
        if entry is None:
            out.append(None)
            continue
        axes = (entry,) if isinstance(entry, str) else tuple(entry)
        axes = tuple(a for a in axes if a in present and a not in used)
        total = 1
        for a in axes:
            total *= mesh.shape[a]
        if not axes or (total and dim % total):
            out.append(None)
            continue
        used.update(axes)
        out.append(axes[0] if len(axes) == 1 else axes)
    return PartitionSpec(*out)


def global_from_local(mesh: Mesh, local_batch, rules: dict | None = None):
    """Build a global batch-sharded array from each process's local shard —
    the multi-host ingest path (each host feeds its own data; the global
    array spans all processes). Works single-process too, so train loops
    don't branch on world size."""
    spec = logical_to_spec(("batch",), rules, mesh)

    def place(arr):
        full_spec = PartitionSpec(*(list(spec) + [None] * (arr.ndim - 1)))
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, full_spec), arr)
    return jax.tree.map(place, local_batch)


def replicate_tree(mesh: Mesh, tree):
    """Replicate host values onto every device of a (possibly multi-host)
    mesh."""
    import numpy as np

    def place(arr):
        return jax.make_array_from_process_local_data(
            replicated(mesh), np.asarray(arr))
    return jax.tree.map(place, tree)
