"""TPU-native parallelism layer: meshes, sharding rules, SPMD collectives.

This is where the framework *exceeds* the reference (SURVEY.md §2.4): DP,
FSDP, TP, SP (ring attention), EP and PP are all PartitionSpecs over one
`jax.sharding.Mesh` instead of N separate wrapper integrations.
"""

from ray_tpu.parallel.mesh import (
    AXIS_ORDER,
    BATCH_AXES,
    MeshSpec,
    dp_mesh,
    single_device_mesh,
)
from ray_tpu.parallel.sharding import (
    DEFAULT_RULES,
    constrain,
    global_from_local,
    kv_cache_shardings,
    kv_cache_specs,
    logical_to_spec,
    named_sharding,
    replicate_tree,
    replicated,
    shard_batch,
    tree_shardings,
)
from ray_tpu.parallel.ring_attention import reference_attention, ring_attention
from ray_tpu.parallel.pipeline import pipeline_apply, stack_stage_params

__all__ = [
    "AXIS_ORDER", "BATCH_AXES", "MeshSpec", "dp_mesh", "single_device_mesh",
    "DEFAULT_RULES", "constrain", "global_from_local",
    "kv_cache_shardings", "kv_cache_specs", "logical_to_spec",
    "named_sharding", "replicate_tree", "replicated", "shard_batch",
    "tree_shardings",
    "reference_attention", "ring_attention",
    "pipeline_apply", "stack_stage_params",
]
