"""Dataset: lazy distributed data API.

Counterpart of the reference's `data/dataset.py:170` (`map_batches` :379,
`repartition` :909, `random_shuffle` :960, `split` :1170, `groupby` :1703,
`sort` :2017, `iter_batches` :3031) over the ray_tpu core. Execution is
lazy: transforms append logical ops; iteration/materialization drives the
streaming executor (`_internal/execution.py`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

import numpy as np

import ray_tpu
from ray_tpu.data._internal import plan as plan_mod
from ray_tpu.data.block import BlockAccessor, concat_blocks


@dataclass
class TaskPoolStrategy:
    """Stateless tasks (default compute; reference `compute.py:58`)."""
    size: int | None = None


@dataclass
class ActorPoolStrategy:
    """Autoscaling-pool-of-actors compute for stateful UDFs — the TPU batch
    inference path (reference `compute.py:180`, `actor_pool_map_operator`).
    """
    size: int | None = None
    min_size: int | None = None
    max_size: int | None = None
    max_tasks_in_flight_per_actor: int = 2


class Dataset:
    def __init__(self, plan: plan_mod.ExecutionPlan):
        self._plan = plan

    # ------------------------------------------------------------------
    # transforms (lazy)
    # ------------------------------------------------------------------

    def _append(self, op) -> "Dataset":
        return Dataset(self._plan.with_op(op))

    def map_batches(self, fn, *, batch_size: int | None = 1024,
                    batch_format: str | None = "numpy",
                    compute=None, fn_args=(), fn_kwargs=None,
                    fn_constructor_args=(), num_cpus=None, num_tpus=None,
                    zero_copy_batch=False, **_ignored) -> "Dataset":
        is_cls = isinstance(fn, type)
        if is_cls and compute is None:
            compute = ActorPoolStrategy(size=2)
        return self._append(plan_mod.MapOp(
            "map_batches", fn, tuple(fn_constructor_args), tuple(fn_args),
            dict(fn_kwargs or {}), batch_size, batch_format,
            zero_copy_batch, compute, num_cpus, num_tpus, is_cls))

    def map(self, fn, *, compute=None, num_cpus=None, **_ignored):
        return self._append(plan_mod.MapOp(
            "map", fn, (), (), {}, None, None, False, compute, num_cpus,
            None, isinstance(fn, type)))

    def filter(self, fn, **_ignored):
        return self._append(plan_mod.MapOp(
            "filter", fn, (), (), {}, None, None, False, None, None, None,
            isinstance(fn, type)))

    def flat_map(self, fn, **_ignored):
        return self._append(plan_mod.MapOp(
            "flat_map", fn, (), (), {}, None, None, False, None, None,
            None, isinstance(fn, type)))

    def add_column(self, col: str, fn) -> "Dataset":
        def add(batch):
            batch = dict(batch)
            batch[col] = np.asarray(fn(batch))
            return batch
        return self.map_batches(add, batch_size=None)

    def drop_columns(self, cols: list) -> "Dataset":
        drop = set(cols)
        return self.map_batches(
            lambda b: {k: v for k, v in b.items() if k not in drop},
            batch_size=None)

    def select_columns(self, cols: list) -> "Dataset":
        keep = list(cols)
        return self.map_batches(
            lambda b: {k: b[k] for k in keep}, batch_size=None)

    def rename_columns(self, mapping: dict) -> "Dataset":
        return self.map_batches(
            lambda b: {mapping.get(k, k): v for k, v in b.items()},
            batch_size=None)

    def random_sample(self, fraction: float, *, seed=None) -> "Dataset":
        def sample(batch, _ctr=[0]):
            n = len(next(iter(batch.values()))) if batch else 0
            # Per-batch sub-seed: a fixed seed must not reuse the identical
            # mask on every block (perfectly correlated "sample").
            _ctr[0] += 1
            rng = (np.random.default_rng() if seed is None else
                   np.random.default_rng(
                       np.random.SeedSequence([seed, _ctr[0]])))
            keep = rng.random(n) < fraction
            return {k: v[keep] for k, v in batch.items()}
        return self.map_batches(sample, batch_size=None)

    # -- all-to-all -----------------------------------------------------

    def repartition(self, num_blocks: int, **_ignored) -> "Dataset":
        return self._append(plan_mod.AllToAll(
            "repartition", {"num_blocks": num_blocks}))

    def random_shuffle(self, *, seed=None, num_blocks=None,
                       **_ignored) -> "Dataset":
        return self._append(plan_mod.AllToAll(
            "random_shuffle", {"seed": seed, "num_blocks": num_blocks}))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._append(plan_mod.AllToAll(
            "sort", {"key": key, "descending": descending}))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def limit(self, n: int) -> "Dataset":
        return self._append(plan_mod.Limit(n))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._append(plan_mod.Union(
            [o._plan.copy() for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._append(plan_mod.Zip(other._plan.copy()))

    # ------------------------------------------------------------------
    # consumption
    # ------------------------------------------------------------------

    def materialize(self) -> "Dataset":
        self._plan.execute()
        return self

    def _blocks(self):
        return self._plan.execute()

    def num_blocks(self) -> int:
        return len(self._blocks())

    def count(self) -> int:
        return sum(m.num_rows for _, m in self._blocks())

    def size_bytes(self) -> int:
        return sum(m.size_bytes for _, m in self._blocks())

    def schema(self):
        for ref, meta in self._plan.stream():
            if meta.num_rows > 0:
                return meta.schema
        return None

    def columns(self) -> list | None:
        for ref, _ in self._plan.stream():
            block = ray_tpu.get(ref)
            return BlockAccessor.for_block(block).column_names()
        return None

    def input_files(self) -> list:
        out = []
        for _, meta in self._blocks():
            out.extend(meta.input_files or [])
        return out

    def take(self, n: int = 20) -> list:
        rows = []
        for ref, _meta in self._plan.stream():
            block = ray_tpu.get(ref)
            for row in BlockAccessor.for_block(block).iter_rows():
                rows.append(row)
                if len(rows) >= n:
                    return rows
        return rows

    def take_all(self) -> list:
        return self.take(int(1e18))

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    def iter_rows(self) -> Iterator[dict]:
        for ref, _meta in self._plan.stream():
            block = ray_tpu.get(ref)
            yield from BlockAccessor.for_block(block).iter_rows()

    def iter_batches(self, *, batch_size: int | None = 256,
                     batch_format: str | None = "numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: int | None = None,
                     local_shuffle_seed: int | None = None,
                     prefetch_batches: int = 1) -> Iterator:
        it = DataIterator(self)
        return it.iter_batches(
            batch_size=batch_size, batch_format=batch_format,
            drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed,
            prefetch_batches=prefetch_batches)

    def iter_device_batches(self, *, mesh, batch_size: int | None = 256,
                            prefetch: int = 2, group: int = 1,
                            rules: dict | None = None,
                            drop_last: bool = True, **kw) -> Iterator:
        """`iter_batches` → train-loop bridge: numpy batches placed on
        the mesh sharded over its data-like axes, `prefetch` transfers
        ahead of the consumer (see ray_tpu/train/loop.py). group=u
        stacks u batches per yield — the input of a fused multi-step
        dispatch (`TrainLoop(unroll=u)`)."""
        return DataIterator(self).iter_device_batches(
            mesh=mesh, batch_size=batch_size, prefetch=prefetch,
            group=group, rules=rules, drop_last=drop_last, **kw)

    def iterator(self) -> "DataIterator":
        return DataIterator(self)

    # -- conversions ----------------------------------------------------

    def to_pandas(self):
        blocks = [ray_tpu.get(r) for r, _ in self._blocks()]
        out = concat_blocks(blocks)
        return BlockAccessor.for_block(out).to_pandas()

    def to_numpy(self) -> dict:
        blocks = [ray_tpu.get(r) for r, _ in self._blocks()]
        return BlockAccessor.for_block(concat_blocks(blocks)).to_numpy()

    def to_arrow_refs(self) -> list:
        return [r for r, _ in self._blocks()]

    # -- splits ---------------------------------------------------------

    def split(self, n: int, *, equal: bool = False) -> list["Dataset"]:
        blocks = self._blocks()
        if equal:
            total = sum(m.num_rows for _, m in blocks)
            per = total // n
            return [
                self._slice_rows(i * per, (i + 1) * per) for i in range(n)
            ]
        shards: list[list] = [[] for _ in range(n)]
        for i, bm in enumerate(blocks):
            shards[i % n].append(bm)
        return [Dataset(plan_mod.ExecutionPlan(
            [plan_mod.InputData(blocks=s)])) for s in shards]

    def _slice_rows(self, start: int, end: int) -> "Dataset":
        out = []
        off = 0
        for ref, meta in self._blocks():
            lo, hi = max(start - off, 0), min(end - off, meta.num_rows)
            if lo < hi:
                block = ray_tpu.get(ref)
                cut = BlockAccessor.for_block(block).slice(lo, hi)
                m = BlockAccessor.for_block(cut).metadata()
                out.append((ray_tpu.put(cut), m))
            off += meta.num_rows
        return Dataset(plan_mod.ExecutionPlan(
            [plan_mod.InputData(blocks=out)]))

    def streaming_split(self, n: int, *, equal: bool = True,
                        locality_hints=None) -> list["DataIterator"]:
        return [DataIterator(ds) for ds in self.split(n, equal=equal)]

    def streaming_split_shard(self, rank: int, world: int) -> "Dataset":
        """Per-worker shard hook used by JaxTrainer._make_shards."""
        return self.split(world, equal=True)[rank]

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed=None) -> tuple["Dataset", "Dataset"]:
        ds = self.random_shuffle(seed=seed) if shuffle else self
        total = ds.count()
        n_test = int(total * test_size) if isinstance(test_size, float) \
            else int(test_size)
        return (ds._slice_rows(0, total - n_test),
                ds._slice_rows(total - n_test, total))

    # -- writes ---------------------------------------------------------

    def _write(self, writer, path: str, **kwargs):
        refs = []
        write = ray_tpu.remote(_write_task)
        for i, (ref, _meta) in enumerate(self._plan.stream()):
            refs.append(write.remote(ref, writer, path, i, kwargs))
        ray_tpu.get(refs, timeout=600)

    def write_parquet(self, path: str, **kwargs):
        from ray_tpu.data.datasource import write_parquet_block
        self._write(write_parquet_block, path, **kwargs)

    def write_csv(self, path: str, **kwargs):
        from ray_tpu.data.datasource import write_csv_block
        self._write(write_csv_block, path, **kwargs)

    def write_json(self, path: str, **kwargs):
        from ray_tpu.data.datasource import write_json_block
        self._write(write_json_block, path, **kwargs)

    def write_tfrecords(self, path: str, **kwargs):
        from ray_tpu.data.datasource import write_tfrecords_block
        self._write(write_tfrecords_block, path, **kwargs)

    def write_numpy(self, path: str, *, column: str = "data", **kwargs):
        from ray_tpu.data.datasource import write_numpy_block
        self._write(write_numpy_block, path, column=column, **kwargs)

    # -- misc -----------------------------------------------------------

    def stats(self) -> str:
        """Per-operator execution report of the latest run (reference:
        `_internal/stats.py` DatasetStats summary); falls back to the
        logical plan when the dataset hasn't executed yet."""
        if getattr(self._plan, "last_stats", None) is not None:
            return (self._plan.describe() + "\n"
                    + self._plan.last_stats.summary())
        return self._plan.describe()

    def __repr__(self):
        return f"Dataset(plan={self._plan.describe()})"


def _write_task(block, writer, path, idx, kwargs):
    writer(block, path, idx, **kwargs)
    return True


class GroupedData:
    """Counterpart of reference `data/grouped_data.py`."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, aggs: list) -> Dataset:
        return self._ds._append(plan_mod.AllToAll(
            "groupby_agg", {"key": self._key, "aggs": aggs}))

    def count(self) -> Dataset:
        return self._agg([(None, "count", "count()")])

    def sum(self, col: str) -> Dataset:
        return self._agg([(col, "sum", f"sum({col})")])

    def mean(self, col: str) -> Dataset:
        return self._agg([(col, "mean", f"mean({col})")])

    def min(self, col: str) -> Dataset:
        return self._agg([(col, "min", f"min({col})")])

    def max(self, col: str) -> Dataset:
        return self._agg([(col, "max", f"max({col})")])

    def std(self, col: str) -> Dataset:
        return self._agg([(col, "std", f"std({col})")])

    def aggregate(self, *aggs) -> Dataset:
        """aggs: tuples (col, how) or (col, how, out_name)."""
        norm = []
        for a in aggs:
            col, how = a[0], a[1]
            out = a[2] if len(a) > 2 else f"{how}({col})"
            norm.append((col, how, out))
        return self._agg(norm)


class DataIterator:
    """Counterpart of reference `data/iterator.py` + block_batching:
    pull blocks as the executor produces them, re-batch, format, prefetch.
    """

    def __init__(self, ds: Dataset):
        self._ds = ds

    def iter_batches(self, *, batch_size: int | None = 256,
                     batch_format: str | None = "numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: int | None = None,
                     local_shuffle_seed: int | None = None,
                     prefetch_batches: int = 1) -> Iterator:
        def block_iter():
            for ref, _meta in self._ds._plan.stream():
                yield ray_tpu.get(ref)

        blocks = block_iter()
        if prefetch_batches and prefetch_batches > 0:
            blocks = _prefetched(blocks, prefetch_batches)
        if local_shuffle_buffer_size:
            blocks = _shuffled_blocks(
                blocks, local_shuffle_buffer_size, local_shuffle_seed)
        yield from _rebatch(blocks, batch_size, batch_format, drop_last)

    def iter_rows(self):
        return self._ds.iter_rows()

    def iter_device_batches(self, *, mesh, batch_size: int | None = 256,
                            prefetch: int = 2, group: int = 1,
                            rules: dict | None = None,
                            drop_last: bool = True, **kw) -> Iterator:
        """Stream batches onto the mesh with host→device prefetch: each
        numpy batch from `iter_batches` is `device_put` sharded
        (batch→data-like axes) up to `prefetch` batches ahead, so
        transfer overlaps the consumer's compute. drop_last defaults to
        True — device batches must be shape-stable or every ragged tail
        recompiles the step."""
        from ray_tpu.train import loop as train_loop

        host = self.iter_batches(batch_size=batch_size,
                                 batch_format="numpy",
                                 drop_last=drop_last, **kw)
        place = train_loop.make_placer(mesh, rules=rules,
                                       stacked=group > 1)
        return train_loop.DevicePrefetcher(host, place, depth=prefetch,
                                           group=group)

    def materialize(self):
        return self._ds.materialize()

    # Train integration: JaxTrainer dataset shards arrive as DataIterator
    # or Dataset; both expose iter_batches.
    def streaming_split_shard(self, rank, world):
        return self._ds.streaming_split_shard(rank, world)


def _prefetched(it, depth: int):
    """Pull ahead on a daemon thread so block fetch/format overlaps the
    consumer's compute (reference: `block_batching` prefetcher)."""
    import queue
    import threading
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    DONE, ERR = object(), object()

    def fill():
        try:
            for x in it:
                q.put(x)
            q.put(DONE)
        except BaseException as e:   # surface in consumer
            q.put((ERR, e))

    threading.Thread(target=fill, daemon=True,
                     name="data-prefetch").start()
    while True:
        x = q.get()
        if x is DONE:
            return
        if isinstance(x, tuple) and len(x) == 2 and x[0] is ERR:
            raise x[1]
        yield x


def _shuffled_blocks(blocks, buffer_rows: int, seed):
    rng = np.random.default_rng(seed)
    buf: list = []
    rows = 0
    for b in blocks:
        buf.append(b)
        rows += BlockAccessor.for_block(b).num_rows()
        if rows >= buffer_rows:
            merged = concat_blocks(buf)
            acc = BlockAccessor.for_block(merged)
            yield acc.take(rng.permutation(acc.num_rows()))
            buf, rows = [], 0
    if buf:
        merged = concat_blocks(buf)
        acc = BlockAccessor.for_block(merged)
        yield acc.take(rng.permutation(acc.num_rows()))


def _rebatch(blocks, batch_size, batch_format, drop_last):
    """Slice a stream of blocks into fixed-size batches across block
    boundaries (reference: `_internal/block_batching/iter_batches.py`)."""
    if batch_size is None:
        for b in blocks:
            acc = BlockAccessor.for_block(b)
            if acc.num_rows():
                yield acc.to_batch(batch_format)
        return
    pending: list = []
    pending_rows = 0
    for b in blocks:
        pending.append(b)
        pending_rows += BlockAccessor.for_block(b).num_rows()
        while pending_rows >= batch_size:
            merged = concat_blocks(pending)
            acc = BlockAccessor.for_block(merged)
            yield BlockAccessor.for_block(
                acc.slice(0, batch_size)).to_batch(batch_format)
            rest = acc.slice(batch_size, acc.num_rows())
            pending = [rest]
            pending_rows = BlockAccessor.for_block(rest).num_rows()
    if pending_rows and not drop_last:
        merged = concat_blocks(pending)
        acc = BlockAccessor.for_block(merged)
        if acc.num_rows():
            yield acc.to_batch(batch_format)
