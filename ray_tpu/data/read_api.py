"""Read/creation API (reference: `data/read_api.py`: read_parquet :505,
read_csv :898, range :120, from_items :1611, from_pandas :1656,
from_numpy :1705, from_arrow :1724, from_huggingface :1748)."""

from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.data import datasource as dsrc
from ray_tpu.data._internal import plan as plan_mod
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset


_builtin_range = range


def _auto_parallelism(parallelism: int) -> int:
    if parallelism and parallelism > 0:
        return parallelism
    ctx = DataContext.get_current()
    if ctx.read_parallelism and ctx.read_parallelism > 0:
        return ctx.read_parallelism
    try:
        cpus = ray_tpu.cluster_resources().get("CPU", 2)
    except Exception:
        cpus = 2
    return max(2, int(cpus))


def _from_datasource(ds: dsrc.Datasource, parallelism: int) -> Dataset:
    tasks = ds.get_read_tasks(_auto_parallelism(parallelism))
    return Dataset(plan_mod.ExecutionPlan(
        [plan_mod.Read(read_tasks=tasks,
                       input_files=getattr(ds, "_files", None))]))


def read_datasource(ds: dsrc.Datasource, *, parallelism: int = -1,
                    **_ignored) -> Dataset:
    return _from_datasource(ds, parallelism)


def read_parquet(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_datasource(dsrc.ParquetDatasource(paths, **kwargs),
                            parallelism)


def read_csv(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_datasource(dsrc.CSVDatasource(paths, **kwargs),
                            parallelism)


def read_json(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_datasource(dsrc.JSONDatasource(paths, **kwargs),
                            parallelism)


def read_text(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_datasource(dsrc.TextDatasource(paths, **kwargs),
                            parallelism)


def read_numpy(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_datasource(dsrc.NumpyDatasource(paths, **kwargs),
                            parallelism)


def read_binary_files(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_datasource(dsrc.BinaryDatasource(paths, **kwargs),
                            parallelism)


def read_images(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    """Decoded images (columns: image, path); `size=(H, W)` resizes to a
    dense batchable block (reference: read_api.py:612 read_images)."""
    return _from_datasource(dsrc.ImageDatasource(paths, **kwargs),
                            parallelism)


def read_sql(sql: str, connection_factory, *, parallelism: int = -1,
             shard_rows=None, num_shards: int = 1) -> Dataset:
    """DBAPI query -> Dataset (reference: read_sql, data/read_api.py).
    `connection_factory` must be picklable (module-level function or
    functools.partial of one). Sharded reads (`shard_rows`) paginate
    with OFFSET/LIMIT: give the query a deterministic ORDER BY."""
    return read_datasource(
        dsrc.SQLDatasource(sql, connection_factory,
                           shard_rows=shard_rows, num_shards=num_shards),
        parallelism=parallelism)


def read_webdataset(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    """Webdataset tar shards -> one row per sample (reference:
    read_webdataset, data/read_api.py)."""
    return read_datasource(dsrc.WebDatasetDatasource(paths, **kwargs),
                           parallelism=parallelism)


def read_tfrecords(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    """tf.train.Example records as columns (reference: read_tfrecords),
    decoded by the built-in proto codec — no tensorflow needed."""
    return _from_datasource(dsrc.TFRecordDatasource(paths, **kwargs),
                            parallelism)


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    return _from_datasource(dsrc.RangeDatasource(n), parallelism)


def range_tensor(n: int, *, shape=(1,), parallelism: int = -1) -> Dataset:
    return _from_datasource(dsrc.RangeDatasource(n, tensor_shape=shape),
                            parallelism)


def _input_data(blocks) -> Dataset:
    pairs = []
    for b in blocks:
        meta = BlockAccessor.for_block(b).metadata()
        pairs.append((ray_tpu.put(b), meta))
    return Dataset(plan_mod.ExecutionPlan(
        [plan_mod.InputData(blocks=pairs)]))


def from_items(items: list, *, parallelism: int = -1) -> Dataset:
    if items and not isinstance(items[0], dict):
        items = [{"item": x} for x in items]
    p = max(1, min(_auto_parallelism(parallelism), max(len(items), 1)))
    bounds = np.linspace(0, len(items), p + 1).astype(int)
    blocks = []
    from ray_tpu.data.block import _rows_to_block
    for i in _builtin_range(p):
        chunk = items[bounds[i]:bounds[i + 1]]
        if chunk:
            blocks.append(_rows_to_block(chunk))
    return _input_data(blocks or [{}])


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return _input_data(dfs)


def from_numpy(arrs) -> Dataset:
    if not isinstance(arrs, list):
        arrs = [arrs]
    return _input_data([{"data": np.asarray(a)} for a in arrs])


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return _input_data(tables)


def from_huggingface(hf_dataset) -> Dataset:
    """datasets.Dataset -> Dataset via its arrow table."""
    table = hf_dataset.data.table
    return _input_data([table])


def from_torch(torch_dataset) -> Dataset:
    rows = [{"item": torch_dataset[i]}
            for i in _builtin_range(len(torch_dataset))]
    return from_items(rows)
