"""Logical plan: lazy op list + optimizer (fusion).

Counterpart of the reference's `data/_internal/logical/` (operator defs,
`optimizers.py` fusion rules) + `planner/planner.py`. Deliberately compact:
ops are dataclasses, the only optimization that matters for the hot path —
fusing consecutive map-type ops into one task launch — is applied at plan
build time (reference: `logical/rules/operator_fusion.py`).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Callable


@dataclass
class LogicalOp:
    pass


@dataclass
class Read(LogicalOp):
    """Source: a list of ReadTask thunks, each producing one block."""
    read_tasks: list = field(default_factory=list)   # callables -> block
    input_files: list | None = None

    @property
    def name(self):
        return "Read"


@dataclass
class InputData(LogicalOp):
    """Source: already-materialized (block_ref, metadata) pairs."""
    blocks: list = field(default_factory=list)

    @property
    def name(self):
        return "InputData"


@dataclass
class MapOp(LogicalOp):
    """Any per-block transform. kind: map_batches|map|filter|flat_map|
    write. `fn` operates on a batch/row per kind; fusion chains these."""
    kind: str
    fn: Callable
    fn_constructor_args: tuple = ()
    fn_args: tuple = ()
    fn_kwargs: dict = field(default_factory=dict)
    batch_size: int | None = None         # map_batches only
    batch_format: str | None = "numpy"
    zero_copy_batch: bool = False
    compute: Any = None                   # None=tasks, ActorPoolStrategy
    num_cpus: float | None = None
    num_tpus: float | None = None
    is_callable_class: bool = False

    @property
    def name(self):
        return self.kind


@dataclass
class AllToAll(LogicalOp):
    """Barrier op: repartition | random_shuffle | sort | groupby_agg."""
    kind: str
    options: dict = field(default_factory=dict)

    @property
    def name(self):
        return self.kind


@dataclass
class Limit(LogicalOp):
    n: int = 0

    @property
    def name(self):
        return f"limit={self.n}"


@dataclass
class Union(LogicalOp):
    others: list = field(default_factory=list)      # list[ExecutionPlan]

    @property
    def name(self):
        return "Union"


@dataclass
class Zip(LogicalOp):
    other: Any = None                               # ExecutionPlan

    @property
    def name(self):
        return "Zip"


class ExecutionPlan:
    """Immutable chain of logical ops; Datasets share structure on append
    (reference: `_internal/plan.py` ExecutionPlan)."""

    def __init__(self, ops: list[LogicalOp]):
        self.ops = list(ops)
        self._cached_blocks = None   # list[(ref, BlockMetadata)] once run
        self.last_stats = None       # PlanStats of the latest execution

    def with_op(self, op: LogicalOp) -> "ExecutionPlan":
        return ExecutionPlan(self.ops + [op])

    def copy(self) -> "ExecutionPlan":
        p = ExecutionPlan(self.ops)
        p._cached_blocks = self._cached_blocks
        return p

    def describe(self) -> str:
        return " -> ".join(op.name for op in self.ops)

    # -- execution ----------------------------------------------------------

    def execute(self):
        """Materialize fully: list[(block_ref, BlockMetadata)]."""
        if self._cached_blocks is None:
            from ray_tpu.data._internal.execution import execute_plan
            self._cached_blocks = list(execute_plan(self))
        return self._cached_blocks

    def stream(self):
        """Yield (block_ref, BlockMetadata) as they become available."""
        if self._cached_blocks is not None:
            yield from self._cached_blocks
            return
        from ray_tpu.data._internal.execution import execute_plan
        yield from execute_plan(self)
