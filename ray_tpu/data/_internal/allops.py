"""All-to-all (barrier) operators: repartition, shuffle, sort, groupby.

Counterpart of the reference's exchange ops (`_internal/shuffle.py`,
`push_based_shuffle.py`, `sort.py`, `fast_repartition.py`). Two-phase
exchange: map-side partition tasks write shard lists to the object store;
reduce-side tasks fetch their shard index from each list (worker->store->
worker; the driver only moves refs and tiny boundary samples, never data).
"""

from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor, concat_blocks


def _store(block):
    """Put the block from the worker; return (block_ref, meta) — only refs
    and metadata ever reach the driver."""
    meta = BlockAccessor.for_block(block).metadata()
    return ray_tpu.put(block), meta


# -- map side ---------------------------------------------------------------

def _split_task(block, n, assignment_seed):
    """Split one block into n shards. assignment_seed None -> contiguous
    chunks; int -> random destination per row (shuffle)."""
    acc = BlockAccessor.for_block(block)
    rows = acc.num_rows()
    if assignment_seed is None:
        bounds = np.linspace(0, rows, n + 1).astype(int)
        return [acc.slice(int(bounds[i]), int(bounds[i + 1]))
                for i in range(n)]
    rng = np.random.default_rng(assignment_seed)
    dest = rng.integers(0, n, rows)
    return [acc.take(np.nonzero(dest == i)[0]) for i in range(n)]


def _range_split_task(block, bounds):
    """Order-preserving split: bounds is a list of (lo, hi) local row
    ranges, one per output partition (empty ranges allowed)."""
    acc = BlockAccessor.for_block(block)
    return [acc.slice(lo, hi) for lo, hi in bounds]


def _boundary_split_task(block, boundaries, key, descending):
    acc = BlockAccessor.for_block(block)
    col = acc.to_numpy()[key]
    dest = np.searchsorted(np.asarray(boundaries), col, side="right")
    n = len(boundaries) + 1
    if descending:
        dest = (n - 1) - dest
    return [acc.take(np.nonzero(dest == i)[0]) for i in range(n)]


def _hash_split_task(block, n, key):
    acc = BlockAccessor.for_block(block)
    col = acc.to_numpy()[key]
    if col.dtype.kind in "OUS":
        # Deterministic across processes (Python's hash() is per-process
        # randomized for str, which would scatter equal keys).
        import zlib
        dest = np.asarray(
            [zlib.crc32(str(x).encode()) % n for x in col])
    else:
        dest = (col.astype(np.int64, copy=False) % n + n) % n
    return [acc.take(np.nonzero(dest == i)[0]) for i in range(n)]


def _sample_task(block, key, k):
    acc = BlockAccessor.for_block(block)
    col = acc.to_numpy()[key]
    if len(col) == 0:
        return col
    idx = np.linspace(0, len(col) - 1, min(k, len(col))).astype(int)
    return np.sort(col)[idx]


# -- reduce side ------------------------------------------------------------

def _fetch_shards(shard_list_refs, index):
    return [ray_tpu.get(r)[index] for r in shard_list_refs]


def _concat_task(shard_list_refs, index, shuffle_seed=None, sort_key=None,
                 descending=False):
    block = concat_blocks(_fetch_shards(shard_list_refs, index))
    acc = BlockAccessor.for_block(block)
    if shuffle_seed is not None:
        rng = np.random.default_rng(shuffle_seed)
        block = acc.take(rng.permutation(acc.num_rows()))
    if sort_key is not None:
        cols = BlockAccessor.for_block(block).to_numpy()
        order = np.argsort(cols[sort_key], kind="stable")
        if descending:
            order = order[::-1]
        block = BlockAccessor.for_block(block).take(order)
    return _store(block)


def _groupby_task(shard_list_refs, index, key, aggs):
    """Per-partition pandas groupby (equal keys are co-located by the hash
    exchange, so per-partition aggregation is exact)."""
    import pandas as pd
    block = concat_blocks(_fetch_shards(shard_list_refs, index))
    df = BlockAccessor.for_block(block).to_pandas()
    if df.empty:
        return _store({})
    gb = df.groupby(key, sort=True)
    pieces = {}
    for col, how, out_name in aggs:
        if how == "count":
            pieces[out_name] = gb.size()
        else:
            pieces[out_name] = getattr(gb[col], how)()
    out = pd.DataFrame(pieces).reset_index()
    return _store(out)


# -- driver-side assembly ---------------------------------------------------

def _collect(task_refs):
    """Each task returns (block_ref, meta) — tiny driver-side fetch."""
    return [ray_tpu.get(r, timeout=600) for r in task_refs]


def _exchange(blocks, n_out, split_fn, split_args, concat_fn, concat_args):
    """Generic 2-phase exchange skeleton."""
    split = ray_tpu.remote(split_fn)
    # shard-list refs stay refs: reduce tasks fetch them from the store.
    shard_list_refs = [split.remote(ref, *split_args(i))
                       for i, (ref, _) in enumerate(blocks)]
    concat = ray_tpu.remote(concat_fn)
    out = [concat.remote(list(shard_list_refs), i, *concat_args(i))
           for i in range(n_out)]
    return _collect(out)


def run(op, blocks):
    kind = op.kind
    o = op.options
    if kind == "repartition":
        # Order-preserving: output partition p owns global row range
        # [p*total/n, (p+1)*total/n); each input block contributes the
        # intersection with its own global range.
        n = o["num_blocks"]
        total = sum(m.num_rows for _, m in blocks)
        gbounds = np.linspace(0, total, n + 1).astype(int)
        per_block_bounds = []
        off = 0
        for _, m in blocks:
            local = []
            for p in range(n):
                lo = min(max(int(gbounds[p]) - off, 0), m.num_rows)
                hi = min(max(int(gbounds[p + 1]) - off, 0), m.num_rows)
                local.append((lo, hi))
            per_block_bounds.append(local)
            off += m.num_rows
        return _exchange(
            blocks, n, _range_split_task,
            lambda i: (per_block_bounds[i],),
            _concat_task, lambda i: (None, None, False))
    if kind == "random_shuffle":
        n = o.get("num_blocks") or max(len(blocks), 1)
        seed = o.get("seed")
        if seed is None:
            # Fresh entropy per unseeded shuffle: epochs must differ.
            seed = int(np.random.SeedSequence().entropy % (2 ** 31))
        return _exchange(blocks, n, _split_task,
                         lambda i: (n, seed + i),
                         _concat_task,
                         lambda i: (seed + 31 * i + 7, None, False))
    if kind == "sort":
        key, desc = o["key"], o.get("descending", False)
        n = max(len(blocks), 1)
        sample = ray_tpu.remote(_sample_task)
        samples = ray_tpu.get(
            [sample.remote(ref, key, 16) for ref, _ in blocks], timeout=600)
        nonempty = [s for s in samples if len(s)]
        allv = np.sort(np.concatenate(nonempty)) if nonempty else []
        if len(allv) == 0 or n == 1:
            boundaries = []
        else:
            idx = np.linspace(0, len(allv) - 1, n + 1).astype(int)[1:-1]
            boundaries = list(np.unique(allv[idx]))
        return _exchange(
            blocks, len(boundaries) + 1,
            _boundary_split_task, lambda i: (boundaries, key, desc),
            _concat_task, lambda i: (None, key, desc))
    if kind == "groupby_agg":
        key, aggs = o["key"], o["aggs"]
        n = min(max(len(blocks), 1), 8)
        out = _exchange(blocks, n, _hash_split_task, lambda i: (n, key),
                        _groupby_task, lambda i: (key, aggs))
        return [(r, m) for r, m in out if m.num_rows > 0]
    raise ValueError(kind)


# -- zip --------------------------------------------------------------------

def zip_streams(left, right):
    """Row-aligned zip: rechunk right to match left's block layout, then
    column-concat per block (reference: `zip_operator.py`)."""
    total_left = sum(m.num_rows for _, m in left)
    total_right = sum(m.num_rows for _, m in right)
    if total_left != total_right:
        raise ValueError(
            f"zip requires equal row counts, got {total_left} vs "
            f"{total_right}")
    ztask = ray_tpu.remote(_zip_task)
    right_refs = [r for r, _ in right]
    right_rows = [m.num_rows for _, m in right]
    out = []
    start = 0
    for (lref, lmeta) in left:
        out.append(ztask.remote(lref, right_refs, right_rows, start,
                                lmeta.num_rows))
        start += lmeta.num_rows
    return _collect(out)


def _zip_task(lblock, right_refs, right_rows, start, n):
    rights = []
    off = 0
    for ref, rn in zip(right_refs, right_rows):
        lo, hi = max(start - off, 0), min(start + n - off, rn)
        if lo < hi:
            rblock = ray_tpu.get(ref)
            rights.append(BlockAccessor.for_block(rblock).slice(lo, hi))
        off += rn
    rcat = concat_blocks(rights)
    lcols = BlockAccessor.for_block(lblock).to_numpy()
    rcols = BlockAccessor.for_block(rcat).to_numpy()
    merged = dict(lcols)
    for k, v in rcols.items():
        merged[k if k not in merged else f"{k}_1"] = v
    return _store(merged)
