"""All-to-all (barrier) operators: repartition, shuffle, sort, groupby.

Counterpart of the reference's exchange ops (`_internal/shuffle.py`,
`push_based_shuffle.py`, `sort.py`, `fast_repartition.py`).

Exchange layout: map-side tasks put EVERY output shard as its own
object and return only refs, so a reduce task's arguments are exactly
its own shards — the store localizes them shard-by-shard (never a
whole mapper output). Above PUSH_SHUFFLE_MIN_BLOCKS input blocks a
PUSH-BASED merge tier slots in (reference:
`push_based_shuffle.py`): mappers are grouped ~sqrt(M) wide, merger
tasks pre-concatenate each group's shards per partition WHILE other
mappers still run (the task graph pipelines map->merge naturally), and
the final reduce fans in over mergers instead of all M mappers —
O(sqrt(M)) fan-in per task instead of O(M), which is what keeps
hundreds-of-blocks exchanges off the quadratic cliff. The driver only
ever moves refs and tiny boundary samples.
"""

from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor, concat_blocks


def _store(block):
    """Put the block from the worker; return (block_ref, meta) — only refs
    and metadata ever reach the driver."""
    meta = BlockAccessor.for_block(block).metadata()
    return ray_tpu.put(block), meta


# -- map side ---------------------------------------------------------------

def _split_task(block, n, assignment_seed):
    """Split one block into n shards. assignment_seed None -> contiguous
    chunks; int -> random destination per row (shuffle)."""
    acc = BlockAccessor.for_block(block)
    rows = acc.num_rows()
    if assignment_seed is None:
        bounds = np.linspace(0, rows, n + 1).astype(int)
        return [acc.slice(int(bounds[i]), int(bounds[i + 1]))
                for i in range(n)]
    rng = np.random.default_rng(assignment_seed)
    dest = rng.integers(0, n, rows)
    return [acc.take(np.nonzero(dest == i)[0]) for i in range(n)]


def _range_split_task(block, bounds):
    """Order-preserving split: bounds is a list of (lo, hi) local row
    ranges, one per output partition (empty ranges allowed)."""
    acc = BlockAccessor.for_block(block)
    return [acc.slice(lo, hi) for lo, hi in bounds]


def _boundary_split_task(block, boundaries, key, descending):
    acc = BlockAccessor.for_block(block)
    col = acc.to_numpy()[key]
    dest = np.searchsorted(np.asarray(boundaries), col, side="right")
    n = len(boundaries) + 1
    if descending:
        dest = (n - 1) - dest
    return [acc.take(np.nonzero(dest == i)[0]) for i in range(n)]


def _hash_split_task(block, n, key):
    acc = BlockAccessor.for_block(block)
    col = acc.to_numpy()[key]
    if col.dtype.kind in "OUS":
        # Deterministic across processes (Python's hash() is per-process
        # randomized for str, which would scatter equal keys).
        import zlib
        dest = np.asarray(
            [zlib.crc32(str(x).encode()) % n for x in col])
    else:
        dest = (col.astype(np.int64, copy=False) % n + n) % n
    return [acc.take(np.nonzero(dest == i)[0]) for i in range(n)]


def _sample_task(block, key, k):
    acc = BlockAccessor.for_block(block)
    col = acc.to_numpy()[key]
    if len(col) == 0:
        return col
    idx = np.linspace(0, len(col) - 1, min(k, len(col))).astype(int)
    return np.sort(col)[idx]


# -- merge / reduce side ----------------------------------------------------

def _put_shards(shards):
    """Map/merge tail: every shard becomes its own object so a consumer
    pulls exactly the shards addressed to it."""
    return [ray_tpu.put(s) for s in shards]


def _split_put_task(split_fn, block, args):
    """Map tail shared by every exchange: split, then one object PER
    shard (a consumer pulls exactly the shards addressed to it)."""
    return _put_shards(split_fn(block, *args))


def _merge_task(n_part, *ref_lists):
    """Push-based merge of one mapper group: the args are the group's
    per-mapper [shard refs] lists (tiny — the scheduler starts this task
    the moment ITS group's mappers finish, while other groups still
    map). Concatenates per partition; returns one ref per partition."""
    out = []
    for p in range(n_part):
        shards = ray_tpu.get([lst[p] for lst in ref_lists])
        out.append(concat_blocks(shards))
    return _put_shards(out)


def _fetch_partition(list_refs, index):
    """Two tiny hops: resolve each upstream [shard refs] list (bytes),
    then fetch ONLY partition `index`'s shard from each."""
    lists = ray_tpu.get(list(list_refs))
    return ray_tpu.get([lst[index] for lst in lists])


def _concat_task(ref_lists, index, shuffle_seed=None, sort_key=None,
                 descending=False):
    """Reduce: fetch partition `index`'s shard from every upstream
    [refs] list (mapper or merger outputs) and concatenate."""
    block = concat_blocks(_fetch_partition(ref_lists, index))
    acc = BlockAccessor.for_block(block)
    if shuffle_seed is not None:
        rng = np.random.default_rng(shuffle_seed)
        block = acc.take(rng.permutation(acc.num_rows()))
    if sort_key is not None:
        cols = BlockAccessor.for_block(block).to_numpy()
        order = np.argsort(cols[sort_key], kind="stable")
        if descending:
            order = order[::-1]
        block = BlockAccessor.for_block(block).take(order)
    return _store(block)


def _groupby_task(ref_lists, index, key, aggs):
    """Per-partition pandas groupby (equal keys are co-located by the hash
    exchange, so per-partition aggregation is exact)."""
    import pandas as pd
    block = concat_blocks(_fetch_partition(ref_lists, index))
    df = BlockAccessor.for_block(block).to_pandas()
    if df.empty:
        return _store({})
    gb = df.groupby(key, sort=True)
    pieces = {}
    for col, how, out_name in aggs:
        if how == "count":
            pieces[out_name] = gb.size()
        else:
            pieces[out_name] = getattr(gb[col], how)()
    out = pd.DataFrame(pieces).reset_index()
    return _store(out)


# -- driver-side assembly ---------------------------------------------------

def _collect(task_refs):
    """Each task returns (block_ref, meta) — tiny driver-side fetch."""
    return [ray_tpu.get(r, timeout=600) for r in task_refs]


def _exchange(blocks, n_out, split_fn, split_args, concat_fn,
              concat_args, stats_op=None):
    """Generic exchange skeleton: map -> [push-based merge ->] reduce.
    Everything between the stages is refs; shard data moves worker->
    store->worker only."""
    import math

    from ray_tpu._private import config as _config

    split = ray_tpu.remote(_split_put_task)
    shard_lists = [split.remote(split_fn, ref, list(split_args(i)))
                   for i, (ref, _) in enumerate(blocks)]
    m = len(shard_lists)
    threshold = _config.get("DATA_PUSH_SHUFFLE_MIN_BLOCKS")
    note = f"direct exchange: {m} maps -> {n_out} partitions"
    sources = shard_lists
    if m >= threshold and n_out > 1:
        # push tier: ~sqrt(M) mappers per merger; a merger starts the
        # moment its own group finishes (pipelined against later maps)
        group = max(2, int(math.ceil(math.sqrt(m))))
        merge = ray_tpu.remote(_merge_task)
        sources = [merge.remote(n_out, *shard_lists[g:g + group])
                   for g in range(0, m, group)]
        note = (f"push-based shuffle: {m} maps -> {len(sources)} "
                f"mergers (fan-in {group}) -> {n_out} partitions")
    if stats_op is not None:
        stats_op.extra = note
    concat = ray_tpu.remote(concat_fn)
    out = [concat.remote(list(sources), i, *concat_args(i))
           for i in range(n_out)]
    result = _collect(out)
    # Intermediate lifecycle: shard refs rode INSIDE list objects, which
    # marks them escaped (session-lifetime) — per-epoch shuffles would
    # leak a dataset's worth of arena per epoch. The reduce is done with
    # every shard, so free them all explicitly (the reference's
    # push_based_shuffle frees its intermediates the same way).
    inter_lists = list(shard_lists)
    if sources is not shard_lists:
        inter_lists += list(sources)
    try:
        nested = ray_tpu.get(inter_lists, timeout=600)
        ray_tpu.free([r for lst in nested for r in lst] + inter_lists)
    except Exception:
        pass    # cleanup only; the exchange result is already safe
    # NOTE: the OUTPUT block refs (inside `result`) remain
    # session-lifetime — dataset results have no destructor-driven
    # lifecycle yet; wiring Dataset GC to ray_tpu.free is future work.
    return result


def run(op, blocks, stats_op=None):
    kind = op.kind
    o = op.options
    if kind == "repartition":
        # Order-preserving: output partition p owns global row range
        # [p*total/n, (p+1)*total/n); each input block contributes the
        # intersection with its own global range.
        n = o["num_blocks"]
        total = sum(m.num_rows for _, m in blocks)
        gbounds = np.linspace(0, total, n + 1).astype(int)
        per_block_bounds = []
        off = 0
        for _, m in blocks:
            local = []
            for p in range(n):
                lo = min(max(int(gbounds[p]) - off, 0), m.num_rows)
                hi = min(max(int(gbounds[p + 1]) - off, 0), m.num_rows)
                local.append((lo, hi))
            per_block_bounds.append(local)
            off += m.num_rows
        return _exchange(
            blocks, n, _range_split_task,
            lambda i: (per_block_bounds[i],),
            _concat_task, lambda i: (None, None, False),
            stats_op=stats_op)
    if kind == "random_shuffle":
        n = o.get("num_blocks") or max(len(blocks), 1)
        seed = o.get("seed")
        if seed is None:
            # Fresh entropy per unseeded shuffle: epochs must differ.
            seed = int(np.random.SeedSequence().entropy % (2 ** 31))
        return _exchange(blocks, n, _split_task,
                         lambda i: (n, seed + i),
                         _concat_task,
                         lambda i: (seed + 31 * i + 7, None, False),
                         stats_op=stats_op)
    if kind == "sort":
        key, desc = o["key"], o.get("descending", False)
        n = max(len(blocks), 1)
        sample = ray_tpu.remote(_sample_task)
        samples = ray_tpu.get(
            [sample.remote(ref, key, 16) for ref, _ in blocks], timeout=600)
        nonempty = [s for s in samples if len(s)]
        allv = np.sort(np.concatenate(nonempty)) if nonempty else []
        if len(allv) == 0 or n == 1:
            boundaries = []
        else:
            idx = np.linspace(0, len(allv) - 1, n + 1).astype(int)[1:-1]
            boundaries = list(np.unique(allv[idx]))
        return _exchange(
            blocks, len(boundaries) + 1,
            _boundary_split_task, lambda i: (boundaries, key, desc),
            _concat_task, lambda i: (None, key, desc),
            stats_op=stats_op)
    if kind == "groupby_agg":
        key, aggs = o["key"], o["aggs"]
        n = min(max(len(blocks), 1), 8)
        out = _exchange(blocks, n, _hash_split_task, lambda i: (n, key),
                        _groupby_task, lambda i: (key, aggs),
                        stats_op=stats_op)
        return [(r, m) for r, m in out if m.num_rows > 0]
    raise ValueError(kind)


# -- zip --------------------------------------------------------------------

def zip_streams(left, right):
    """Row-aligned zip: rechunk right to match left's block layout, then
    column-concat per block (reference: `zip_operator.py`)."""
    total_left = sum(m.num_rows for _, m in left)
    total_right = sum(m.num_rows for _, m in right)
    if total_left != total_right:
        raise ValueError(
            f"zip requires equal row counts, got {total_left} vs "
            f"{total_right}")
    ztask = ray_tpu.remote(_zip_task)
    right_refs = [r for r, _ in right]
    right_rows = [m.num_rows for _, m in right]
    out = []
    start = 0
    for (lref, lmeta) in left:
        out.append(ztask.remote(lref, right_refs, right_rows, start,
                                lmeta.num_rows))
        start += lmeta.num_rows
    return _collect(out)


def _zip_task(lblock, right_refs, right_rows, start, n):
    rights = []
    off = 0
    for ref, rn in zip(right_refs, right_rows):
        lo, hi = max(start - off, 0), min(start + n - off, rn)
        if lo < hi:
            rblock = ray_tpu.get(ref)
            rights.append(BlockAccessor.for_block(rblock).slice(lo, hi))
        off += rn
    rcat = concat_blocks(rights)
    lcols = BlockAccessor.for_block(lblock).to_numpy()
    rcols = BlockAccessor.for_block(rcat).to_numpy()
    merged = dict(lcols)
    for k, v in rcols.items():
        merged[k if k not in merged else f"{k}_1"] = v
    return _store(merged)
