"""Per-operator execution statistics (reference: `data/_internal/stats.py`
DatasetStats): each pipeline stage records blocks/rows/bytes produced and
the wall time spent blocked in its generator. Times are INCLUSIVE of
upstream pull time (pull-driven pipeline — the same caveat the
reference's streaming timings carry); the summary orders stages so the
deltas are readable."""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class OpStats:
    name: str
    blocks: int = 0
    rows: int = 0
    bytes: int = 0
    wall_s: float = 0.0
    extra: str = ""     # op-specific note (e.g. shuffle strategy/fan-in)


@dataclass
class PlanStats:
    ops: list = field(default_factory=list)
    started: float = field(default_factory=time.perf_counter)
    finished: float | None = None

    def new_op(self, name: str) -> OpStats:
        op = OpStats(name)
        self.ops.append(op)
        return op

    def summary(self) -> str:
        if not self.ops:
            return "Dataset not executed yet"
        total = ((self.finished or time.perf_counter()) - self.started)
        lines = [f"Dataset execution: {total:.3f}s total "
                 "(stage times include upstream pull)"]
        for op in self.ops:
            mb = op.bytes / (1024 * 1024)
            tail = f" [{op.extra}]" if op.extra else ""
            lines.append(
                f"  {op.name}: {op.wall_s:.3f}s, {op.blocks} blocks, "
                f"{op.rows} rows, {mb:.2f} MiB{tail}")
        return "\n".join(lines)


def timed_stage(stream, op: OpStats, stats: PlanStats):
    """Wrap a stage's (ref, meta) generator with accounting."""
    def gen():
        it = iter(stream)
        while True:
            t0 = time.perf_counter()
            try:
                ref, meta = next(it)
            except StopIteration:
                op.wall_s += time.perf_counter() - t0
                stats.finished = time.perf_counter()
                return
            op.wall_s += time.perf_counter() - t0
            op.blocks += 1
            op.rows += getattr(meta, "num_rows", 0) or 0
            op.bytes += getattr(meta, "size_bytes", 0) or 0
            yield ref, meta
    return gen()
