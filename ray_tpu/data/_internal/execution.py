"""Streaming execution of a logical plan over ray_tpu tasks/actors.

Counterpart of the reference's streaming executor
(`_internal/execution/streaming_executor.py:49` + operator classes under
`execution/operators/`). Shape of the design:

- Consecutive map-type ops are FUSED into one task payload (reference:
  operator fusion rule), so a read->map_batches->filter chain is one
  process-hop per block.
- Execution is a pull-driven generator pipeline: each stage consumes the
  previous stage's (ref, meta) stream and keeps at most `max_in_flight`
  tasks outstanding — bounded pipelining IS the backpressure (reference:
  streaming_executor_state.py resource budgets; ours is expressed in task
  slots instead of bytes because the object store is node-local tmpfs).
- Every stored object is a pair (block, BlockMetadata) so metadata is
  always available with the ref.
- All-to-all ops (shuffle/sort/repartition/groupby) are barriers, as in the
  reference's exchange ops.

Actor compute (`ActorPoolStrategy`) runs the same fused payload inside a
pool of stateful actors — the TPU batch-inference path where the model
loads once per actor (reference: `actor_pool_map_operator.py:34`).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass

import numpy as np

import ray_tpu
from ray_tpu.data._internal import plan as plan_mod
from ray_tpu.data.block import BlockAccessor, BlockMetadata, concat_blocks

from ray_tpu._private.constants import (
    DATA_BYTES_IN_FLIGHT as _DEFAULT_BYTES_IN_FLIGHT,
    DATA_MAX_TASKS_IN_FLIGHT as _DEFAULT_IN_FLIGHT,
)


def _item_bytes(item, ctx) -> int:
    """Estimated input bytes of one work item: exact for (ref, meta) pairs
    (block already in the store), estimated for ReadTask thunks (reference:
    streaming executors budget on block-size estimates too)."""
    if isinstance(item, tuple):
        size = getattr(item[1], "size_bytes", None)
        if size:
            return int(size)
    est = getattr(item, "estimated_size_bytes", None)
    return int(est) if est else ctx.default_block_size_estimate


class _InFlightBudget:
    """Task-slot AND byte budget for one operator's outstanding tasks
    (streaming_executor_state.py resource-budget equivalent): admit while
    BOTH under budget; always admit at least one task so a single
    over-budget block can't deadlock the pipeline."""

    def __init__(self, ctx, max_tasks: int):
        self.max_tasks = max_tasks
        self.max_bytes = (ctx.max_bytes_in_flight
                          or _DEFAULT_BYTES_IN_FLIGHT)
        self.tasks = 0
        self.bytes = 0

    def admit(self, nbytes: int) -> bool:
        if self.tasks == 0:
            return True
        return (self.tasks < self.max_tasks
                and self.bytes + nbytes <= self.max_bytes)

    def add(self, nbytes: int):
        self.tasks += 1
        self.bytes += nbytes

    def remove(self, nbytes: int):
        self.tasks -= 1
        self.bytes -= nbytes


# ---------------------------------------------------------------------------
# fused map chains
# ---------------------------------------------------------------------------

@dataclass
class _ChainStage:
    kind: str                 # map_batches | map | filter | flat_map | write
    fn: object
    fn_constructor_args: tuple
    fn_args: tuple
    fn_kwargs: dict
    batch_size: int | None
    batch_format: str | None
    is_callable_class: bool


def _make_stage(op: plan_mod.MapOp) -> _ChainStage:
    return _ChainStage(op.kind, op.fn, op.fn_constructor_args, op.fn_args,
                       op.fn_kwargs, op.batch_size, op.batch_format,
                       op.is_callable_class)


def _instantiate(stage: _ChainStage, cache: dict):
    """Callable classes are constructed once per process/actor and cached
    (the whole point of actor compute: load the model once). Keyed by
    identity (module, qualname, ctor args), NOT id(): cloudpickle ships a
    fresh class object per task for by-value-pickled classes, so id() would
    miss every time (reconstructing the model per block) and leak stale
    instances."""
    if not stage.is_callable_class:
        return stage.fn
    key = (getattr(stage.fn, "__module__", ""),
           getattr(stage.fn, "__qualname__", repr(stage.fn)),
           repr(stage.fn_constructor_args))
    if key not in cache:
        cache[key] = stage.fn(*stage.fn_constructor_args)
    return cache[key]


def _apply_stage(stage: _ChainStage, block, cache: dict):
    acc = BlockAccessor.for_block(block)
    fn = _instantiate(stage, cache)
    if stage.kind == "map_batches":
        n = acc.num_rows()
        bs = stage.batch_size or max(n, 1)
        out = []
        for s in range(0, max(n, 1), bs):
            sub = BlockAccessor.for_block(
                acc.slice(s, min(s + bs, n))) if n else acc
            batch = sub.to_batch(stage.batch_format)
            res = fn(batch, *stage.fn_args, **stage.fn_kwargs)
            out.append(BlockAccessor.batch_to_block(res))
        return concat_blocks(out)
    if stage.kind == "map":
        rows = [fn(r, *stage.fn_args, **stage.fn_kwargs)
                for r in acc.iter_rows()]
        return BlockAccessor.batch_to_block(rows)
    if stage.kind == "filter":
        keep = [i for i, r in enumerate(acc.iter_rows())
                if fn(r, *stage.fn_args, **stage.fn_kwargs)]
        return acc.take(keep)
    if stage.kind == "flat_map":
        rows = []
        for r in acc.iter_rows():
            rows.extend(fn(r, *stage.fn_args, **stage.fn_kwargs))
        return BlockAccessor.batch_to_block(rows)
    if stage.kind == "write":
        fn(block, *stage.fn_args, **stage.fn_kwargs)
        return block
    raise ValueError(stage.kind)


def _run_chain(stages: list, item, _cache={}):
    """Task body: item is either a bare block (resolved from a block ref)
    or a ReadTask thunk. Returns (block_ref, meta): the block itself is
    `put` into the store FROM THE WORKER, so the driver only ever touches
    refs + metadata — dataset bytes never funnel through the driver."""
    if callable(item):                      # read task
        block = item()
        files = getattr(item, "input_files", None)
    else:
        block = item
        files = None
    for stage in stages:
        block = _apply_stage(stage, block, _cache)
    meta = BlockAccessor.for_block(block).metadata(files)
    return ray_tpu.put(block), meta


class _MapWorker:
    """Actor hosting a fused chain; constructor caches live for the actor's
    lifetime (reference: `actor_pool_map_operator.py` _MapWorker)."""

    def __init__(self):
        self._cache = {}

    def ready(self):
        return True

    def apply(self, stages, item):
        return _run_chain(stages, item, self._cache)


# ---------------------------------------------------------------------------
# stage streams
# ---------------------------------------------------------------------------

def _submit_arg(item):
    """(ref, meta) pairs submit as the bare top-level ref (the scheduler
    resolves it to the stored (block, meta) pair); ReadTask thunks submit
    as-is."""
    return item[0] if isinstance(item, tuple) else item


def _task_map_stream(inputs, stages, op: plan_mod.MapOp | None):
    """Submit one task per input with a bounded window; yield refs in order."""
    fn = ray_tpu.remote(_run_chain)
    opts = {}
    if op is not None:
        if op.num_cpus is not None:
            opts["num_cpus"] = op.num_cpus
        if op.num_tpus is not None:
            opts["num_tpus"] = op.num_tpus
    if opts:
        fn = fn.options(**opts)
    from ray_tpu.data.context import DataContext
    ctx = DataContext.get_current()
    budget = _InFlightBudget(
        ctx, ctx.max_tasks_per_operator or _DEFAULT_IN_FLIGHT)
    window: list = []          # (task_ref, input_bytes)
    for item in inputs:
        nbytes = _item_bytes(item, ctx)
        while not budget.admit(nbytes):
            ref, nb = window.pop(0)
            budget.remove(nb)
            yield _result(ref)
        window.append((fn.remote(stages, _submit_arg(item)), nbytes))
        budget.add(nbytes)
    for ref, _nb in window:
        yield _result(ref)


def _actor_map_stream(inputs, stages, op: plan_mod.MapOp):
    from ray_tpu.data.dataset import ActorPoolStrategy
    strat: ActorPoolStrategy = op.compute
    size = strat.size or strat.min_size or 2
    opts = {}
    if op.num_cpus is not None:
        opts["num_cpus"] = op.num_cpus
    if op.num_tpus is not None:
        opts["num_tpus"] = op.num_tpus
    cls = ray_tpu.remote(_MapWorker)
    if opts:
        cls = cls.options(**opts)
    actors = [cls.remote() for _ in range(size)]
    try:
        ray_tpu.get([a.ready.remote() for a in actors], timeout=120)
        per_actor = max(1, strat.max_tasks_in_flight_per_actor)
        from ray_tpu.data.context import DataContext
        ctx = DataContext.get_current()
        budget = _InFlightBudget(ctx, size * per_actor)
        window: list = []
        rr = itertools.cycle(range(size))
        for item in inputs:
            nbytes = _item_bytes(item, ctx)
            while not budget.admit(nbytes):
                ref, nb = window.pop(0)
                budget.remove(nb)
                yield _result(ref)
            actor = actors[next(rr)]
            window.append(
                (actor.apply.remote(stages, _submit_arg(item)), nbytes))
            budget.add(nbytes)
        for ref, _nb in window:
            yield _result(ref)
    finally:
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


def _result(task_ref):
    """A chain task's return IS (block_ref, meta) — tiny; the block stays
    in the store until some consumer fetches the block_ref."""
    block_ref, meta = ray_tpu.get(task_ref)
    return block_ref, meta


def _source_stream(op):
    if isinstance(op, plan_mod.InputData):
        yield from op.blocks
        return
    # Read: run read tasks through the (possibly fused) map path; callers
    # fuse stages onto it, so a bare Read is _task_map_stream with no stages.
    raise AssertionError("Read handled in execute_plan segmentation")


def _limit_stream(inputs, n: int):
    seen = 0
    for ref, meta in inputs:
        if seen >= n:
            break
        if seen + meta.num_rows <= n:
            seen += meta.num_rows
            yield ref, meta
            continue
        block = ray_tpu.get(ref)
        cut = BlockAccessor.for_block(block).slice(0, n - seen)
        cut_meta = BlockAccessor.for_block(cut).metadata()
        yield ray_tpu.put(cut), cut_meta
        seen = n


# ---------------------------------------------------------------------------
# plan segmentation + dispatch
# ---------------------------------------------------------------------------

def execute_plan(plan: plan_mod.ExecutionPlan):
    """Generator of (ref, meta) driving the fused stage pipeline. Records
    per-stage stats onto the plan (Dataset.stats())."""
    from ray_tpu.data._internal import allops
    from ray_tpu.data._internal.stats import PlanStats, timed_stage

    stats = PlanStats()
    plan.last_stats = stats

    def timed(stream, name):
        return timed_stage(stream, stats.new_op(name), stats)

    ops = plan.ops
    stream = None
    i = 0
    while i < len(ops):
        op = ops[i]
        if isinstance(op, (plan_mod.Read, plan_mod.InputData)):
            # Fuse any directly following map ops into the source stage.
            stages, j = _collect_stages(ops, i + 1)
            fused = "+".join(o.name for o in ops[i:j])
            if isinstance(op, plan_mod.InputData):
                if stages:
                    map_op = ops[i + 1]
                    stream = timed(_dispatch_map(iter(op.blocks), stages,
                                                 map_op), fused)
                else:
                    stream = timed(iter(op.blocks), fused)
            else:
                map_op = ops[i + 1] if stages else None
                stream = timed(_dispatch_map(iter(op.read_tasks), stages,
                                             map_op), fused)
            i = j
        elif isinstance(op, plan_mod.MapOp):
            stages, j = _collect_stages(ops, i)
            fused = "+".join(o.name for o in ops[i:j])
            stream = timed(_dispatch_map(stream, stages, op), fused)
            i = j
        elif isinstance(op, plan_mod.AllToAll):
            # materialize INSIDE a generator so the timed wrapper charges
            # the barrier's compute to this op, not ~0s; the op stats
            # object rides along so the exchange can record its strategy
            # (direct vs push-based + merge fan-in)
            op_stats = stats.new_op(op.name)

            def _run_barrier(_op=op, _up=stream, _os=op_stats):
                yield from allops.run(_op, list(_up), stats_op=_os)
            stream = timed_stage(_run_barrier(), op_stats, stats)
            i += 1
        elif isinstance(op, plan_mod.Limit):
            stream = timed(_limit_stream(stream, op.n), op.name)
            i += 1
        elif isinstance(op, plan_mod.Union):
            streams = [stream] + [p.stream() for p in op.others]
            stream = timed(itertools.chain(*streams), op.name)
            i += 1
        elif isinstance(op, plan_mod.Zip):
            def _run_zip(_op=op, _up=stream):
                yield from allops.zip_streams(
                    list(_up), list(_op.other.stream()))
            stream = timed(_run_zip(), op.name)
            i += 1
        else:
            raise ValueError(f"unknown op {op}")
    yield from stream


def _collect_stages(ops, start):
    """Greedy fusion of consecutive task-compute map ops. Actor-compute ops
    never fuse with neighbors (they need their own pool)."""
    from ray_tpu.data.context import DataContext
    stages = []
    j = start
    while j < len(ops) and isinstance(ops[j], plan_mod.MapOp):
        op = ops[j]
        if op.compute is not None and (stages or j > start):
            break
        stages.append(_make_stage(op))
        j += 1
        if op.compute is not None:
            break
        if not DataContext.get_current().enable_operator_fusion:
            break
    return stages, j


def _dispatch_map(inputs, stages, op: plan_mod.MapOp | None):
    if op is not None and op.compute is not None:
        return _actor_map_stream(inputs, stages, op)
    return _task_map_stream(inputs, stages, op)
