"""Block model: the unit of distributed data.

Counterpart of the reference's `data/block.py` + `_internal/arrow_block.py` /
`pandas_block.py` / numpy support: a Block is a pyarrow Table, a pandas
DataFrame, or a dict of numpy arrays (column-major). `BlockAccessor` gives a
uniform view over all three, chosen so the hot path for TPU feeding —
`iter_batches(batch_format="numpy")` → `jax.device_put` — is zero-copy from
Arrow where dtypes allow.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable

import numpy as np

# A Block is pa.Table | pd.DataFrame | dict[str, np.ndarray].
Block = Any


@dataclass
class BlockMetadata:
    """Counterpart of reference `data/block.py` BlockMetadata: size info
    kept driver-side so planning never fetches data."""
    num_rows: int
    size_bytes: int
    schema: Any = None
    input_files: list | None = None


def _is_tabular_dict(d) -> bool:
    return isinstance(d, dict) and all(
        isinstance(v, np.ndarray) for v in d.values())


class BlockAccessor:
    """Uniform view over arrow Table / pandas DataFrame / numpy dict."""

    def __init__(self, block):
        self._block = block

    @staticmethod
    def for_block(block) -> "BlockAccessor":
        return BlockAccessor(block)

    # -- builders -----------------------------------------------------------

    @staticmethod
    def batch_to_block(batch):
        """Normalize a UDF-returned batch into a canonical block."""
        import pandas as pd
        import pyarrow as pa
        if isinstance(batch, (pa.Table, pd.DataFrame)):
            return batch
        if _is_tabular_dict(batch):
            return batch
        if isinstance(batch, dict):
            return {k: np.asarray(v) for k, v in batch.items()}
        if isinstance(batch, np.ndarray):
            return {"data": batch}
        if isinstance(batch, list):
            return _rows_to_block(batch)
        raise TypeError(
            f"UDF returned unsupported batch type {type(batch).__name__}; "
            "expected dict-of-ndarray, ndarray, pyarrow.Table, DataFrame, "
            "or list of rows")

    # -- core ---------------------------------------------------------------

    @property
    def block(self):
        return self._block

    def num_rows(self) -> int:
        import pandas as pd
        import pyarrow as pa
        b = self._block
        if isinstance(b, pa.Table):
            return b.num_rows
        if isinstance(b, pd.DataFrame):
            return len(b)
        if not b:
            return 0
        return len(next(iter(b.values())))

    def size_bytes(self) -> int:
        import pandas as pd
        import pyarrow as pa
        b = self._block
        if isinstance(b, pa.Table):
            return b.nbytes
        if isinstance(b, pd.DataFrame):
            return int(b.memory_usage(index=False, deep=True).sum())
        return sum(v.nbytes for v in b.values())

    def schema(self):
        import pandas as pd
        import pyarrow as pa
        b = self._block
        if isinstance(b, pa.Table):
            return b.schema
        if isinstance(b, pd.DataFrame):
            return pa.Schema.from_pandas(b, preserve_index=False)
        return {k: v.dtype for k, v in b.items()}

    def metadata(self, input_files=None) -> BlockMetadata:
        return BlockMetadata(self.num_rows(), self.size_bytes(),
                             self.schema(), input_files)

    def column_names(self) -> list:
        import pandas as pd
        import pyarrow as pa
        b = self._block
        if isinstance(b, pa.Table):
            return list(b.column_names)
        if isinstance(b, pd.DataFrame):
            return list(b.columns)
        return list(b.keys())

    # -- conversions --------------------------------------------------------

    def to_arrow(self):
        import pandas as pd
        import pyarrow as pa
        b = self._block
        if isinstance(b, pa.Table):
            return b
        if isinstance(b, pd.DataFrame):
            return pa.Table.from_pandas(b, preserve_index=False)
        cols, names = [], []
        for k, v in b.items():
            names.append(k)
            if v.ndim == 1:
                cols.append(pa.array(v))
            else:  # tensor column: list-of-lists representation
                cols.append(pa.array(list(v)))
        return pa.Table.from_arrays(cols, names=names)

    def to_pandas(self):
        import pandas as pd
        import pyarrow as pa
        b = self._block
        if isinstance(b, pd.DataFrame):
            return b
        if isinstance(b, pa.Table):
            return b.to_pandas()
        return pd.DataFrame(
            {k: (v if v.ndim == 1 else list(v)) for k, v in b.items()})

    def to_numpy(self) -> dict:
        import pandas as pd
        import pyarrow as pa
        b = self._block
        if _is_tabular_dict(b):
            return b
        if isinstance(b, pa.Table):
            out = {}
            for name in b.column_names:
                col = b.column(name)
                try:
                    out[name] = col.to_numpy(zero_copy_only=False)
                except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                    out[name] = np.asarray(col.to_pylist(), dtype=object)
                if out[name].dtype == object and len(out[name]) and \
                        isinstance(out[name][0], (list, np.ndarray)):
                    try:
                        out[name] = np.stack(
                            [np.asarray(x) for x in out[name]])
                    except ValueError:
                        pass   # ragged; keep object array
            return out
        if isinstance(b, pd.DataFrame):
            return {c: b[c].to_numpy() for c in b.columns}
        raise TypeError(type(b))

    def to_batch(self, batch_format: str | None):
        if batch_format in (None, "default", "numpy"):
            return self.to_numpy()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self.to_arrow()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    # -- row/slice ops ------------------------------------------------------

    def slice(self, start: int, end: int):
        import pandas as pd
        import pyarrow as pa
        b = self._block
        if isinstance(b, pa.Table):
            return b.slice(start, end - start)
        if isinstance(b, pd.DataFrame):
            return b.iloc[start:end]
        return {k: v[start:end] for k, v in b.items()}

    def take(self, indices):
        import pandas as pd
        import pyarrow as pa
        b = self._block
        idx = np.asarray(indices)
        if idx.dtype != bool:
            idx = idx.astype(np.int64, copy=False)   # [] defaults to f64
        if isinstance(b, pa.Table):
            return b.take(idx)
        if isinstance(b, pd.DataFrame):
            return b.iloc[idx]
        return {k: v[idx] for k, v in b.items()}

    def iter_rows(self) -> Iterable[dict]:
        cols = self.to_numpy()
        names = list(cols)
        n = self.num_rows()
        for i in range(n):
            yield {k: cols[k][i] for k in names}


def _rows_to_block(rows: list):
    """List of dict rows (or scalars) -> numpy-dict block."""
    if not rows:
        return {}
    if isinstance(rows[0], dict):
        keys = rows[0].keys()
        out = {}
        for k in keys:
            vals = [r[k] for r in rows]
            try:
                out[k] = np.asarray(vals)
            except ValueError:
                out[k] = np.asarray(vals, dtype=object)
        return out
    return {"item": np.asarray(rows)}


def concat_blocks(blocks: list):
    """Concatenate same-kind blocks (normalizing mixed kinds via arrow)."""
    import pandas as pd
    import pyarrow as pa
    blocks = [b for b in blocks
              if BlockAccessor.for_block(b).num_rows() > 0]
    if not blocks:
        return {}
    kinds = {type(b) for b in blocks}
    if len(kinds) > 1:
        blocks = [BlockAccessor.for_block(b).to_arrow() for b in blocks]
    b0 = blocks[0]
    if isinstance(b0, pa.Table):
        return pa.concat_tables(blocks, promote_options="default")
    if isinstance(b0, pd.DataFrame):
        return pd.concat(blocks, ignore_index=True)
    keys = blocks[0].keys()
    return {k: np.concatenate([b[k] for b in blocks]) for k in keys}
