"""Execution context / knobs (reference: `data/context.py` DataContext).

Every knob here is read by the executor — config options that exist but do
nothing are worse than missing ones.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ray_tpu._private.constants import DATA_BLOCK_SIZE_ESTIMATE


@dataclass
class DataContext:
    # Backpressure bounds, both enforced by the executor (reference:
    # streaming_executor_state.py byte budgets): at most
    # max_tasks_per_operator tasks AND max_bytes_in_flight input bytes may
    # be outstanding per operator. The byte budget is what keeps a
    # pipeline whose working set exceeds the shm arena from overcommitting
    # it (blocks of unknown size count as default_block_size_estimate).
    max_tasks_per_operator: int | None = None    # None = config default
    max_bytes_in_flight: int | None = None       # None = config default
    default_block_size_estimate: int = field(
        default_factory=lambda: DATA_BLOCK_SIZE_ESTIMATE)
    # Default parallelism for read_*/from_* when the call passes -1.
    read_parallelism: int = -1                   # -1 = #CPUs
    enable_operator_fusion: bool = True

    _local = threading.local()

    @staticmethod
    def get_current() -> "DataContext":
        ctx = getattr(DataContext._local, "ctx", None)
        if ctx is None:
            ctx = DataContext()
            DataContext._local.ctx = ctx
        return ctx
