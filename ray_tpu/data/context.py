"""Execution context / knobs (reference: `data/context.py` DataContext).

Every knob here is read by the executor — config options that exist but do
nothing are worse than missing ones.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass


@dataclass
class DataContext:
    # Max concurrent tasks per operator: the backpressure bound (the
    # reference budgets bytes in streaming_executor_state; ours is task
    # slots — the object store is node-local tmpfs, so slots ~ blocks).
    max_tasks_per_operator: int | None = None    # None = default (8)
    # Default parallelism for read_*/from_* when the call passes -1.
    read_parallelism: int = -1                   # -1 = #CPUs
    enable_operator_fusion: bool = True

    _local = threading.local()

    @staticmethod
    def get_current() -> "DataContext":
        ctx = getattr(DataContext._local, "ctx", None)
        if ctx is None:
            ctx = DataContext()
            DataContext._local.ctx = ctx
        return ctx
