"""ray_tpu.data — distributed datasets with streaming execution.

Counterpart of the reference's Ray Data (`python/ray/data/`, SURVEY.md
§2.7): lazy logical plans, fused map stages over tasks/actor pools,
two-phase exchanges for shuffle/sort/groupby, and `iter_batches` feeding
`jax.device_put` for TPU ingest — `Dataset.iter_device_batches(mesh=...)`
streams sharded device batches through the overlap-aware prefetcher in
`ray_tpu/train/loop.py`.
"""

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import (
    ActorPoolStrategy,
    DataIterator,
    Dataset,
    GroupedData,
    TaskPoolStrategy,
)
from ray_tpu.data.read_api import (
    from_arrow,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    from_torch,
    range,
    range_tensor,
    read_binary_files,
    read_csv,
    read_datasource,
    read_json,
    read_images,
    read_numpy,
    read_parquet,
    read_text,
    read_sql,
    read_tfrecords,
    read_webdataset,
)

__all__ = [
    "ActorPoolStrategy", "TaskPoolStrategy", "BlockAccessor",
    "BlockMetadata", "Block", "DataContext", "DataIterator", "Dataset",
    "GroupedData",
    "from_arrow", "from_huggingface", "from_items", "from_numpy",
    "from_pandas", "from_torch", "range", "range_tensor",
    "read_binary_files", "read_csv", "read_datasource", "read_json",
    "read_images", "read_numpy", "read_parquet", "read_sql",
    "read_text",
    "read_webdataset",
    "read_tfrecords",
]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu("data")
del _rlu
