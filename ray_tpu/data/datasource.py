"""Datasources: pluggable readers/writers producing ReadTasks.

Counterpart of the reference's `data/datasource/` (parquet, csv, json,
text, numpy, binary, range). A ReadTask is a zero-arg callable returning
one block; it runs inside a worker task so IO parallelizes and the driver
never touches file bytes.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Callable

import numpy as np


class ReadTask:
    """Callable producing one block, with file provenance for metadata."""

    def __init__(self, fn: Callable, input_files: list | None = None):
        self._fn = fn
        self.input_files = input_files

    def __call__(self):
        return self._fn()


def _expand_paths(paths) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                f for f in _glob.glob(os.path.join(p, "**"), recursive=True)
                if os.path.isfile(f)))
        elif any(c in p for c in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths!r}")
    return out


def _chunk(files: list, parallelism: int) -> list[list]:
    parallelism = max(1, min(parallelism, len(files)))
    bounds = np.linspace(0, len(files), parallelism + 1).astype(int)
    return [files[bounds[i]:bounds[i + 1]] for i in range(parallelism)
            if bounds[i] < bounds[i + 1]]


def _object_array(vals: list) -> np.ndarray:
    """list -> 1-D object ndarray (ragged/bytes-safe; np.asarray would
    coerce to fixed-width dtypes and e.g. strip trailing NULs from
    bytes)."""
    arr = np.empty(len(vals), dtype=object)
    for i, v in enumerate(vals):
        arr[i] = v
    return arr


class Datasource:
    """Subclass hook-point (reference: `datasource.py` Datasource)."""

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        raise NotImplementedError

    def write(self, block, path: str, **kwargs):
        raise NotImplementedError


class FileBasedDatasource(Datasource):
    def __init__(self, paths, **read_kwargs):
        self._files = _expand_paths(paths)
        self._kwargs = read_kwargs

    def _read_files(self, files: list) -> object:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        return [
            ReadTask((lambda fs=fs: self._read_files(fs)), input_files=fs)
            for fs in _chunk(self._files, parallelism)
        ]


class ParquetDatasource(FileBasedDatasource):
    def _read_files(self, files):
        import pyarrow.parquet as pq
        import pyarrow as pa
        tables = [pq.read_table(f, **self._kwargs) for f in files]
        return pa.concat_tables(tables) if len(tables) > 1 else tables[0]


class CSVDatasource(FileBasedDatasource):
    def _read_files(self, files):
        import pyarrow as pa
        from pyarrow import csv as pacsv
        tables = [pacsv.read_csv(f, **self._kwargs) for f in files]
        return pa.concat_tables(tables) if len(tables) > 1 else tables[0]


class JSONDatasource(FileBasedDatasource):
    """JSONL (newline-delimited) via pyarrow.json."""

    def _read_files(self, files):
        import pyarrow as pa
        from pyarrow import json as pajson
        tables = [pajson.read_json(f, **self._kwargs) for f in files]
        return pa.concat_tables(tables) if len(tables) > 1 else tables[0]


class TextDatasource(FileBasedDatasource):
    def _read_files(self, files):
        lines = []
        for f in files:
            with open(f, "r", encoding=self._kwargs.get("encoding", "utf-8"),
                      errors="replace") as fh:
                lines.extend(l.rstrip("\n") for l in fh)
        return {"text": np.asarray(lines, dtype=object)}


class NumpyDatasource(FileBasedDatasource):
    def _read_files(self, files):
        arrs = [np.load(f, allow_pickle=False) for f in files]
        return {"data": np.concatenate(arrs) if len(arrs) > 1 else arrs[0]}


class BinaryDatasource(FileBasedDatasource):
    def _read_files(self, files):
        blobs, names = [], []
        for f in files:
            with open(f, "rb") as fh:
                blobs.append(fh.read())
            names.append(f)
        return {"bytes": np.asarray(blobs, dtype=object),
                "path": np.asarray(names, dtype=object)}


class ImageDatasource(FileBasedDatasource):
    """Decoded images as HWC uint8 arrays (reference:
    `data/datasource/image_datasource.py`): columns `image` (object array
    of ndarrays, or a dense [N,H,W,C] block when `size=` forces uniform
    shapes) and `path`."""

    def _read_files(self, files):
        from PIL import Image
        size = self._kwargs.get("size")          # (H, W) resize
        mode = self._kwargs.get("mode", "RGB")
        imgs, names = [], []
        for f in files:
            with Image.open(f) as im:
                im = im.convert(mode)
                if size is not None:
                    im = im.resize((size[1], size[0]))
                imgs.append(np.asarray(im))
            names.append(f)
        if size is not None:
            col = np.stack(imgs)
        else:
            col = _object_array(imgs)
        return {"image": col, "path": np.asarray(names, dtype=object)}


class TFRecordDatasource(FileBasedDatasource):
    """tf.train.Example records decoded into columns (reference:
    `data/datasource/tfrecords_datasource.py`) via the built-in proto
    codec (_private/tfrecord.py — no tensorflow in the image).
    Single-element features unwrap to scalars, like the reference."""

    def _read_files(self, files):
        from ray_tpu._private.tfrecord import decode_example, read_records
        rows = []
        for f in files:
            for payload in read_records(f):
                ex = decode_example(payload)
                rows.append({
                    k: (v[0] if len(v) == 1 else v)
                    for k, v in ex.items()})
        cols: dict = {}
        # union of feature keys across ALL records — a sparse feature in
        # later records must not be silently dropped
        keys: dict = {}
        for r in rows:
            for k in r:
                keys[k] = True
        for k in keys:
            vals = [r.get(k) for r in rows]
            try:
                cols[k] = np.asarray(vals)
                if cols[k].dtype.kind == "O" and not isinstance(
                        vals[0], (bytes, str, list)):
                    raise ValueError
            except ValueError:
                arr = np.empty(len(vals), dtype=object)
                for i, v in enumerate(vals):
                    arr[i] = v
                cols[k] = arr
        return cols


class WebDatasetDatasource(FileBasedDatasource):
    """POSIX-tar shards in the webdataset layout (reference:
    `data/datasource/webdataset_datasource.py`): files inside each tar
    are grouped into samples by their basename ("abc.jpg" + "abc.cls" =
    one sample with keys "jpg" and "cls"), decoded by extension:

    - jpg/jpeg/png/ppm -> HWC uint8 arrays (PIL)
    - cls/id           -> int
    - txt              -> str
    - json             -> parsed object
    - npy              -> ndarray
    - anything else    -> raw bytes

    Rows carry "__key__" plus one column per extension. Pass
    ``decode=False`` to get raw bytes for every entry."""

    _IMAGE_EXTS = ("jpg", "jpeg", "png", "ppm")

    def _decode(self, ext: str, data: bytes):
        if not self._kwargs.get("decode", True):
            return data
        if ext in self._IMAGE_EXTS:
            import io

            from PIL import Image
            with Image.open(io.BytesIO(data)) as im:
                return np.asarray(im.convert(
                    self._kwargs.get("mode", "RGB")))
        if ext in ("cls", "id"):
            return int(data.decode().strip())
        if ext == "txt":
            return data.decode()
        if ext == "json":
            import json as _json
            return _json.loads(data)
        if ext == "npy":
            import io
            return np.load(io.BytesIO(data), allow_pickle=False)
        return data

    def _read_files(self, files):
        import tarfile

        rows: list[dict] = []
        for path in files:
            samples: dict[str, dict] = {}
            order: list[str] = []
            with tarfile.open(path) as tar:
                for member in tar:
                    if not member.isfile():
                        continue
                    base = os.path.basename(member.name)
                    # webdataset groups by everything before the FIRST
                    # dot: "000.seg.png" joins sample "000" as field
                    # "seg.png" (compound extensions)
                    key, _, ext = base.partition(".")
                    data = tar.extractfile(member).read()
                    if key not in samples:
                        samples[key] = {"__key__": key}
                        order.append(key)
                    samples[key][ext.lower()] = self._decode(
                        ext.lower(), data)
            rows.extend(samples[k] for k in order)
        cols: dict[str, list] = {}
        for row in rows:
            for k in row:
                cols.setdefault(k, [])
        for row in rows:
            for k, acc in cols.items():
                acc.append(row.get(k))
        return {k: _object_array(vals) for k, vals in cols.items()}


class SQLDatasource(Datasource):
    """DBAPI-2 query results as rows (reference:
    `data/datasource/sql_datasource.py` read_sql over a connection
    factory). `connection_factory` must be picklable (e.g. a module-
    level function returning sqlite3/psycopg connections) since read
    tasks run in workers. Parallelism: the query runs once per shard
    with OFFSET/LIMIT pagination when `shard_rows` is given (the query
    MUST be deterministically ordered — put an ORDER BY on a unique key
    or shards may duplicate/miss rows; the final shard is unbounded so
    no row past num_shards*shard_rows is dropped), else as a single
    task."""

    def __init__(self, sql: str, connection_factory, shard_rows=None,
                 num_shards: int = 1):
        self.sql = sql
        self.connection_factory = connection_factory
        self.shard_rows = shard_rows
        self.num_shards = num_shards

    def _fetch(self, sql: str):
        conn = self.connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            names = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        cols = {}
        for j, name in enumerate(names):
            vals = [r[j] for r in rows]
            if any(isinstance(v, bytes) for v in vals):
                # np.asarray would make fixed-width "S" dtype and strip
                # trailing NULs — silent BLOB corruption
                cols[name] = _object_array(vals)
                continue
            try:
                cols[name] = np.asarray(vals)
            except ValueError:
                cols[name] = _object_array(vals)
        return cols

    # last shard is unbounded so rows past num_shards*shard_rows are
    # never silently dropped (2**62 is within every engine's LIMIT max)
    _UNBOUNDED = 1 << 62

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        base_sql = self.sql.rstrip().rstrip(";")
        if self.shard_rows is None:
            return [ReadTask(lambda sql=base_sql: self._fetch(sql))]
        tasks = []
        for i in range(self.num_shards):
            limit = (self.shard_rows if i < self.num_shards - 1
                     else self._UNBOUNDED)
            sharded = (f"{base_sql} LIMIT {limit} "
                       f"OFFSET {i * self.shard_rows}")
            tasks.append(ReadTask(
                lambda sql=sharded: self._fetch(sql)))
        return tasks


class RangeDatasource(Datasource):
    def __init__(self, n: int, tensor_shape=None):
        self._n = n
        self._shape = tensor_shape

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        parallelism = max(1, min(parallelism, max(self._n, 1)))
        bounds = np.linspace(0, self._n, parallelism + 1).astype(int)
        tasks = []
        shape = self._shape
        for i in range(parallelism):
            lo, hi = int(bounds[i]), int(bounds[i + 1])
            if lo >= hi and self._n > 0:
                continue

            def make(lo=lo, hi=hi):
                ids = np.arange(lo, hi)
                if shape is None:
                    return {"id": ids}
                data = np.broadcast_to(
                    ids.reshape((-1,) + (1,) * len(shape)),
                    (hi - lo,) + tuple(shape)).copy()
                return {"data": data}
            tasks.append(ReadTask(make))
        return tasks or [ReadTask(lambda: {"id": np.arange(0)})]


# -- writers (one file per block, run inside write tasks) -------------------

def write_parquet_block(block, path_dir, block_idx, **kwargs):
    import pyarrow.parquet as pq
    from ray_tpu.data.block import BlockAccessor
    os.makedirs(path_dir, exist_ok=True)
    table = BlockAccessor.for_block(block).to_arrow()
    pq.write_table(table,
                   os.path.join(path_dir, f"part-{block_idx:05d}.parquet"),
                   **kwargs)


def write_csv_block(block, path_dir, block_idx, **kwargs):
    from pyarrow import csv as pacsv
    from ray_tpu.data.block import BlockAccessor
    os.makedirs(path_dir, exist_ok=True)
    table = BlockAccessor.for_block(block).to_arrow()
    pacsv.write_csv(table,
                    os.path.join(path_dir, f"part-{block_idx:05d}.csv"))


def write_json_block(block, path_dir, block_idx, **kwargs):
    from ray_tpu.data.block import BlockAccessor
    os.makedirs(path_dir, exist_ok=True)
    df = BlockAccessor.for_block(block).to_pandas()
    df.to_json(os.path.join(path_dir, f"part-{block_idx:05d}.json"),
               orient="records", lines=True)


def write_tfrecords_block(block, path_dir, block_idx, **kwargs):
    """One Example per row; numeric columns become float/int64 lists,
    bytes/str become bytes lists (reference: write_tfrecords)."""
    from ray_tpu._private.tfrecord import encode_example, write_record
    from ray_tpu.data.block import BlockAccessor
    acc = BlockAccessor.for_block(block)
    os.makedirs(path_dir, exist_ok=True)
    path = os.path.join(path_dir, f"part-{block_idx:05d}.tfrecords")
    with open(path, "wb") as f:
        for row in acc.iter_rows():
            write_record(f, encode_example(dict(row)))
    return path


def write_numpy_block(block, path_dir, block_idx, column="data", **kwargs):
    from ray_tpu.data.block import BlockAccessor
    os.makedirs(path_dir, exist_ok=True)
    cols = BlockAccessor.for_block(block).to_numpy()
    np.save(os.path.join(path_dir, f"part-{block_idx:05d}.npy"),
            cols[column])
