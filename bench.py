"""Flagship benchmark: GPT train throughput, streaming fresh host batches
through the overlapped training loop (ray_tpu/train/loop.py).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
"checkpoint_overhead_pct", "mfu", "step_breakdown" (host step-time
shares from TrainLoop.last_breakdown: prefetch / dispatch / metrics /
checkpoint / publish) and "retraces_unexpected" (retrace-sentinel
violations of the fused dispatch's compile-once pin — must be 0).

Methodology (changed in PR 2): earlier rounds re-dispatched one jitted
step per Python iteration on a single pre-sharded device batch, so the
number excluded host→device transfer and dispatch overhead. The loop now
generates a FRESH host batch every step and streams it through the
double-buffered prefetcher with fused multi-step dispatch, so tokens/s is
an honest end-to-end figure — host feed, transfer, dispatch, compute and
the (ring-buffered, every-K-steps) metrics fetch all inside the timed
region. The overlap work keeps it at or above the r5 fixed-batch number
(61.6k tok/s on v5e).

Knobs (env vars, platform-tuned defaults below):
  RAY_TPU_BENCH_ACCUM     gradient-accumulation microbatches per step
                          (spmd.make_train_step(accum=k); k splits the
                          batch, so tokens/step is unchanged)
  RAY_TPU_BENCH_UNROLL    steps fused into one jitted dispatch
                          (loop.TrainLoop(unroll=u))
  RAY_TPU_BENCH_PREFETCH  host→device transfers kept in flight
                          (loop.DevicePrefetcher(depth=d))
  RAY_TPU_BENCH_INTERVAL  steps between host metric fetches
                          (loop.MetricsRing(interval=K))
  RAY_TPU_BENCH_BATCH / RAY_TPU_BENCH_STEPS  shape of the timed region
  RAY_TPU_BENCH_CKPT_EVERY  async-snapshot cadence for the
                          checkpoint-overhead region (ft.AsyncCheckpointer)

The reference publishes no committed throughput numbers (BASELINE.md —
"harness only"); its north star is "ResNet-50 / GPT wall-clock at >= NCCL
DDP parity". DDP-over-NCCL training of dense transformers lands at ~40% MFU
on A100-class setups, so `vs_baseline` reports measured MFU / 0.40: >= 1.0
means the TPU path beats the reference's realistic efficiency envelope.
"""

from __future__ import annotations

import json
import os
import time

import jax
import numpy as np

# bf16 peak FLOPs per chip by device kind (jax device_kind substrings).
_PEAK_FLOPS = (
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),   # v5 litepod
    ("v5", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)
_BASELINE_MFU = 0.40


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_FLOPS:
        if key in kind:
            return val
    return 197e12


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def main():
    from ray_tpu.models import gpt
    from ray_tpu.parallel import MeshSpec
    from ray_tpu.train import loop, spmd

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    if on_tpu:
        cfg = gpt.GPTConfig(vocab_size=50304, d_model=1024, n_layers=12,
                            n_heads=16, d_ff=4096, max_seq_len=1024,
                            attn_impl="flash", logits_dtype="bfloat16",
                            remat_policy="dots", loss_impl="fused")
        # bf16 unembed output (loss upcasts before logsumexp): halves
        # the HBM traffic of the biggest activation; measured +2.3%
        # tok/s on v5e at loss parity to 3 decimals (57.6k -> 59.0k)
        # Batch swept on v5e: 8 -> 55.2k tok/s (0.468 MFU), 16 -> 58.4k
        # (0.495), 32 -> 58.5k (plateau; remat required above 8 anyway).
        # remat_policy swept on v5e at B=16 (r5): save-nothing 58.2k,
        # attn_out 58.0k, dots 61.6k (+5.8%, loss parity to 4 decimals).
        # loss_impl="fused" (ops/fused_xent.py) streams the unembed in
        # vocab chunks so the [B, T, V] logits tensor never exists; that
        # is what reopened B>16 (r5 runs B=24).
        # accum=1: B=24 fits, so accumulation is off on the bench; flip
        # RAY_TPU_BENCH_ACCUM to trade peak activations for scan steps
        # when sweeping B beyond HBM. unroll=4 amortizes one Python
        # dispatch over 4 steps; prefetch=2 double-buffers the host feed.
        batch_size, steps, warmup = 24, 20, 4
        accum, unroll, prefetch, interval = 1, 4, 2, 10
    else:   # CPU smoke mode so the benchmark is runnable anywhere.
        # Exercises the full overlap path end-to-end: fused loss (scan
        # fallback), accum=2 microbatching, unroll=2 fused dispatch,
        # depth-2 prefetch, ring-buffered metrics. XLA:CPU compile of the
        # nested scans dominates wall-clock, so the model is as small as
        # the path allows — the number only matters on silicon.
        cfg = gpt.small(loss_impl="fused", n_layers=1, max_seq_len=64,
                        d_model=64, d_ff=256, n_heads=2, vocab_size=256)
        steps, warmup = 8, 2
        accum, unroll, prefetch, interval = 2, 2, 2, 4
        # microbatches shard over the data axes, so the batch must hold
        # accum * n_devices rows (tests force an 8-device CPU mesh)
        grain = accum * len(devices)
        batch_size = grain * max(1, 4 // grain)

    batch_size = _env_int("RAY_TPU_BENCH_BATCH", batch_size)
    steps = _env_int("RAY_TPU_BENCH_STEPS", steps)
    accum = _env_int("RAY_TPU_BENCH_ACCUM", accum)
    unroll = _env_int("RAY_TPU_BENCH_UNROLL", unroll)
    prefetch = _env_int("RAY_TPU_BENCH_PREFETCH", prefetch)
    interval = _env_int("RAY_TPU_BENCH_INTERVAL", interval)
    warmup = max(unroll * ((warmup + unroll - 1) // unroll), unroll)
    steps = max(unroll * (steps // unroll), unroll)

    mesh = MeshSpec(data=-1).build(devices)
    state, step_fn, _ = spmd.make_gpt_trainer(cfg, mesh, accum=accum)

    # Fresh host batch every step — the data plane the loop must hide.
    def host_batches():
        rng = np.random.default_rng(0)
        while True:
            toks = rng.integers(0, cfg.vocab_size,
                                (batch_size, cfg.max_seq_len + 1),
                                np.int32)
            yield {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    place = loop.make_placer(mesh, stacked=unroll > 1)
    batches = loop.DevicePrefetcher(host_batches(), place,
                                    depth=prefetch, group=unroll)
    tokens_per_step = batch_size * cfg.max_seq_len
    flops_tok = spmd.train_flops_per_token(cfg, cfg.max_seq_len)
    train = loop.TrainLoop(step_fn, unroll=unroll,
                           metrics_interval=interval,
                           flops_per_step=flops_tok * tokens_per_step)

    # Warmup compiles the fused dispatch and fills the prefetch ring;
    # drain() inside run() blocks until the device finishes, so the
    # timed region starts on an idle device with transfers in flight.
    state, metrics = train.run(state, batches, num_steps=warmup)
    assert np.isfinite(metrics[-1]["loss"])

    t0 = time.perf_counter()
    state, metrics = train.run(state, batches, num_steps=steps)
    # run() already drained the ring (a device_get of every pending
    # dispatch), so execution — not just dispatch — is inside dt.
    dt = time.perf_counter() - t0
    assert np.isfinite(metrics[-1]["loss"])

    # Checkpoint-overhead region: the SAME compiled loop reruns with an
    # async checkpointer attached (device-side copies + background
    # host fetch/commit — train/ft.py), so the delta vs the clean region
    # is exactly what fault tolerance costs per step, end-of-run flush
    # included.
    import shutil
    import tempfile

    from ray_tpu.train import ft

    ckpt_every = _env_int("RAY_TPU_BENCH_CKPT_EVERY",
                          max(unroll, steps // 2))
    ckpt_dir = tempfile.mkdtemp(prefix="ray_tpu_bench_ckpt_")
    try:
        ckpt = ft.AsyncCheckpointer(ckpt_dir, every=ckpt_every,
                                    max_in_flight=2, keep=1)
        train.checkpointer = ckpt
        t0 = time.perf_counter()
        state, metrics = train.run(state, batches, num_steps=steps)
        dt_ckpt = time.perf_counter() - t0
        train.checkpointer = None
        assert np.isfinite(metrics[-1]["loss"])
        assert ckpt.commits > 0, "checkpoint region committed nothing"
        ckpt.close()
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    checkpoint_overhead_pct = (dt_ckpt - dt) / dt * 100.0
    # Step-time breakdown from the checkpoint region — the run where all
    # the host activities the loop is supposed to hide (data feed,
    # metrics plumbing, checkpoint snapshots) are actually live.
    bd = train.last_breakdown
    step_breakdown = {
        k: round(bd.get(f"{k}_share", 0.0), 4)
        for k in ("prefetch", "dispatch", "metrics", "checkpoint",
                  "publish")}

    tok_s = tokens_per_step * steps / dt
    mfu = tok_s * flops_tok / (peak_flops(devices[0]) * len(devices))
    vs_baseline = mfu / _BASELINE_MFU if on_tpu else 0.0

    print(json.dumps({
        "metric": "gpt_train_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 3),
        "checkpoint_overhead_pct": round(checkpoint_overhead_pct, 2),
        "mfu": round(mfu, 4),
        "step_breakdown": step_breakdown,
        "retraces_unexpected": train.sentinel.retraces_unexpected,
    }))


if __name__ == "__main__":
    main()
