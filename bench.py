"""Flagship benchmark: GPT train-step throughput on the local chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no committed throughput numbers (BASELINE.md —
"harness only"); its north star is "ResNet-50 / GPT wall-clock at >= NCCL
DDP parity". DDP-over-NCCL training of dense transformers lands at ~40% MFU
on A100-class setups, so `vs_baseline` reports measured MFU / 0.40: >= 1.0
means the TPU path beats the reference's realistic efficiency envelope.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

# bf16 peak FLOPs per chip by device kind (jax device_kind substrings).
_PEAK_FLOPS = (
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),   # v5 litepod
    ("v5", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)
_BASELINE_MFU = 0.40


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_FLOPS:
        if key in kind:
            return val
    return 197e12


def main():
    from ray_tpu.models import gpt
    from ray_tpu.parallel import MeshSpec
    from ray_tpu.train import spmd

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = gpt.GPTConfig(vocab_size=50304, d_model=1024, n_layers=12,
                            n_heads=16, d_ff=4096, max_seq_len=1024,
                            attn_impl="flash", logits_dtype="bfloat16",
                            remat_policy="dots", loss_impl="fused")
        # bf16 unembed output (loss upcasts before logsumexp): halves
        # the HBM traffic of the biggest activation; measured +2.3%
        # tok/s on v5e at loss parity to 3 decimals (57.6k -> 59.0k)
        # Batch swept on v5e: 8 -> 55.2k tok/s (0.468 MFU), 16 -> 58.4k
        # (0.495), 32 -> 58.5k (plateau; remat required above 8 anyway).
        # remat_policy swept on v5e at B=16 (r5): save-nothing 58.2k,
        # attn_out 58.0k, dots 61.6k (+5.8%, loss parity to 4 decimals)
        # — saving matmul outputs lets backward skip re-running the
        # einsums AND the flash-fwd residual recompute; B=24/32 with
        # dots previously exceeded what the compiler would schedule
        # (remote compile OOM): the [B, T, V] logits tensor plus its
        # backward was the peak.
        # loss_impl="fused" (ops/fused_xent.py) removes that tensor —
        # the loss streams the unembed in vocab chunks, peak loss
        # activation O(B*T*chunk) — which is exactly what the B>16
        # compile OOM was made of, so the batch sweep reopens above 16.
        # B=24 is the conservative middle of the newly-compilable range;
        # re-sweep 24/32 on silicon and record here.
        batch_size, steps, warmup = 24, 20, 3
    else:   # CPU smoke mode so the benchmark is runnable anywhere.
        # Runs the fused loss end-to-end too (scan path: the pure-JAX
        # lax.scan blockwise fallback — same custom_vjp, no Pallas).
        cfg = gpt.small(loss_impl="fused")
        batch_size, steps, warmup = 4, 5, 1

    devices = jax.devices()
    mesh = MeshSpec(data=-1).build(devices)
    state, step_fn, shard_tokens = spmd.make_gpt_trainer(cfg, mesh)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size,
                          (batch_size, cfg.max_seq_len + 1), np.int32)
    batch = shard_tokens({"inputs": tokens[:, :-1].copy(),
                          "targets": tokens[:, 1:].copy()})

    for _ in range(warmup):
        state, metrics = step_fn(state, batch)
    # device_get (not just block_until_ready) so remote-tunnel backends
    # can't report completion before execution finishes.
    float(jax.device_get(metrics["loss"]))

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
    float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0

    tokens_per_step = batch_size * cfg.max_seq_len
    tok_s = tokens_per_step * steps / dt
    flops_tok = spmd.train_flops_per_token(cfg, cfg.max_seq_len)
    mfu = tok_s * flops_tok / (peak_flops(devices[0]) * len(devices))
    vs_baseline = mfu / _BASELINE_MFU if on_tpu else 0.0

    print(json.dumps({
        "metric": "gpt_train_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 3),
    }))


if __name__ == "__main__":
    main()
