"""Inference benchmark: autoregressive decode throughput through the
continuous-batching engine (ray_tpu/serve/engine.py).

Prints ONE JSON line. Headline fields follow bench.py's contract
({"metric", "value", "unit", "vs_baseline"}); the inference-specific
extras ride alongside:

  prefill_tokens_per_sec   prompt tokens absorbed per second (chunked
                           prefill, cache write included)
  decode_tokens_per_sec    generated tokens per second across all slots
                           (the headline `value`)
  p50_token_latency_ms     per-decode-step wall latency percentiles —
  p99_token_latency_ms     each step emits one token per resident slot,
                           so this IS per-token latency for a stream
  slot_occupancy           mean fraction of cache slots resident over
                           the timed region (continuous batching's job
                           is to keep this near 1.0)
  prefix_hit_rate          fraction of prompt tokens served from the
                           radix prefix cache instead of prefilled
  cache_block_utilization  mean fraction of the paged pool's blocks
                           live during the timed region
  max_admission_stall_ms   the longest a decode step waited on that
                           tick's admission work (chunked prefill is
                           supposed to bound this to one chunk)
  weight_swap_ms           in-place weight hot-swap latency: the
                           update_params call to the first post-swap
                           token, with the trace counters asserted
                           unchanged (no recompile)
  rollout_tok_s            rl.EngineSampler trajectory-generation rate
                           through the warm engine (tokens/s)
  ttft_ms_p50 / _p99       submit-to-first-token percentiles over the
                           timed region (the flight recorder's TTFT)
  retraces_unexpected      retrace-sentinel violations of the pinned
                           compile-once paths (must be 0 in a bench)
  trace_overhead_pct       flight-recorder cost: wall-time delta of the
                           same workload with per-request tracing
                           sampled at 1.0 vs 0.0. Only measured when
                           RAY_TPU_INFER_BENCH_TRACE_OVERHEAD=1 (it
                           doubles the run); 0.0 otherwise
  priority_mix             the PRIORITY_MIX knob this run used ("" off)
  preemptions              streams evicted for a higher class during
                           the priority phase (0 when the mix is unset)
  reprefill_blocks         resume blocks re-prefilled that the radix
                           cache did not cover
  queue_wait_ms_p99_by_class  per-class p99 submit-to-first-token (ms),
                           keyed by class id ({} when the mix is unset)
  disagg_decode_tpot_ms_p99 / colocated_decode_tpot_ms_p99
                           client-observed inter-token gap p99 for the
                           decode streams of the disagg A/B phase,
                           role-split vs colocated — the disagg
                           headline: the role-split number stays flat
                           under long-prefill interference while the
                           colocated one absorbs whole prefill chunks
                           between decode ticks
  disagg_ttft_ms_p99 / colocated_ttft_ms_p99
                           submit-to-first-token p99 for those streams
                           (the disagg side includes the KV handoff)
  kv_transfer_gbps         KV-block handoff bandwidth, export blob to
                           imported pool blocks (GB/s, import wall)
  kv_blocks_streamed       paged KV blocks shipped prefill -> decode
  kv_dtype / weight_dtype  the quantization knobs this run used
  pool_bytes               device bytes of the preallocated KV block
                           pool(s), scale arrays included
  capacity_streams_per_gb  concurrent mean-context streams one GiB of
                           pool budget holds (1 GiB / kv_bytes_per_token
                           / mean context) — the capacity lever
                           kv_dtype="int8" pulls
  capacity_vs_f32          kv-bytes-per-token ratio vs a full-precision
                           f32 pool of the same geometry (2.0 for the
                           default bf16 pool, >3x for int8+scales)
  quality_logprob_delta    quantization quality proxy: mean |per-token
                           greedy logprob delta| vs an f32-pool f32-
                           weight engine on the same prompts (0.0 when
                           nothing is quantized — nothing to compare)

Knobs (env vars, platform-tuned defaults in main()):
  RAY_TPU_INFER_BENCH_SLOTS          resident decode slots (cache batch)
  RAY_TPU_INFER_BENCH_MAX_LEN        per-request cache capacity
  RAY_TPU_INFER_BENCH_PROMPT        prompt length per request
  RAY_TPU_INFER_BENCH_NEW            generated tokens per request
  RAY_TPU_INFER_BENCH_REQUESTS       total requests in the timed region
  RAY_TPU_INFER_BENCH_BLOCK          paged-cache block size
  RAY_TPU_INFER_BENCH_CHUNK          prefill chunk budget (tokens/tick)
  RAY_TPU_INFER_BENCH_SHARED_PREFIX  tokens of system prompt shared by
                                     every request (0 = fully random);
                                     exercises radix sharing
  RAY_TPU_INFER_BENCH_RAGGED         1 = ragged prompt lengths, drawn
                                     uniformly from [PROMPT/2, PROMPT]
  RAY_TPU_INFER_BENCH_SPEC           "" (off) | "ngram" | "draft":
                                     speculative decoding backend. When
                                     set, prompts switch to a repeated-
                                     motif workload (the case n-gram
                                     lookahead exists for), a second
                                     spec-enabled engine runs the same
                                     traffic, and the JSON reports
                                     acceptance_rate / tokens_per_step /
                                     spec_decode_tok_s alongside the
                                     unchanged baseline headline
  RAY_TPU_INFER_BENCH_SPEC_K         speculated tokens per step (k)
  RAY_TPU_INFER_BENCH_DRAFT_LAYERS   draft model depth for SPEC=draft
  RAY_TPU_INFER_BENCH_KV_DTYPE       "f32" | "int8": paged KV pool
                                     element type (int8 = per-row-scale
                                     quantized pool, models/gpt.py)
  RAY_TPU_INFER_BENCH_WEIGHT_DTYPE   "f32" | "int8": weight-only decode
                                     matmul precision
  RAY_TPU_INFER_BENCH_PRIORITY_MIX   comma-separated per-class request
                                     counts, lowest class first (e.g.
                                     "3,0,1" = 3 class-0 + 1 class-2).
                                     When set, an extra phase runs the
                                     mix through a priority-enabled
                                     engine — the low classes admitted
                                     and decoding first, the high wave
                                     arriving into a loaded pool — and
                                     the JSON gains `priority_mix`,
                                     `preemptions`, `reprefill_blocks`,
                                     and `queue_wait_ms_p99_by_class`
                                     (all neutral when unset)
  RAY_TPU_INFER_BENCH_CACHE_BLOCKS   paged-pool size for the priority
                                     phase (0 = engine default); size it
                                     below the mix's total footprint to
                                     force block-pressure preemption
  RAY_TPU_INFER_BENCH_DISAGG         1 (default) = run the disaggregated
                                     prefill/decode A/B: the same mixed
                                     workload (decode streams + long-
                                     prefill interference) through equal
                                     engine counts colocated vs role-
                                     split, reporting client-observed
                                     decode TPOT/TTFT p99 per mode plus
                                     kv_transfer_gbps for the KV-block
                                     handoffs; 0 = skip (zeros in JSON)
  RAY_TPU_INFER_BENCH_PREFILL_REPLICAS  prefill-role engines in the A/B
  RAY_TPU_INFER_BENCH_DECODE_REPLICAS   decode-role engines in the A/B

Baseline: single-token decode is HBM-bandwidth-bound — every step
streams the full parameter set plus the live KV prefix through the chip
regardless of batch. `vs_baseline` is measured decode tokens/s divided
by the bandwidth-roofline tokens/s (params + mean live cache bytes per
step, slots tokens per step, chip HBM bandwidth from the table below):
1.0 means decode runs at memory speed; the gap is dispatch + compute +
unfused overhead. CPU smoke reports 0.0, as in bench.py.
"""

from __future__ import annotations

import json
import os
import time

import jax
import numpy as np

# HBM bandwidth per chip, bytes/s, by device kind substring (same probe
# idiom as bench.py's _PEAK_FLOPS).
_HBM_BW = (
    ("v6", 1638e9),
    ("v5p", 2765e9),
    ("v5e", 819e9),
    ("v5", 819e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)


def hbm_bandwidth(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _HBM_BW:
        if key in kind:
            return val
    return 819e9


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def decode_roofline_tokens_per_sec(cfg, slots: int, mean_ctx: float,
                                   device) -> float:
    """Bandwidth-bound decode ceiling: one step reads all params once
    plus each slot's live K/V prefix, and emits `slots` tokens.

    Quantization rescales the denominator — that is the whole point of
    the int8 paths: `weight_dtype="int8"` reads the layer matmuls at 1
    byte/param (embed/norms stay full precision), and `kv_dtype="int8"`
    reads each cached position at H*(Dh + 4) bytes per K or V row (int8
    payload + one f32 scale per (position, head)) instead of
    H*Dh*bpe."""
    # param count straight from config (no tracing needed):
    d, f, v, L = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_layers
    matmul_params = L * (4 * d * d + 3 * d * f)
    full_params = v * d + cfg.max_seq_len * d + d + L * 2 * d
    bpe = 2 if "bfloat16" in cfg.dtype else 4
    w_bpe = 1 if cfg.weight_dtype == "int8" else bpe
    if cfg.kv_dtype == "int8":
        kv_row = cfg.n_heads * (cfg.head_dim + 4)
    else:
        kv_row = cfg.n_heads * cfg.head_dim * bpe
    kv_bytes = slots * mean_ctx * 2 * kv_row
    bytes_per_step = (full_params * bpe + matmul_params * w_bpe
                      + kv_bytes)
    return hbm_bandwidth(device) * slots / bytes_per_step


def main():
    from ray_tpu.models import gpt
    from ray_tpu.serve.engine import InferenceEngine

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    if on_tpu:
        cfg = gpt.GPTConfig(vocab_size=50304, d_model=1024, n_layers=12,
                            n_heads=16, d_ff=4096, max_seq_len=1024)
        slots, max_len, prompt_len, new_tokens, requests = \
            8, 1024, 128, 128, 32
    else:   # CPU smoke mode — the full engine path on a toy model.
        cfg = gpt.small(n_layers=1, max_seq_len=64, d_model=64,
                        d_ff=256, n_heads=2, vocab_size=256)
        slots, max_len, prompt_len, new_tokens, requests = 2, 32, 6, 4, 4

    slots = _env_int("RAY_TPU_INFER_BENCH_SLOTS", slots)
    max_len = _env_int("RAY_TPU_INFER_BENCH_MAX_LEN", max_len)
    prompt_len = _env_int("RAY_TPU_INFER_BENCH_PROMPT", prompt_len)
    new_tokens = _env_int("RAY_TPU_INFER_BENCH_NEW", new_tokens)
    requests = _env_int("RAY_TPU_INFER_BENCH_REQUESTS", requests)
    block_size = _env_int("RAY_TPU_INFER_BENCH_BLOCK", 16)
    chunk = _env_int("RAY_TPU_INFER_BENCH_CHUNK", 0)
    shared_prefix = _env_int("RAY_TPU_INFER_BENCH_SHARED_PREFIX", 0)
    ragged = _env_int("RAY_TPU_INFER_BENCH_RAGGED", 0)
    spec = os.environ.get("RAY_TPU_INFER_BENCH_SPEC", "")
    spec_k = _env_int("RAY_TPU_INFER_BENCH_SPEC_K", 4)
    draft_layers = _env_int("RAY_TPU_INFER_BENCH_DRAFT_LAYERS", 1)
    kv_dtype = os.environ.get("RAY_TPU_INFER_BENCH_KV_DTYPE", "f32")
    weight_dtype = os.environ.get(
        "RAY_TPU_INFER_BENCH_WEIGHT_DTYPE", "f32")
    if kv_dtype != "f32" or weight_dtype != "f32":
        import dataclasses
        cfg = dataclasses.replace(cfg, kv_dtype=kv_dtype,
                                  weight_dtype=weight_dtype)
    if spec not in ("", "ngram", "draft"):
        raise SystemExit("SPEC must be '', 'ngram' or 'draft'")
    if prompt_len + new_tokens > max_len:
        raise SystemExit("PROMPT + NEW must fit in MAX_LEN")
    if shared_prefix >= prompt_len:
        raise SystemExit("SHARED_PREFIX must be < PROMPT")

    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    system_prompt = rng.integers(0, cfg.vocab_size, shared_prefix)

    if spec:
        # Repeated-suffix workload: each prompt tiles a short motif, so
        # the request's own history predicts its continuation — the
        # regime n-gram lookahead (and cheap drafting) pays off in.
        def make_prompt():
            motif = rng.integers(0, cfg.vocab_size, 4)
            reps = -(-prompt_len // motif.size)
            return np.tile(motif, reps)[:prompt_len].astype(np.int32)
    else:
        def make_prompt():
            p = prompt_len
            if ragged:
                p = int(rng.integers(
                    max(prompt_len // 2, shared_prefix + 1),
                    prompt_len + 1))
            suffix = rng.integers(0, cfg.vocab_size, p - shared_prefix)
            return np.concatenate([system_prompt, suffix]) \
                .astype(np.int32)

    def run_engine(extra_kwargs):
        eng = InferenceEngine(params, cfg, slots=slots, max_len=max_len,
                              block_size=block_size,
                              prefill_chunk=chunk or None,
                              **extra_kwargs)
        # Warmup: compiles the prefill chunk buckets and the (single)
        # decode/verify executables, then drops compile time from the
        # accounting.
        for _ in range(min(requests, slots)):
            eng.submit(make_prompt(), max_new_tokens=new_tokens)
        eng.run_until_idle()
        eng.reset_stats()
        for _ in range(requests):
            eng.submit(make_prompt(), max_new_tokens=new_tokens)
        # Wall time of the timed region (not just attributed device
        # time): the flight recorder's per-token work happens between
        # device calls, so only wall time can see its overhead.
        t0 = time.perf_counter()
        eng.run_until_idle()
        wall = time.perf_counter() - t0
        return eng, eng.stats(), wall

    eng, s, _ = run_engine({})
    assert s["decode_traces"] == 1, "decode recompiled mid-bench"
    assert s["retraces_unexpected"] == 0, "retrace sentinel tripped"

    # --- flight-recorder overhead probe (opt-in: doubles the run) ------
    trace_overhead_pct = 0.0
    if _env_int("RAY_TPU_INFER_BENCH_TRACE_OVERHEAD", 0):
        _, _, wall_on = run_engine({"telemetry_sample": 1.0})
        _, _, wall_off = run_engine({"telemetry_sample": 0.0})
        trace_overhead_pct = ((wall_on - wall_off)
                              / max(wall_off, 1e-9) * 100.0)

    # --- quantization quality proxy ------------------------------------
    # Greedy-decode the same prompts through the (warm, pre-swap)
    # quantized engine and a fresh full-precision one, and report the
    # mean absolute per-token logprob drift — the pinned bound for
    # "int8 is tight-allclose to f32". 0.0 when nothing is quantized.
    quality_logprob_delta = 0.0
    if cfg.kv_dtype != "f32" or cfg.weight_dtype != "f32":
        import dataclasses
        fcfg = dataclasses.replace(cfg, kv_dtype="f32",
                                   weight_dtype="f32")
        feng = InferenceEngine(params, fcfg, slots=slots,
                               max_len=max_len, block_size=block_size,
                               prefill_chunk=chunk or None)
        deltas = []
        for p in [make_prompt() for _ in range(min(requests, slots))]:
            a = [t.logprob for t in
                 eng.generate(p, max_new_tokens=new_tokens)]
            b = [t.logprob for t in
                 feng.generate(p, max_new_tokens=new_tokens)]
            deltas.extend(abs(x - y) for x, y in zip(a, b))
        quality_logprob_delta = float(np.mean(deltas))

    # --- RL flywheel probe: in-place weight hot-swap + engine rollout --
    # Reuses the warm baseline engine: update_params must not retrigger
    # any compilation (trace counters pinned), weight_swap_ms runs from
    # the update_params call to the first post-swap token, and
    # rollout_tok_s is the EngineSampler's trajectory-generation rate.
    from ray_tpu.rl.sampler import EngineSampler
    sampler = EngineSampler(eng, max_new_tokens=new_tokens,
                            temperature=1.0)
    probe = [make_prompt() for _ in range(min(requests, slots))]
    # First swap warms the donated-copy executable (one compile, ever);
    # the second is the steady-state measurement.
    eng.update_params(gpt.init_params(jax.random.PRNGKey(2), cfg))
    sampler.rollout(probe)
    traces_before = (eng.decode_traces, eng.prefill_traces,
                     eng.swap_traces)
    eng.update_params(gpt.init_params(jax.random.PRNGKey(3), cfg))
    sampler.rollout(probe)
    assert (eng.decode_traces, eng.prefill_traces,
            eng.swap_traces) == traces_before, \
        "weight hot-swap retriggered compilation"
    swap_stats = eng.stats()
    assert swap_stats["swaps"] == 2 and swap_stats["params_version"] == 2
    weight_swap_ms = swap_stats["weight_swap_ms"]
    rollout_tok_s = sampler.last_rollout_tok_s

    # --- priority-mix phase: class contention under a tight pool -------
    priority_mix = os.environ.get("RAY_TPU_INFER_BENCH_PRIORITY_MIX", "")
    preemptions = reprefill_blocks = 0
    wait_p99_by_class: dict[str, float] = {}
    if priority_mix:
        mix = [int(x) for x in priority_mix.split(",")]
        cache_blocks = _env_int("RAY_TPU_INFER_BENCH_CACHE_BLOCKS", 0)
        pkw = {"priority_classes": max(len(mix), 2)}
        if cache_blocks:
            pkw["cache_blocks"] = cache_blocks
        peng = InferenceEngine(params, cfg, slots=slots, max_len=max_len,
                               block_size=block_size,
                               prefill_chunk=chunk or None, **pkw)
        # Low classes first, pumped until they hold blocks and decode —
        # so the higher waves land on a loaded pool and any preemption
        # is real block pressure, not queue ordering.
        for cls, n in enumerate(mix):
            for _ in range(n):
                peng.submit(make_prompt(), max_new_tokens=new_tokens,
                            priority=cls)
            for _ in range(200):
                if not peng._pending:
                    break
                peng.step()
        peng.run_until_idle()
        ps = peng.stats()
        preemptions = ps["preemptions"]
        reprefill_blocks = ps["reprefill_blocks"]
        wait_p99_by_class = {
            c: round(pc["queue_wait_ms_p99"], 3)
            for c, pc in ps["per_class"].items()}
        peng.check_invariants()

    # --- disaggregated prefill/decode A/B ------------------------------
    # Same mixed workload (decode streams + long-prefill interference)
    # through the same total engine count, split two ways. Colocated:
    # every engine takes both kinds of traffic, so each long prompt's
    # chunked prefill runs BETWEEN that engine's decode ticks and
    # stretches its streams' inter-token gaps. Disagg: prefill-role
    # engines absorb the long prompts and hand finished KV blocks to
    # decode-role engines, whose ticks stay pure decode. TPOT is
    # measured CLIENT-SIDE (inter-token arrival gaps at the consumer) —
    # the engine's own p99_token_latency_ms only times the decode device
    # call and cannot see prefill chunks sitting between ticks.
    disagg = _env_int("RAY_TPU_INFER_BENCH_DISAGG", 1)
    pre_n = _env_int("RAY_TPU_INFER_BENCH_PREFILL_REPLICAS", 1)
    dec_n = _env_int("RAY_TPU_INFER_BENCH_DECODE_REPLICAS", 1)
    disagg_tpot_p99 = coloc_tpot_p99 = 0.0
    disagg_ttft_p99 = coloc_ttft_p99 = 0.0
    kv_transfer_gbps = 0.0
    kv_blocks_streamed = 0
    if disagg:
        import threading

        total_engines = pre_n + dec_n
        n_streams = slots * dec_n
        n_long = max(2, requests)
        long_p = min(max_len - 2,
                     max(prompt_len * 4, prompt_len + 2 * block_size))

        def make_long():
            return rng.integers(0, cfg.vocab_size, long_p) \
                .astype(np.int32)

        def new_engine(role=None):
            ekw = {"role": role} if role else {}
            return InferenceEngine(params, cfg, slots=slots,
                                   max_len=max_len,
                                   block_size=block_size,
                                   prefill_chunk=chunk or None, **ekw)

        def drain(e, rid, recs, t_submit):
            ttft, gaps, last = None, [], t_submit
            for _tok in e.tokens_for(rid):
                now = time.perf_counter()
                if ttft is None:
                    ttft = (now - t_submit) * 1e3
                else:
                    gaps.append((now - last) * 1e3)
                last = now
            recs.append((ttft, gaps))

        def _p99(xs):
            return float(np.percentile(xs, 99)) if xs else 0.0

        def collect(recs):
            ttfts = [t for t, _ in recs if t is not None]
            gaps = [g for _, gs in recs for g in gs]
            return _p99(ttfts), _p99(gaps)

        # -- colocated baseline ----------------------------------------
        engines = [new_engine() for _ in range(total_engines)]
        for e in engines:       # warm both prompt-shape buckets
            e.generate(make_prompt(), max_new_tokens=2)
            e.generate(make_long(), max_new_tokens=1)
        stream_recs: list = []
        sink: list = []
        threads = []
        for i in range(n_streams):
            e = engines[i % total_engines]
            t0 = time.perf_counter()
            rid = e.submit(make_prompt(), max_new_tokens=new_tokens)
            th = threading.Thread(target=drain,
                                  args=(e, rid, stream_recs, t0),
                                  daemon=True)
            th.start()
            threads.append(th)
        time.sleep(0.05)        # let the streams reach steady decode
        for j in range(n_long):
            e = engines[j % total_engines]
            t0 = time.perf_counter()
            rid = e.submit(make_long(), max_new_tokens=1)
            th = threading.Thread(target=drain, args=(e, rid, sink, t0),
                                  daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=300)
        coloc_ttft_p99, coloc_tpot_p99 = collect(stream_recs)

        # -- disaggregated ---------------------------------------------
        pres = [new_engine("prefill") for _ in range(pre_n)]
        decs = [new_engine("decode") for _ in range(dec_n)]
        for k, de in enumerate(decs):   # warm prefill + import + decode
            pe = pres[k % pre_n]
            for mk, mn in ((make_long, 1), (make_prompt, 2)):
                blob = pe.handoff_for(
                    pe.submit(mk(), max_new_tokens=mn))
                list(de.tokens_for(de.import_handoff(blob)))
        stream_recs, sink, threads = [], [], []
        kv_bytes_streamed = 0
        import_wall = 0.0
        for i in range(n_streams):
            pe, de = pres[i % pre_n], decs[i % dec_n]
            t0 = time.perf_counter()
            rid = pe.submit(make_prompt(), max_new_tokens=new_tokens)
            blob = pe.handoff_for(rid)
            ti = time.perf_counter()
            drid = de.import_handoff(blob)
            import_wall += time.perf_counter() - ti
            kv_bytes_streamed += blob["kv_bytes"]
            kv_blocks_streamed += blob["n_blocks"]
            th = threading.Thread(target=drain,
                                  args=(de, drid, stream_recs, t0),
                                  daemon=True)
            th.start()
            threads.append(th)
        time.sleep(0.05)

        _kv_mu = threading.Lock()

        def long_disagg(pe, de, t0):
            nonlocal kv_bytes_streamed, kv_blocks_streamed, import_wall
            rid = pe.submit(make_long(), max_new_tokens=1)
            blob = pe.handoff_for(rid)
            ti = time.perf_counter()
            drid = de.import_handoff(blob)
            with _kv_mu:
                import_wall += time.perf_counter() - ti
                kv_bytes_streamed += blob["kv_bytes"]
                kv_blocks_streamed += blob["n_blocks"]
            drain(de, drid, sink, t0)

        for j in range(n_long):
            th = threading.Thread(
                target=long_disagg,
                args=(pres[j % pre_n], decs[j % dec_n],
                      time.perf_counter()),
                daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=300)
        disagg_ttft_p99, disagg_tpot_p99 = collect(stream_recs)
        kv_transfer_gbps = kv_bytes_streamed / max(import_wall,
                                                   1e-9) / 1e9
        for pe in pres:
            assert pe.stats()["decode_steps"] == 0, \
                "prefill engine decoded"
            pe.check_invariants()
        for de in decs:
            de.check_invariants()

    spec_stats = None
    if spec:
        ekw = {"spec": spec, "spec_k": spec_k}
        if spec == "draft":
            import dataclasses
            dcfg = dataclasses.replace(cfg, n_layers=draft_layers)
            ekw["draft_cfg"] = dcfg
            ekw["draft_params"] = gpt.init_params(
                jax.random.PRNGKey(1), dcfg)
        _, spec_stats, _ = run_engine(ekw)
        assert spec_stats["decode_traces"] <= 1, \
            "decode recompiled mid-bench"
        assert spec_stats["verify_traces"] == 1, \
            "verify recompiled mid-bench"

    prefill_tok_s = s["prefill_tokens"] / max(s["prefill_time_s"], 1e-9)
    decode_tok_s = s["decode_tokens"] / max(s["decode_time_s"], 1e-9)
    spec_decode_tok_s = (
        spec_stats["decode_tokens"] / max(spec_stats["decode_time_s"],
                                          1e-9)
        if spec_stats else 0.0)
    mean_ctx = prompt_len + new_tokens / 2
    vs_baseline = (decode_tok_s / decode_roofline_tokens_per_sec(
        cfg, slots, mean_ctx, devices[0])) if on_tpu else 0.0

    print(json.dumps({
        "metric": "gpt_decode_tokens_per_sec",
        "value": round(decode_tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 3),
        "prefill_tokens_per_sec": round(prefill_tok_s, 1),
        "decode_tokens_per_sec": round(decode_tok_s, 1),
        "p50_token_latency_ms": round(s["p50_token_latency_ms"], 3),
        "p99_token_latency_ms": round(s["p99_token_latency_ms"], 3),
        "slot_occupancy": round(s["slot_occupancy"], 3),
        "prefix_hit_rate": round(s["prefix_hit_rate"], 3),
        "cache_block_utilization": round(
            s["cache_block_utilization"], 3),
        "max_admission_stall_ms": round(
            s["max_admission_stall_ms"], 3),
        "block_size": s["block_size"],
        "cache_blocks": s["cache_blocks"],
        "shared_prefix": shared_prefix,
        # quantization / capacity
        "kv_dtype": cfg.kv_dtype,
        "weight_dtype": cfg.weight_dtype,
        "pool_bytes": s["pool_bytes"],
        "capacity_streams_per_gb": round(
            (1 << 30) / (s["kv_bytes_per_token"] * mean_ctx), 1),
        "capacity_vs_f32": round(
            (cfg.n_layers * 2 * cfg.n_heads * cfg.head_dim * 4)
            / s["kv_bytes_per_token"], 3),
        "quality_logprob_delta": round(quality_logprob_delta, 5),
        # speculative decoding (zeros / 1.0-neutral when SPEC is off)
        "spec": spec,
        "spec_k": spec_k if spec else 0,
        "acceptance_rate": round(
            spec_stats["acceptance_rate"] if spec_stats else 0.0, 3),
        "tokens_per_step": round(
            spec_stats["tokens_per_step"] if spec_stats
            else s["tokens_per_step"], 3),
        "spec_decode_tok_s": round(spec_decode_tok_s, 1),
        # RL flywheel probe
        "weight_swap_ms": round(weight_swap_ms, 3),
        "rollout_tok_s": round(rollout_tok_s, 1),
        # telemetry plane
        "ttft_ms_p50": round(s["ttft_ms_p50"], 3),
        "ttft_ms_p99": round(s["ttft_ms_p99"], 3),
        "retraces_unexpected": s["retraces_unexpected"],
        "trace_overhead_pct": round(trace_overhead_pct, 2),
        # priority/preemption phase (neutral when the mix is unset)
        "priority_mix": priority_mix,
        "preemptions": preemptions,
        "reprefill_blocks": reprefill_blocks,
        "queue_wait_ms_p99_by_class": wait_p99_by_class,
        # disaggregated prefill/decode A/B (zeros when DISAGG=0)
        "disagg": int(bool(disagg)),
        "disagg_prefill_replicas": pre_n if disagg else 0,
        "disagg_decode_replicas": dec_n if disagg else 0,
        "disagg_decode_tpot_ms_p99": round(disagg_tpot_p99, 3),
        "colocated_decode_tpot_ms_p99": round(coloc_tpot_p99, 3),
        "disagg_ttft_ms_p99": round(disagg_ttft_p99, 3),
        "colocated_ttft_ms_p99": round(coloc_ttft_p99, 3),
        "kv_transfer_gbps": round(kv_transfer_gbps, 4),
        "kv_blocks_streamed": kv_blocks_streamed,
    }))


if __name__ == "__main__":
    main()
