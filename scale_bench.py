"""Core-runtime scalability benchmark -> SCALE.json.

Counterpart of the reference's `python/ray/_private/ray_perf.py:93`
microbenchmark suites + the release scalability envelope
(`release/benchmarks/README.md:8-31`: 1M queued tasks, 10k concurrent,
40k actors, 1 GiB broadcast). Suites here measure the same axes at a
scale one machine can hold, and record the machine shape next to every
number so the envelope is honest:

  queued_tasks        submit 100k no-op tasks before draining any
  task_throughput     no-op tasks/s through the pool (warm workers)
  actor_creation      actor processes created/s (modest N; process-per-
                      actor on this box)
  actor_call_rate     pipelined method calls/s on one actor
  small_put_get       1 KiB put+get round trips/s
  store_bandwidth     25 MiB put+get GB/s through the shm arena
  broadcast_1gib      one 1 GiB object read by tasks on N daemon nodes

Run: python scale_bench.py [--queued 100000] [--actors 200] [--out SCALE.json]
The reference package is not installed in this container (zero-egress
image), so `ray_comparison` records the published envelope instead of a
same-container measurement.
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import time


def bench_queued_tasks(ray_tpu, n: int) -> dict:
    @ray_tpu.remote
    def nop():
        return None

    # warm one worker so drain isn't dominated by first-spawn
    ray_tpu.get(nop.remote())
    t0 = time.perf_counter()
    refs = [nop.remote() for _ in range(n)]
    t_submit = time.perf_counter() - t0
    t1 = time.perf_counter()
    ray_tpu.get(refs)
    t_drain = time.perf_counter() - t1
    # absorb the 100k-ObjectRef release storm HERE: the batched decref
    # flood (and the head's free processing) otherwise lands in the
    # middle of the next suite's window (the same isolation _settle
    # exists for)
    del refs
    ray_tpu.get(ray_tpu.put(1))
    time.sleep(3.0)
    return {
        "queued": n,
        "submit_per_s": round(n / t_submit, 1),
        "drain_per_s": round(n / t_drain, 1),
        # submit is now a pure enqueue (no inline dispatch when the
        # backlog is deep), so dispatch work that used to overlap the
        # submit window lands in the drain window; the end-to-end rate
        # is the number the two split views can't misrepresent
        "end_to_end_per_s": round(n / (t_submit + t_drain), 1),
        "submit_s": round(t_submit, 2),
        "drain_s": round(t_drain, 2),
    }


def bench_task_throughput(ray_tpu, n: int = 2000) -> dict:
    @ray_tpu.remote
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(20)])
    t0 = time.perf_counter()
    ray_tpu.get([nop.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    return {"tasks": n, "tasks_per_s": round(n / dt, 1)}


def _settle(ray_tpu, timeout: float = 120.0) -> None:
    """Wait until dying worker processes are reaped, so one suite's
    teardown storm (e.g. 200 actor exits) can't pollute the next
    suite's numbers on a small box."""
    client = ray_tpu._worker.get_client()
    deadline = time.time() + timeout
    while time.time() < deadline:
        workers = client.control("list_workers")
        if sum(1 for w in workers if w.get("alive")) <= 4:
            return
        time.sleep(0.5)


def bench_actor_creation(ray_tpu, n: int) -> dict:
    @ray_tpu.remote(num_cpus=0)
    class A:
        def ping(self):
            return 1

    t0 = time.perf_counter()
    actors = [A.remote() for _ in range(n)]
    ray_tpu.get([a.ping.remote() for a in actors])
    dt = time.perf_counter() - t0
    for a in actors:
        ray_tpu.kill(a)
    _settle(ray_tpu)
    return {"actors": n, "created_per_s": round(n / dt, 2),
            "total_s": round(dt, 1)}


def bench_actor_calls(ray_tpu, n: int = 2000) -> dict:
    @ray_tpu.remote(num_cpus=0)
    class Counter:
        def __init__(self):
            self.i = 0

        def inc(self):
            self.i += 1
            return self.i

    a = Counter.remote()
    ray_tpu.get(a.inc.remote())
    t0 = time.perf_counter()
    out = ray_tpu.get([a.inc.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    assert out[-1] == n + 1
    ray_tpu.kill(a)
    return {"calls": n, "calls_per_s": round(n / dt, 1)}


def bench_small_put_get(ray_tpu, n: int = 500) -> dict:
    import numpy as np
    arr = np.zeros(256, np.float32)   # 1 KiB
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(ray_tpu.put(arr))
    dt = time.perf_counter() - t0
    return {"round_trips": n, "per_s": round(n / dt, 1)}


def bench_store_bandwidth(ray_tpu, n: int = 40) -> dict:
    import numpy as np
    big = np.zeros(25_000_000 // 4, np.float32)   # 25 MiB
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(ray_tpu.put(big))
    dt = time.perf_counter() - t0
    return {"mib": 25, "reps": n,
            "gb_per_s": round(n * big.nbytes / dt / 1e9, 2)}


def bench_broadcast(ray_tpu, cluster, gib: float = 1.0,
                    n_nodes: int = 2) -> dict:
    import numpy as np
    node_ids = [cluster.add_node({"CPU": 1, f"bx{i}": 1})
                for i in range(n_nodes)]

    payload = np.ones(int(gib * (1 << 30) // 4), np.float32)

    @ray_tpu.remote
    def reduce_sum(a):
        return float(a[::4096].sum())

    def fanout():
        t_put0 = time.perf_counter()
        ref = ray_tpu.put(payload)
        t_put = time.perf_counter() - t_put0
        t0 = time.perf_counter()
        refs = [reduce_sum.options(resources={f"bx{i}": 1}).remote(ref)
                for i in range(n_nodes)]
        out = ray_tpu.get(refs, timeout=600)
        dt = time.perf_counter() - t0
        assert all(abs(v - out[0]) < 1e-3 for v in out)
        del refs, ref
        ray_tpu.get(ray_tpu.put(1))   # drain the decref batch promptly
        return t_put, dt

    # Steady state, not first touch: this box is a microVM with lazy
    # host memory — the FIRST write of any page costs a hypervisor
    # fault (~0.26 GB/s); recycled arena blocks run at memory speed.
    # A real cluster streams through warm, recycled blocks, so the
    # steady-state number is the framework's throughput and the cold
    # pass would measure the hypervisor. Two warm passes to converge.
    fanout()
    time.sleep(3)
    fanout()
    time.sleep(3)
    t_put, dt = fanout()
    for nid in node_ids:
        cluster.kill_node(nid)
    total_bytes = payload.nbytes * n_nodes
    return {"gib": gib, "nodes": n_nodes, "put_s": round(t_put, 2),
            "fanout_s": round(dt, 2),
            "aggregate_gb_per_s": round(total_bytes / dt / 1e9, 2)}


def bench_tracing_overhead(ray_tpu, n: int = 2000) -> dict:
    """Cost of the always-compiled-in tracing instrumentation with
    recording OFF, relative to the measured per-task latency. The task
    path has two disabled-path touch points (the submit-side TaskSpec
    stamp and the worker-side span check), each no more expensive than
    one full `span()` call; <1% of a no-op task is the contract."""
    from ray_tpu.util import tracing
    ns_per_call = tracing.probe_disabled_overhead_ns()

    @ray_tpu.remote
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(20)])
    t0 = time.perf_counter()
    ray_tpu.get([nop.remote() for _ in range(n)])
    task_ns = (time.perf_counter() - t0) / n * 1e9
    overhead_pct = 100.0 * 2 * ns_per_call / task_ns
    return {
        "span_disabled_ns": round(ns_per_call, 1),
        "task_ns": round(task_ns, 1),
        "overhead_pct": round(overhead_pct, 4),
        "under_1pct": bool(overhead_pct < 1.0),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--queued", type=int, default=100_000)
    ap.add_argument("--actors", type=int, default=200)
    ap.add_argument("--broadcast-gib", type=float, default=1.0)
    ap.add_argument("--broadcast-nodes", type=int, default=2)
    ap.add_argument("--out", default="SCALE.json")
    args = ap.parse_args()

    os.environ.setdefault("RAY_TPU_OBJECT_STORE_BYTES",
                          str(4 * (1 << 30)))   # 1 GiB payloads fit
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_resources={"CPU": max(4, os.cpu_count() or 1)})

    results = {}
    # queued_tasks runs LAST among the task suites: its 100k-ObjectRef
    # release storm drains for a long tail and was bleeding into the
    # suites measured after it
    results["task_throughput"] = bench_task_throughput(ray_tpu)
    results["actor_call_rate"] = bench_actor_calls(ray_tpu)
    results["actor_creation"] = bench_actor_creation(ray_tpu, args.actors)
    results["small_put_get"] = bench_small_put_get(ray_tpu)
    results["store_bandwidth"] = bench_store_bandwidth(ray_tpu)
    results["queued_tasks"] = bench_queued_tasks(ray_tpu, args.queued)
    _settle(ray_tpu)
    results["broadcast_1gib"] = bench_broadcast(
        ray_tpu, cluster, args.broadcast_gib, args.broadcast_nodes)
    results["tracing_overhead"] = bench_tracing_overhead(ray_tpu)

    # Per-stage control-plane attribution over everything this run
    # submitted (submit→queue→dispatch→execute→result_put→got): the
    # before/after ledger each scheduler-throughput PR is judged by.
    client = ray_tpu._worker.get_client()
    stage_breakdown = client.control("stage_breakdown")

    doc = {
        "machine": {
            "cpus": os.cpu_count(),
            "platform": platform.platform(),
        },
        "results": results,
        "stage_breakdown": stage_breakdown,
        "ray_comparison": {
            "same_container": None,
            "note": "reference ray package not installed in this "
                    "zero-egress container; published envelope for "
                    "context (release/benchmarks/README.md:8-31): 1M+ "
                    "tasks queued on one m4.16xlarge (64 cores), 10k+ "
                    "concurrent tasks / 40k+ actors on a 64-node "
                    "cluster, 1 GiB broadcast to 50+ nodes. This box "
                    "has 1 core; numbers above are per-core envelope "
                    "points, not cluster ceilings.",
        },
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps(doc["results"], indent=2))
    cluster.shutdown()


if __name__ == "__main__":
    main()
