"""Core-runtime scalability benchmark -> SCALE.json.

Counterpart of the reference's `python/ray/_private/ray_perf.py:93`
microbenchmark suites + the release scalability envelope
(`release/benchmarks/README.md:8-31`: 1M queued tasks, 10k concurrent,
40k actors, 1 GiB broadcast). Suites here measure the same axes at a
scale one machine can hold, and record the machine shape next to every
number so the envelope is honest:

  queued_tasks        submit 100k no-op tasks before draining any
  task_throughput     no-op tasks/s through the pool (warm workers)
  actor_creation      actor processes created/s (modest N; process-per-
                      actor on this box)
  actor_call_rate     pipelined method calls/s on one actor
  small_put_get       1 KiB put+get round trips/s
  store_bandwidth     25 MiB put+get GB/s through the shm arena
  broadcast_1gib      one 1 GiB object read by tasks on N daemon nodes

Run: python scale_bench.py [--queued 100000] [--actors 200] [--out SCALE.json]
The reference package is not installed in this container (zero-egress
image), so `ray_comparison` records the published envelope instead of a
same-container measurement.
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import time


def bench_queued_tasks(ray_tpu, n: int) -> dict:
    @ray_tpu.remote
    def nop():
        return None

    # warm one worker so drain isn't dominated by first-spawn
    ray_tpu.get(nop.remote())
    t0 = time.perf_counter()
    refs = [nop.remote() for _ in range(n)]
    t_submit = time.perf_counter() - t0
    t1 = time.perf_counter()
    ray_tpu.get(refs)
    t_drain = time.perf_counter() - t1
    # absorb the 100k-ObjectRef release storm HERE: the batched decref
    # flood (and the head's free processing) otherwise lands in the
    # middle of the next suite's window (the same isolation _settle
    # exists for)
    del refs
    ray_tpu.get(ray_tpu.put(1))
    time.sleep(3.0)
    return {
        "queued": n,
        "submit_per_s": round(n / t_submit, 1),
        "drain_per_s": round(n / t_drain, 1),
        # submit is now a pure enqueue (no inline dispatch when the
        # backlog is deep), so dispatch work that used to overlap the
        # submit window lands in the drain window; the end-to-end rate
        # is the number the two split views can't misrepresent
        "end_to_end_per_s": round(n / (t_submit + t_drain), 1),
        "submit_s": round(t_submit, 2),
        "drain_s": round(t_drain, 2),
    }


def bench_task_throughput(ray_tpu, n: int = 2000) -> dict:
    @ray_tpu.remote
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(20)])
    t0 = time.perf_counter()
    ray_tpu.get([nop.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    return {"tasks": n, "tasks_per_s": round(n / dt, 1)}


def _settle(ray_tpu, timeout: float = 120.0) -> None:
    """Wait until dying worker processes are reaped, so one suite's
    teardown storm (e.g. 200 actor exits) can't pollute the next
    suite's numbers on a small box."""
    client = ray_tpu._worker.get_client()
    deadline = time.time() + timeout
    while time.time() < deadline:
        workers = client.control("list_workers")
        if sum(1 for w in workers if w.get("alive")) <= 4:
            return
        time.sleep(0.5)


def bench_actor_creation(ray_tpu, n: int) -> dict:
    @ray_tpu.remote(num_cpus=0)
    class A:
        def ping(self):
            return 1

    t0 = time.perf_counter()
    actors = [A.remote() for _ in range(n)]
    ray_tpu.get([a.ping.remote() for a in actors])
    dt = time.perf_counter() - t0
    for a in actors:
        ray_tpu.kill(a)
    _settle(ray_tpu)
    return {"actors": n, "created_per_s": round(n / dt, 2),
            "total_s": round(dt, 1)}


def bench_actor_calls(ray_tpu, n: int = 2000) -> dict:
    @ray_tpu.remote(num_cpus=0)
    class Counter:
        def __init__(self):
            self.i = 0

        def inc(self):
            self.i += 1
            return self.i

    a = Counter.remote()
    ray_tpu.get(a.inc.remote())
    t0 = time.perf_counter()
    out = ray_tpu.get([a.inc.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    assert out[-1] == n + 1
    ray_tpu.kill(a)
    return {"calls": n, "calls_per_s": round(n / dt, 1)}


def bench_small_put_get(ray_tpu, n: int = 500) -> dict:
    import numpy as np
    arr = np.zeros(256, np.float32)   # 1 KiB
    for _ in range(20):   # warm the path (same courtesy the task suites get)
        ray_tpu.get(ray_tpu.put(arr))
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(ray_tpu.put(arr))
    dt = time.perf_counter() - t0
    return {"round_trips": n, "per_s": round(n / dt, 1)}


def bench_small_put_get_zero_copy(ray_tpu, n: int = 300) -> dict:
    """The two small-object fast paths the zero-copy rework targets:
    1 KiB values ride inline in the descriptor (no store file at all);
    256 KiB values land in the shm arena and `get` must hand back an
    arena-backed read-only view, not an intermediate bytes copy."""
    import numpy as np
    small = np.zeros(256, np.float32)          # 1 KiB -> inline
    big = np.zeros(64 * 1024, np.float32)      # 256 KiB -> arena
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(ray_tpu.put(small))
    dt_small = time.perf_counter() - t0
    t1 = time.perf_counter()
    for _ in range(n):
        out = ray_tpu.get(ray_tpu.put(big))
    dt_big = time.perf_counter() - t1
    # zero-copy evidence: the array is a view over store memory (has a
    # base buffer and is read-only), not a freshly-owned copy
    zero_copy = bool(out.base is not None and not out.flags.writeable)
    return {
        "round_trips": n,
        "inline_1kib_per_s": round(n / dt_small, 1),
        "arena_256kib_per_s": round(n / dt_big, 1),
        "arena_gb_per_s": round(n * big.nbytes / dt_big / 1e9, 3),
        "arena_zero_copy_view": zero_copy,
    }


def parity_workload(n_tasks: int = 2000, n_puts: int = 200) -> dict:
    """One self-contained session: pipelined-submit n_tasks, drain, then
    n_puts put/get round trips — returning rates AND output digests so
    two runs with different channel settings can be checked for
    bit-identical results (batching must change timing, never values).
    Run via `scale_bench.py --parity-child N M` so the framing/pipeline
    env flags are construction-time fresh."""
    import hashlib

    import numpy as np

    import ray_tpu
    from ray_tpu._private import config

    ray_tpu.init(num_cpus=4)

    @ray_tpu.remote
    def affine(i):
        return i * 3 + 1

    ray_tpu.get(affine.remote(0))    # warm one worker
    t0 = time.perf_counter()
    refs = [affine.remote(i) for i in range(n_tasks)]
    t_submit = time.perf_counter() - t0
    t1 = time.perf_counter()
    out = ray_tpu.get(refs)
    t_drain = time.perf_counter() - t1

    arr = np.arange(256, dtype=np.float32)    # 1 KiB
    t2 = time.perf_counter()
    for _ in range(n_puts):
        got = ray_tpu.get(ray_tpu.put(arr))
    t_put = time.perf_counter() - t2
    digest = hashlib.sha256(np.asarray(got).tobytes()).hexdigest()
    doc = {
        "channel_batching": bool(config.get("CHANNEL_BATCHING")),
        "submit_pipeline": bool(config.get("SUBMIT_PIPELINE")),
        "tasks": n_tasks,
        "submit_per_s": round(n_tasks / t_submit, 1),
        "drain_per_s": round(n_tasks / t_drain, 1),
        "end_to_end_per_s": round(n_tasks / (t_submit + t_drain), 1),
        "put_get_per_s": round(n_puts / t_put, 1),
        # parity evidence: every task result and the round-tripped
        # object bytes, reduced to comparable values
        "task_checksum": sum(out),
        "object_digest": digest,
    }
    ray_tpu.shutdown()
    return doc


def bench_batched_vs_unbatched(n_tasks: int = 20_000,
                               n_puts: int = 500) -> dict:
    """Before/after envelope for the batched control plane: the same
    parity workload in two fresh processes — framing + pipelined
    submission ON (the default) vs the legacy per-message/per-ack wire
    — with output parity asserted, not assumed."""
    import subprocess
    import sys

    out = {}
    for label, flag in (("batched", "1"), ("unbatched", "0")):
        env = dict(os.environ,
                   RAY_TPU_CHANNEL_BATCHING=flag,
                   RAY_TPU_SUBMIT_PIPELINE=flag)
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--parity-child",
             str(n_tasks), str(n_puts)],
            env=env, capture_output=True, text=True, timeout=1200)
        if r.returncode != 0:
            raise RuntimeError(f"{label} parity child failed:\n"
                               f"{r.stdout}\n{r.stderr}")
        out[label] = json.loads(r.stdout.strip().splitlines()[-1])
    b, u = out["batched"], out["unbatched"]
    if (b["task_checksum"] != u["task_checksum"]
            or b["object_digest"] != u["object_digest"]):
        raise AssertionError(
            f"batching changed RESULTS, not just timing: {b} vs {u}")
    out["output_parity"] = True
    out["speedup_end_to_end"] = round(
        b["end_to_end_per_s"] / u["end_to_end_per_s"], 2)
    out["speedup_submit"] = round(b["submit_per_s"] / u["submit_per_s"], 2)
    out["speedup_put_get"] = round(b["put_get_per_s"] / u["put_get_per_s"],
                                   2)
    return out


def bench_store_bandwidth(ray_tpu, n: int = 40) -> dict:
    import numpy as np
    big = np.zeros(25_000_000 // 4, np.float32)   # 25 MiB
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(ray_tpu.put(big))
    dt = time.perf_counter() - t0
    return {"mib": 25, "reps": n,
            "gb_per_s": round(n * big.nbytes / dt / 1e9, 2)}


def bench_broadcast(ray_tpu, cluster, gib: float = 1.0,
                    n_nodes: int = 2) -> dict:
    import numpy as np
    node_ids = [cluster.add_node({"CPU": 1, f"bx{i}": 1})
                for i in range(n_nodes)]

    payload = np.ones(int(gib * (1 << 30) // 4), np.float32)

    @ray_tpu.remote
    def reduce_sum(a):
        return float(a[::4096].sum())

    def fanout():
        t_put0 = time.perf_counter()
        ref = ray_tpu.put(payload)
        t_put = time.perf_counter() - t_put0
        t0 = time.perf_counter()
        refs = [reduce_sum.options(resources={f"bx{i}": 1}).remote(ref)
                for i in range(n_nodes)]
        out = ray_tpu.get(refs, timeout=600)
        dt = time.perf_counter() - t0
        assert all(abs(v - out[0]) < 1e-3 for v in out)
        del refs, ref
        ray_tpu.get(ray_tpu.put(1))   # drain the decref batch promptly
        return t_put, dt

    # Steady state, not first touch: this box is a microVM with lazy
    # host memory — the FIRST write of any page costs a hypervisor
    # fault (~0.26 GB/s); recycled arena blocks run at memory speed.
    # A real cluster streams through warm, recycled blocks, so the
    # steady-state number is the framework's throughput and the cold
    # pass would measure the hypervisor. Two warm passes to converge.
    fanout()
    time.sleep(3)
    fanout()
    time.sleep(3)
    t_put, dt = fanout()
    for nid in node_ids:
        cluster.kill_node(nid)
    total_bytes = payload.nbytes * n_nodes
    return {"gib": gib, "nodes": n_nodes, "put_s": round(t_put, 2),
            "fanout_s": round(dt, 2),
            "aggregate_gb_per_s": round(total_bytes / dt / 1e9, 2)}


def bench_tracing_overhead(ray_tpu, n: int = 2000) -> dict:
    """Cost of the always-compiled-in tracing instrumentation with
    recording OFF, relative to the measured per-task latency. The task
    path has two disabled-path touch points (the submit-side TaskSpec
    stamp and the worker-side span check), each no more expensive than
    one full `span()` call; <1% of a no-op task is the contract."""
    from ray_tpu.util import tracing
    ns_per_call = tracing.probe_disabled_overhead_ns()

    @ray_tpu.remote
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(20)])
    t0 = time.perf_counter()
    ray_tpu.get([nop.remote() for _ in range(n)])
    task_ns = (time.perf_counter() - t0) / n * 1e9
    overhead_pct = 100.0 * 2 * ns_per_call / task_ns
    return {
        "span_disabled_ns": round(ns_per_call, 1),
        "task_ns": round(task_ns, 1),
        "overhead_pct": round(overhead_pct, 4),
        "under_1pct": bool(overhead_pct < 1.0),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--queued", type=int, default=100_000)
    ap.add_argument("--actors", type=int, default=200)
    ap.add_argument("--broadcast-gib", type=float, default=1.0)
    ap.add_argument("--broadcast-nodes", type=int, default=2)
    ap.add_argument("--out", default="SCALE.json")
    ap.add_argument("--parity-child", nargs=2, type=int, metavar=("N", "M"),
                    help="internal: run the parity workload (N tasks, M "
                         "put/gets) in THIS process and print JSON")
    args = ap.parse_args()

    if args.parity_child:
        print(json.dumps(parity_workload(*args.parity_child)))
        return

    os.environ.setdefault("RAY_TPU_OBJECT_STORE_BYTES",
                          str(4 * (1 << 30)))   # 1 GiB payloads fit
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_resources={"CPU": max(4, os.cpu_count() or 1)})

    results = {}
    # queued_tasks runs LAST among the task suites: its 100k-ObjectRef
    # release storm drains for a long tail and was bleeding into the
    # suites measured after it
    results["task_throughput"] = bench_task_throughput(ray_tpu)
    results["actor_call_rate"] = bench_actor_calls(ray_tpu)
    results["actor_creation"] = bench_actor_creation(ray_tpu, args.actors)
    results["small_put_get"] = bench_small_put_get(ray_tpu)
    results["small_put_get_zero_copy"] = bench_small_put_get_zero_copy(
        ray_tpu)
    results["store_bandwidth"] = bench_store_bandwidth(ray_tpu)
    results["queued_tasks"] = bench_queued_tasks(ray_tpu, args.queued)
    _settle(ray_tpu)
    results["broadcast_1gib"] = bench_broadcast(
        ray_tpu, cluster, args.broadcast_gib, args.broadcast_nodes)
    results["tracing_overhead"] = bench_tracing_overhead(ray_tpu)
    # last: spawns its own fresh sessions in subprocesses, so the
    # parent cluster must be idle while they run
    results["batched_vs_unbatched"] = bench_batched_vs_unbatched()

    # Per-stage control-plane attribution over everything this run
    # submitted (submit→queue→dispatch→execute→result_put→got): the
    # before/after ledger each scheduler-throughput PR is judged by.
    client = ray_tpu._worker.get_client()
    stage_breakdown = client.control("stage_breakdown")

    doc = {
        "machine": {
            "cpus": os.cpu_count(),
            "platform": platform.platform(),
            "variance_note": "single-run numbers on a shared-core "
                             "microVM: repeated full runs observed "
                             "±25% on queued_tasks and up to 4x on the "
                             "put/get suites — compare envelopes across "
                             "machine classes, not runs",
        },
        "results": results,
        "stage_breakdown": stage_breakdown,
        "ray_comparison": {
            "same_container": None,
            "note": "reference ray package not installed in this "
                    "zero-egress container; published envelope for "
                    "context (release/benchmarks/README.md:8-31): 1M+ "
                    "tasks queued on one m4.16xlarge (64 cores), 10k+ "
                    "concurrent tasks / 40k+ actors on a 64-node "
                    "cluster, 1 GiB broadcast to 50+ nodes. This box "
                    "has 1 core; numbers above are per-core envelope "
                    "points, not cluster ceilings.",
        },
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps(doc["results"], indent=2))
    cluster.shutdown()


if __name__ == "__main__":
    main()
