PY ?= python

.PHONY: lint test test-fast trace-smoke scale-smoke quant-smoke disagg-smoke

# Static invariant checks (R001-R005): exits non-zero on any
# non-waived finding. tests/test_graftlint.py::test_repo_is_clean runs
# the same sweep in tier-1, so CI cannot drift from this target.
lint:
	$(PY) -m ray_tpu.tools.graftlint ray_tpu/

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q

test-fast:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

# Distributed-tracing smoke: one trace_id across >=3 processes in the
# merged /api/timeline, for both entry paths (driver task chain and
# HTTP proxy -> replica).
trace-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_tracing_distributed.py \
		-q -k 'merged or proxy'

# Quantization CPU parity + JSON-contract subset: int8 KV token
# identity vs f32 (incl. COW / spec-decode), kernel dequant parity,
# fused-prefill parity, the quantized fuzz tier, and the bench fields
# (capacity_vs_f32, quality_logprob_delta) pinned end to end.
quant-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_paged_cache.py \
		tests/test_spec_decode.py tests/test_bench_infer_smoke.py \
		-q -m 'not slow' -k 'quant or Quant or FusedPrefill'

# Disaggregated prefill/decode smoke: token identity vs colocated
# across spec backends + int8, KV-block streaming over netaddr with
# transfer stats, cancel/failover block accounting, SLO admission,
# and streams-driven decode-pool autoscaling.
disagg-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_serve_disagg.py -q

# Trimmed scale_bench parity run: channel batching + pipelined
# submission ON vs OFF must produce bit-identical task results and
# object bytes (timing may differ, values may not).
scale-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_scale_smoke.py -q
