PY ?= python

.PHONY: lint test test-fast

# Static invariant checks (R001-R005): exits non-zero on any
# non-waived finding. tests/test_graftlint.py::test_repo_is_clean runs
# the same sweep in tier-1, so CI cannot drift from this target.
lint:
	$(PY) -m ray_tpu.tools.graftlint ray_tpu/

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q

test-fast:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'
