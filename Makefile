PY ?= python

.PHONY: lint test test-fast trace-smoke scale-smoke

# Static invariant checks (R001-R005): exits non-zero on any
# non-waived finding. tests/test_graftlint.py::test_repo_is_clean runs
# the same sweep in tier-1, so CI cannot drift from this target.
lint:
	$(PY) -m ray_tpu.tools.graftlint ray_tpu/

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q

test-fast:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

# Distributed-tracing smoke: one trace_id across >=3 processes in the
# merged /api/timeline, for both entry paths (driver task chain and
# HTTP proxy -> replica).
trace-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_tracing_distributed.py \
		-q -k 'merged or proxy'

# Trimmed scale_bench parity run: channel batching + pipelined
# submission ON vs OFF must produce bit-identical task results and
# object bytes (timing may differ, values may not).
scale-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_scale_smoke.py -q
