"""Actor tests, modeled on the reference's `python/ray/tests/test_actor.py`:
lifecycle, ordering, named actors, restarts, kill, concurrency."""

import time

import pytest

import ray_tpu
from ray_tpu.actor import wait_for_actor_ready
from ray_tpu.exceptions import ActorDiedError


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.x = start

    def incr(self, n=1):
        self.x += n
        return self.x

    def value(self):
        return self.x

    def crash(self):
        import os
        os._exit(1)


def test_actor_basic(ray_session):
    c = Counter.remote(10)
    assert ray_tpu.get(c.incr.remote()) == 11
    assert ray_tpu.get(c.incr.remote(5)) == 16
    assert ray_tpu.get(c.value.remote()) == 16


def test_actor_method_ordering(ray_session):
    c = Counter.remote(0)
    refs = [c.incr.remote() for _ in range(20)]
    assert ray_tpu.get(refs) == list(range(1, 21))


def test_actor_handle_passed_to_task(ray_session):
    c = Counter.remote(0)

    @ray_tpu.remote
    def bump(counter, n):
        return ray_tpu.get(counter.incr.remote(n))

    assert ray_tpu.get(bump.remote(c, 42)) == 42


def test_named_actor(ray_session):
    Counter.options(name="the-counter").remote(5)
    h = ray_tpu.get_actor("the-counter")
    assert ray_tpu.get(h.incr.remote()) == 6


def test_get_actor_missing(ray_session):
    with pytest.raises(ValueError):
        ray_tpu.get_actor("no-such-actor")


def test_duplicate_actor_name_rejected(ray_session):
    Counter.options(name="dup-name").remote()
    with pytest.raises(Exception):
        h = Counter.options(name="dup-name").remote()
        wait_for_actor_ready(h, timeout=30)


def test_actor_constructor_error(ray_session):
    @ray_tpu.remote
    class Bad:
        def __init__(self):
            raise RuntimeError("ctor fails")

        def ping(self):
            return "pong"

    b = Bad.remote()
    with pytest.raises((RuntimeError, ActorDiedError)):
        ray_tpu.get(b.ping.remote(), timeout=60)


def test_actor_death_fails_pending(ray_session):
    c = Counter.remote(0)
    assert ray_tpu.get(c.incr.remote()) == 1
    c.crash.remote()
    with pytest.raises(ActorDiedError):
        ray_tpu.get(c.value.remote(), timeout=60)


def test_actor_restart(ray_session):
    # max_task_retries stays 0 so the crashing call itself is NOT replayed
    # on the restarted instance (replaying it would crash-loop, same as the
    # reference).
    c = Counter.options(max_restarts=2).remote(0)
    assert ray_tpu.get(c.incr.remote()) == 1
    c.crash.remote()
    # After restart state resets to the constructor args (reference
    # semantics: restarted actors rerun __init__).
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            assert ray_tpu.get(c.incr.remote(), timeout=30) >= 1
            break
        except ActorDiedError:
            time.sleep(0.5)
    else:
        pytest.fail("actor never came back")


def test_kill_actor(ray_session):
    c = Counter.remote(0)
    assert ray_tpu.get(c.incr.remote()) == 1
    ray_tpu.kill(c)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(c.incr.remote(), timeout=60)


def test_actor_max_concurrency(ray_session):
    @ray_tpu.remote
    class Sleeper:
        def nap(self, t):
            time.sleep(t)
            return t

    s = Sleeper.options(max_concurrency=4).remote()
    t0 = time.time()
    refs = [s.nap.remote(1.0) for _ in range(4)]
    ray_tpu.get(refs, timeout=60)
    # 4 overlapping 1s naps should take well under 4s.
    assert time.time() - t0 < 3.5


def test_method_num_returns(ray_session):
    @ray_tpu.remote
    class Splitter:
        @ray_tpu.method(num_returns=2)
        def pair(self):
            return "a", "b"

    s = Splitter.remote()
    a, b = s.pair.remote()
    assert ray_tpu.get([a, b]) == ["a", "b"]


# ---------------------------------------------------------------------------
# asyncio actors (reference: async actor execution, _private/async_compat.py
# + async execute_task in _raylet.pyx — any `async def` method switches the
# actor onto a per-actor event loop with max_concurrency as a semaphore)
# ---------------------------------------------------------------------------

def test_async_actor_overlapping_awaits(ray_session):
    @ray_tpu.remote
    class Signal:
        def __init__(self):
            import asyncio
            self.event = asyncio.Event()

        async def wait(self):
            await self.event.wait()
            return "released"

        async def release(self):
            self.event.set()
            return True

    s = Signal.remote()
    # wait() blocks on an asyncio.Event only a SECOND concurrently
    # running method can set: deadlocks unless calls overlap on one loop
    r1 = s.wait.remote()
    time.sleep(0.3)
    r2 = s.release.remote()
    assert ray_tpu.get(r2, timeout=30) is True
    assert ray_tpu.get(r1, timeout=30) == "released"


def test_async_actor_default_high_concurrency(ray_session):
    @ray_tpu.remote
    class Napper:
        async def nap(self, i):
            import asyncio
            await asyncio.sleep(0.5)
            return i

    n = Napper.remote()
    t0 = time.time()
    out = ray_tpu.get([n.nap.remote(i) for i in range(20)], timeout=60)
    # async actors default to max_concurrency=1000: 20 naps overlap
    assert time.time() - t0 < 4.0
    assert sorted(out) == list(range(20))


def test_async_actor_semaphore_limit(ray_session):
    @ray_tpu.remote(max_concurrency=2)
    class Two:
        async def nap(self):
            import asyncio
            await asyncio.sleep(0.4)
            return 1

    t = Two.remote()
    t0 = time.time()
    ray_tpu.get([t.nap.remote() for _ in range(6)], timeout=60)
    dt = time.time() - t0
    # 6 naps through a 2-permit semaphore: 3 serialized waves
    assert dt > 1.0, f"semaphore not enforced ({dt:.2f}s)"


def test_async_actor_sync_methods_and_errors(ray_session):
    @ray_tpu.remote
    class Mixed:
        async def boom(self):
            raise ValueError("async boom")

        def plain(self):
            return "sync-ok"

    m = Mixed.remote()
    assert ray_tpu.get(m.plain.remote(), timeout=30) == "sync-ok"
    with pytest.raises(Exception, match="async boom"):
        ray_tpu.get(m.boom.remote(), timeout=30)


def test_failed_constructor_recycles_pooled_worker(ray_session):
    """A pooled worker converted into an actor host goes back to the
    pool when the user constructor raises — repeated creation failures
    must not strand healthy workers."""
    import ray_tpu
    from ray_tpu import exceptions as exc

    @ray_tpu.remote(num_cpus=0)
    class Broken:
        def __init__(self):
            raise RuntimeError("nope")

        def ping(self):
            return 1

    @ray_tpu.remote(num_cpus=0)
    class Fine:
        def ping(self):
            return 1

    for _ in range(6):
        b = Broken.remote()
        with pytest.raises(exc.RayTpuError):
            ray_tpu.get(b.ping.remote(), timeout=60)
    # the pool is intact: a healthy actor still comes up quickly
    f = Fine.remote()
    assert ray_tpu.get(f.ping.remote(), timeout=60) == 1
    ray_tpu.kill(f)
