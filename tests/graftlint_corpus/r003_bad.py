"""R003 corpus: retrace hazards."""
import jax
import jax.numpy as jnp

SCHEDULE = {"warmup": 100}           # mutable module global


def _step(x, flag):
    if flag:                         # R003: Python branch on traced arg
        x = x * 2.0
    return x + SCHEDULE["warmup"]    # R003: closes over mutable global


step = jax.jit(_step)

shaped = jax.jit(lambda x, shape: jnp.zeros(shape) + x,
                 static_argnums=(1,))


def build(xs):
    fns = []
    for x in xs:
        fns.append(jax.jit(lambda v: v + x))   # R003: jit in a loop
    return fns


def call_site(x):
    return shaped(x, [4, 4])         # R003: unhashable static arg
