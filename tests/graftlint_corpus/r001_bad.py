"""R001 corpus: host syncs inside jitted functions."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated_step(x):
    print("step", x)                 # R001: print under jit
    y = np.asarray(x)                # R001: host pull under trace
    return jnp.sum(y)


def _inner(x):
    v = x.mean().item()              # R001: .item() is a host sync
    lr = float(x[0])                 # R001: concretizes a traced value
    jax.device_get(x)                # R001: explicit host sync
    x.block_until_ready()            # R001: host sync
    return x * v * lr


fast_inner = jax.jit(_inner)
