# Fixture corpus for tests/test_graftlint.py. These files are linted
# as data, never imported or executed; each rNNN_bad.py must trip its
# rule and each rNNN_clean.py must not.
