"""R003 corpus: trace-stable jit usage."""
import jax
import jax.numpy as jnp

WARMUP = 100                         # immutable module global: fine


def _step(x, flag):
    return jax.lax.cond(flag, lambda v: v * 2.0, lambda v: v, x) + WARMUP


step = jax.jit(_step)

shaped = jax.jit(lambda x, shape: jnp.zeros(shape) + x,
                 static_argnums=(1,))


def call_site(x):
    return shaped(x, (4, 4))         # hashable tuple static: fine
