"""R004 corpus: snapshot under lock, block outside it."""
import threading
import time


class Scheduler:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = __import__("queue").Queue()
        self._pending = []

    def tick(self):
        with self._lock:
            work = list(self._pending)   # snapshot under lock
            self._pending.clear()
        time.sleep(0.01)                 # blocking happens outside
        for item in work:
            self._queue.put_nowait(item)

    def drain(self):
        with self._lock:
            n = len(self._pending)
        return n


class Ordered:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def forward(self):
        with self._a_lock:
            with self._b_lock:           # one consistent order: fine
                return 1

    def also_forward(self):
        with self._a_lock:
            with self._b_lock:
                return 2
