"""R001 corpus: jitted functions that stay on-device."""
import jax
import jax.numpy as jnp


@jax.jit
def decorated_step(x):
    y = jnp.asarray(x)               # jnp stays on device: fine
    return jnp.sum(y) * 2.0


def _inner(x):
    scale = float(1e-3)              # constant arg: fine
    return jnp.where(x > 0, x * scale, 0.0)


fast_inner = jax.jit(_inner)
