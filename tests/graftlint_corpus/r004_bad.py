"""R004 corpus: blocking under lock + lock-order cycle."""
import threading
import time


class Scheduler:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = __import__("queue").Queue()
        self._futs = []

    def tick(self):
        with self._lock:
            time.sleep(0.01)             # R004: sleep under lock
            item = self._queue.get()     # R004: queue recv under lock
            self._futs[0].result()       # R004: future wait under lock
            return item

    def drain(self):
        with self._lock:
            self._slow_helper()          # R004: via method recursion

    def _slow_helper(self):
        time.sleep(0.5)


class Ordered:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def forward(self):
        with self._a_lock:
            with self._b_lock:
                return 1

    def backward(self):
        with self._b_lock:
            with self._a_lock:           # R004: a->b and b->a = cycle
                return 2
