"""R002 corpus: donated buffer read after dispatch."""
import jax


def _step(state, batch):
    return state, batch


step_fn = jax.jit(_step, donate_argnums=(0,))


def train(state, batches):
    for batch in batches:
        new_state, _ = step_fn(state, batch)
        loss = state["loss"]         # R002: state was donated above
        state = new_state
    return state, loss


def report(state, batch):
    out, _ = step_fn(state, batch)
    return out, state                # R002: donated `state` read again
