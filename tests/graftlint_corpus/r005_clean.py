"""R005 corpus: stats() docstring matches returned keys exactly."""


class Engine:
    def stats(self):
        """Live counters.

        - ``ticks``: scheduler iterations
        - ``queued``: submitted but unadmitted requests
        """
        return {
            "ticks": 0,
            "queued": 0,
        }
