"""R002 corpus: donation with same-statement reassignment."""
import jax


def _step(state, batch):
    return state, batch


step_fn = jax.jit(_step, donate_argnums=(0,))


def train(state, batches):
    for batch in batches:
        state, metrics = step_fn(state, batch)   # canonical pattern
    return state, metrics


def swap_then_rebuild(state, batch):
    out, _ = step_fn(state, batch)
    state = out                      # full reassignment before any read
    return state
