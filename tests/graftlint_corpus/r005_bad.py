"""R005 corpus: stats() docstring out of sync with returned keys."""


class Engine:
    def stats(self):
        """Live counters.

        - ``ticks``: scheduler iterations
        - ``queued``: submitted but unadmitted requests
        - ``retired``: finished requests
        """
        return {
            "ticks": 0,
            "queued": 0,
            "emitted": 0,        # R005: undocumented key
            # R005: documented key "retired" never returned
        }
