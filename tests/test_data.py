"""Data layer tests (reference coverage shapes: `data/tests/test_basic.py`,
`test_map.py`, `test_sort.py`, `test_consumption.py`)."""

import os

import numpy as np
import pytest

from ray_tpu import data as rtd


def test_range_count_take(ray_session):
    ds = rtd.range(100, parallelism=4)
    assert ds.count() == 100
    rows = ds.take(5)
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]
    assert ds.num_blocks() == 4


def test_map_batches_tasks(ray_session):
    ds = rtd.range(32, parallelism=2).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2})
    out = ds.to_numpy()
    np.testing.assert_array_equal(out["sq"], np.arange(32) ** 2)


def test_map_batches_fusion_single_hop(ray_session):
    # read -> map -> map fuses; result correctness is the observable here.
    ds = (rtd.range(16, parallelism=2)
          .map_batches(lambda b: {"x": b["id"] * 2})
          .map_batches(lambda b: {"x": b["x"] + 1}))
    np.testing.assert_array_equal(
        ds.to_numpy()["x"], np.arange(16) * 2 + 1)


def test_map_filter_flat_map(ray_session):
    ds = rtd.from_items([{"v": i} for i in range(10)])
    ds = ds.map(lambda r: {"v": r["v"] * 10})
    ds = ds.filter(lambda r: r["v"] >= 50)
    ds = ds.flat_map(lambda r: [{"v": r["v"]}, {"v": r["v"] + 1}])
    vals = sorted(r["v"] for r in ds.take_all())
    assert vals == [50, 51, 60, 61, 70, 71, 80, 81, 90, 91]


def test_actor_pool_map_batches(ray_session):
    class AddModel:
        def __init__(self):
            self.offset = 100      # "model load" happens once per actor

        def __call__(self, batch):
            return {"y": batch["id"] + self.offset}

    ds = rtd.range(20, parallelism=4).map_batches(
        AddModel, compute=rtd.ActorPoolStrategy(size=2))
    out = np.sort(ds.to_numpy()["y"])
    np.testing.assert_array_equal(out, np.arange(20) + 100)


def test_repartition(ray_session):
    ds = rtd.range(40, parallelism=2).repartition(5)
    assert ds.num_blocks() == 5
    assert ds.count() == 40
    # contiguous repartition preserves order
    np.testing.assert_array_equal(ds.to_numpy()["id"], np.arange(40))


def test_random_shuffle(ray_session):
    ds = rtd.range(50, parallelism=2).random_shuffle(seed=7)
    out = ds.to_numpy()["id"]
    assert sorted(out.tolist()) == list(range(50))
    assert not np.array_equal(out, np.arange(50))


def test_sort(ray_session):
    rng = np.random.default_rng(0)
    vals = rng.permutation(60)
    ds = rtd.from_numpy(vals).rename_columns({"data": "v"}) \
        .repartition(3).sort("v")
    out = ds.to_numpy()["v"]
    np.testing.assert_array_equal(out, np.arange(60))
    out_desc = rtd.from_numpy(vals).rename_columns({"data": "v"}) \
        .repartition(3).sort("v", descending=True).to_numpy()["v"]
    np.testing.assert_array_equal(out_desc, np.arange(60)[::-1])


def test_groupby_agg(ray_session):
    items = [{"k": i % 3, "v": float(i)} for i in range(12)]
    ds = rtd.from_items(items)
    out = ds.groupby("k").sum("v").to_pandas().sort_values("k")
    assert out["sum(v)"].tolist() == [
        sum(float(i) for i in range(12) if i % 3 == k) for k in range(3)]
    cnt = ds.groupby("k").count().to_pandas()
    assert sorted(cnt["count()"].tolist()) == [4, 4, 4]


def test_groupby_string_keys(ray_session):
    # string keys must co-locate across worker processes (deterministic
    # hash, not Python's per-process-randomized hash()).
    items = [{"k": "abc" if i % 2 else "xyz", "v": 1.0} for i in range(20)]
    out = rtd.from_items(items).repartition(4).groupby("k").sum("v") \
        .to_pandas().sort_values("k")
    assert out["sum(v)"].tolist() == [10.0, 10.0]
    assert out["k"].tolist() == ["abc", "xyz"]


def test_limit_union_zip(ray_session):
    a = rtd.range(10, parallelism=2)
    b = rtd.range(10, parallelism=2).map_batches(
        lambda x: {"id2": x["id"] + 100}, batch_size=None)
    assert a.limit(3).count() == 3
    assert a.union(a).count() == 20
    z = a.zip(b).to_numpy()
    np.testing.assert_array_equal(z["id2"], z["id"] + 100)


def test_iter_batches_sizes_and_formats(ray_session):
    ds = rtd.range(25, parallelism=3)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=10)]
    assert sizes == [10, 10, 5]
    sizes = [len(b["id"])
             for b in ds.iter_batches(batch_size=10, drop_last=True)]
    assert sizes == [10, 10]
    import pandas as pd
    for b in ds.iter_batches(batch_size=None, batch_format="pandas"):
        assert isinstance(b, pd.DataFrame)


def test_split_and_streaming_split(ray_session):
    ds = rtd.range(30, parallelism=3)
    shards = ds.split(3, equal=True)
    assert [s.count() for s in shards] == [10, 10, 10]
    all_ids = sorted(
        sum((s.to_numpy()["id"].tolist() for s in shards), []))
    assert all_ids == list(range(30))
    shard = ds.streaming_split_shard(1, 3)
    assert shard.count() == 10


def test_parquet_csv_json_roundtrip(ray_session, tmp_path):
    import pandas as pd
    df = pd.DataFrame({"a": np.arange(10), "b": np.arange(10) * 2.0})
    ds = rtd.from_pandas(df).repartition(2)
    pq_dir = str(tmp_path / "pq")
    ds.write_parquet(pq_dir)
    back = rtd.read_parquet(pq_dir).to_pandas().sort_values("a")
    np.testing.assert_array_equal(back["a"], df["a"])
    csv_dir = str(tmp_path / "csv")
    ds.write_csv(csv_dir)
    back = rtd.read_csv(csv_dir).to_pandas().sort_values("a")
    np.testing.assert_array_equal(back["b"], df["b"])
    js_dir = str(tmp_path / "js")
    ds.write_json(js_dir)
    back = rtd.read_json(js_dir).to_pandas().sort_values("a")
    np.testing.assert_array_equal(back["b"], df["b"])


def test_from_formats(ray_session):
    import pandas as pd
    import pyarrow as pa
    assert rtd.from_items([1, 2, 3]).take_all()[0]["item"] == 1
    assert rtd.from_numpy(np.ones((4, 2))).count() == 4
    t = pa.table({"x": [1, 2]})
    assert rtd.from_arrow(t).count() == 2
    df = pd.DataFrame({"x": [1, 2, 3]})
    assert rtd.from_pandas(df).count() == 3
    ds = rtd.range_tensor(6, shape=(2, 2))
    assert ds.to_numpy()["data"].shape == (6, 2, 2)


def test_add_drop_select_columns_sample(ray_session):
    ds = rtd.range(20, parallelism=2).add_column(
        "double", lambda b: b["id"] * 2)
    assert set(ds.columns()) == {"id", "double"}
    assert set(ds.select_columns(["double"]).columns()) == {"double"}
    assert set(ds.drop_columns(["double"]).columns()) == {"id"}
    s = rtd.range(100, parallelism=2).random_sample(0.5, seed=0)
    assert 20 < s.count() < 80


def test_train_test_split_and_schema(ray_session):
    ds = rtd.range(20, parallelism=2)
    train, test = ds.train_test_split(0.25)
    assert train.count() == 15 and test.count() == 5
    assert ds.schema() is not None
    assert "Read" in ds.stats()


def test_read_write_tfrecords_roundtrip(ray_session, tmp_path):
    """Example-proto columns survive a write/read roundtrip through the
    built-in codec (reference: read_tfrecords/write_tfrecords)."""
    ds = rtd.from_items([
        {"name": f"row{i}", "score": float(i) / 2, "count": i,
         "tags": [i, i + 1]}
        for i in range(10)
    ])
    out = tmp_path / "tfr"
    out.mkdir()
    ds.write_tfrecords(str(out))
    back = rtd.read_tfrecords(str(out)).take_all()
    back.sort(key=lambda r: r["count"])
    assert len(back) == 10
    assert back[3]["name"] == b"row3"          # bytes, like the reference
    assert back[3]["score"] == pytest.approx(1.5)
    assert back[3]["count"] == 3
    assert list(back[3]["tags"]) == [3, 4]


def test_read_images(ray_session, tmp_path):
    from PIL import Image

    for i in range(4):
        Image.new("RGB", (8 + i, 6), color=(i * 10, 0, 0)).save(
            tmp_path / f"img{i}.png")
    ds = rtd.read_images(str(tmp_path), size=(16, 16))
    rows = ds.take_all()
    assert len(rows) == 4
    assert rows[0]["image"].shape == (16, 16, 3)
    # ragged (no resize): object column of per-image arrays
    ragged = rtd.read_images(str(tmp_path)).take_all()
    shapes = sorted(r["image"].shape for r in ragged)
    assert shapes[0] == (6, 8, 3) and shapes[-1] == (6, 11, 3)


def test_dataset_stats_per_op(ray_session):
    ds = rtd.from_items([{"v": i} for i in range(32)]) \
        .map_batches(lambda b: b).repartition(4)
    list(ds.iter_rows())
    report = ds.stats()
    assert "blocks" in report and "rows" in report
    assert "Repartition" in report or "repartition" in report.lower()


def test_read_webdataset(ray_session, tmp_path):
    """Webdataset tar shards: extension-grouped samples with per-ext
    decoding (reference: data/datasource/webdataset_datasource.py)."""
    import io
    import json as _json
    import tarfile

    from PIL import Image

    shard = tmp_path / "shard-000000.tar"
    with tarfile.open(shard, "w") as tar:
        def add(name, data: bytes):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))

        for i in range(3):
            img = Image.fromarray(
                np.full((4, 5, 3), i * 10, np.uint8))
            buf = io.BytesIO()
            img.save(buf, format="PNG")
            add(f"sample{i}.png", buf.getvalue())
            add(f"sample{i}.cls", str(i).encode())
            add(f"sample{i}.json",
                _json.dumps({"meta": i}).encode())

    ds = rtd.read_webdataset(str(shard))
    rows = ds.take_all()
    assert len(rows) == 3
    rows.sort(key=lambda r: r["__key__"])
    for i, row in enumerate(rows):
        assert row["__key__"] == f"sample{i}"
        assert row["cls"] == i
        assert row["json"]["meta"] == i
        assert row["png"].shape == (4, 5, 3)
        assert int(row["png"][0, 0, 0]) == i * 10


def _sql_conn_at(path):
    import sqlite3
    return sqlite3.connect(path)


def test_read_sql(ray_session, tmp_path):
    """DBAPI reads with OFFSET/LIMIT sharding (reference:
    data/datasource/sql_datasource.py)."""
    import functools
    import sqlite3

    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE t (id INTEGER, name TEXT, score REAL,"
                 " blob BLOB)")
    conn.executemany("INSERT INTO t VALUES (?, ?, ?, ?)",
                     [(i, f"row{i}", i * 0.5, bytes([i, 0]))
                      for i in range(20)])
    conn.commit()
    conn.close()
    factory = functools.partial(_sql_conn_at, db)

    ds = rtd.read_sql("SELECT id, name, score, blob FROM t ORDER BY id;",
                      factory)
    rows = ds.take_all()
    assert len(rows) == 20
    assert rows[3]["name"] == "row3" and rows[3]["score"] == 1.5
    # BLOBs keep trailing NULs (object dtype, not fixed-width "S")
    assert rows[3]["blob"] == bytes([3, 0])

    # 3 shards of 8 only cover 24 by LIMIT, but the LAST shard is
    # unbounded, so an uneven 20 rows all arrive
    sharded = rtd.read_sql("SELECT id FROM t ORDER BY id", factory,
                           shard_rows=7, num_shards=2)
    ids = sorted(r["id"] for r in sharded.take_all())
    assert ids == list(range(20))


def test_push_based_shuffle_many_blocks(ray_session, monkeypatch):
    """Above the block threshold the exchange inserts the push-based
    merge tier (reference: push_based_shuffle.py): correctness at 10x
    the usual block count, and the per-op stats record the merge
    fan-in."""
    monkeypatch.setenv("RAY_TPU_DATA_PUSH_SHUFFLE_MIN_BLOCKS", "16")
    n = 2000
    ds = rtd.range(n, parallelism=40).random_shuffle(seed=3)
    out = sorted(r["id"] for r in ds.take_all())
    assert out == list(range(n))                 # a permutation: no loss
    st = ds.stats()
    assert "push-based shuffle" in st and "fan-in" in st, st
    assert "40 maps" in st

    # sort through the same tier stays totally ordered
    ds2 = rtd.range(n, parallelism=40).random_shuffle(seed=5).sort("id")
    vals = [r["id"] for r in ds2.take_all()]
    assert vals == list(range(n))

    # below the threshold the direct exchange is kept
    monkeypatch.setenv("RAY_TPU_DATA_PUSH_SHUFFLE_MIN_BLOCKS", "1000")
    ds3 = rtd.range(200, parallelism=8).random_shuffle(seed=1)
    assert sorted(r["id"] for r in ds3.take_all()) == list(range(200))
    assert "direct exchange" in ds3.stats()


def test_shuffle_intermediates_freed(ray_session):
    """Per-epoch shuffles must not leak shard objects: exchange
    intermediates ride refs inside list objects (escaped from normal
    refcounting), so the exchange frees them explicitly — without that,
    every epoch leaks a dataset's worth of arena."""
    import time as _time

    import ray_tpu
    from ray_tpu._private.worker import get_client
    node = get_client().node

    def tracked():
        with node.lock:
            return len(node.directory)

    # warm one epoch (pool workers, function blobs)
    rtd.range(400, parallelism=8).random_shuffle(seed=0).take_all()
    _time.sleep(1.5)
    base = tracked()
    for epoch in range(3):
        rtd.range(400, parallelism=8).random_shuffle(
            seed=epoch).take_all()
    _time.sleep(1.5)
    ray_tpu.get(ray_tpu.put(1))        # drain the decref batch
    _time.sleep(1.0)
    after = tracked()
    # Intermediates are 8 shard-lists + 64 shards + reduce returns per
    # epoch (~80): leaking them would show ~240 here. The residue this
    # bound allows (~16/epoch) is each epoch's OUTPUT blocks — dataset
    # results are session-lifetime today (their refs ride inside task
    # returns and escape refcounting; a Dataset.__del__ lifecycle is
    # future work, noted in allops.py).
    assert after - base < 60, f"leaked {after - base} objects"
