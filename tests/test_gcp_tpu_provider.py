"""TPU-VM node provider against a fake TPU REST API.

Covers the reference-parity behaviors of
`autoscaler/_private/gcp/node_provider.py`: create/list/terminate with
label-based tag filtering, transient-error retry, gang-atomic slice
creation (operation failure leaves NO node), autoscaler integration
(demand scales slices up, idle scales down), and `ray-tpu up` driving a
gcp-tpu provider end-to-end through a standalone head.
"""

import subprocess
import sys
import time

import pytest

from fake_tpu_api import FakeTpuApi
from ray_tpu.autoscaler.gcp_tpu import (
    TpuVmNodeProvider,
    bootstrap_gcp_tpu,
    default_startup_script,
)
from ray_tpu.autoscaler.node_provider import (
    TAG_NODE_KIND,
    TAG_NODE_TYPE,
    make_node_provider,
)


def _provider(api_url, **kw):
    return TpuVmNodeProvider(
        {"project_id": "proj", "zone": "us-central2-b",
         "api_endpoint": api_url, "token": "fake-token",
         "operation_poll_interval_s": 0.05, **kw},
        cluster_name="testcluster")


def test_bootstrap_validation():
    with pytest.raises(ValueError, match="project_id"):
        bootstrap_gcp_tpu({"zone": "us-central2-b"})
    cfg = bootstrap_gcp_tpu({"project_id": "p", "zone": "z"})
    assert cfg["api_endpoint"].startswith("https://tpu.googleapis")
    assert cfg["api_version"] == "v2"


def test_create_list_terminate_lifecycle():
    api = FakeTpuApi()
    url = api.serve()
    try:
        p = _provider(url)
        tags = {TAG_NODE_KIND: "worker", TAG_NODE_TYPE: "v5e_16"}
        p.create_node({"accelerator_type": "v5litepod-16"}, tags, 2)
        nodes = p.non_terminated_nodes({})
        assert len(nodes) == 2
        # tag filters ride GCP labels (sanitized keys/values)
        assert p.non_terminated_nodes({TAG_NODE_TYPE: "v5e_16"}) == nodes
        assert p.non_terminated_nodes({TAG_NODE_TYPE: "other"}) == []
        assert p.is_running(nodes[0])
        assert p.internal_ip(nodes[0]).startswith("10.0.0.")
        labels = p.node_tags(nodes[0])
        assert labels["ray-tpu-cluster"] == "testcluster"
        # the node body carried the accelerator config
        assert api.nodes[nodes[0]]["acceleratorType"] == "v5litepod-16"
        p.terminate_node(nodes[0])
        assert len(p.non_terminated_nodes({})) == 1
    finally:
        api.close()


def test_list_paging():
    api = FakeTpuApi(page_size=2)
    url = api.serve()
    try:
        p = _provider(url)
        p.create_node({"accelerator_type": "v5litepod-8"},
                      {TAG_NODE_KIND: "worker"}, 5)
        assert len(p.non_terminated_nodes({})) == 5
    finally:
        api.close()


def test_transient_errors_retried():
    api = FakeTpuApi(fail_creates=2)   # first two creates 503
    url = api.serve()
    try:
        p = _provider(url)
        p.create_node({"accelerator_type": "v5litepod-8"},
                      {TAG_NODE_KIND: "worker"}, 1)
        assert len(p.non_terminated_nodes({})) == 1
    finally:
        api.close()


def test_gang_atomic_create_failure():
    """A failed slice operation must leave NO node behind and surface the
    error (whole-slice atomicity: SURVEY §7.4#3)."""
    api = FakeTpuApi(fail_create_operation=True)
    url = api.serve()
    try:
        p = _provider(url)
        with pytest.raises(RuntimeError, match="no capacity"):
            p.create_node({"accelerator_type": "v5litepod-16"},
                          {TAG_NODE_KIND: "worker"}, 1)
        assert p.non_terminated_nodes({}) == []
    finally:
        api.close()


def test_async_operation_polling():
    api = FakeTpuApi(create_delay_s=0.3)
    url = api.serve()
    try:
        p = _provider(url)
        t0 = time.monotonic()
        p.create_node({"accelerator_type": "v5litepod-8"},
                      {TAG_NODE_KIND: "worker"}, 1)
        assert time.monotonic() - t0 >= 0.3    # blocked on the operation
        nid = p.non_terminated_nodes({})[0]
        assert p.is_running(nid)
    finally:
        api.close()


def test_startup_script_injected():
    api = FakeTpuApi()
    url = api.serve()
    try:
        p = TpuVmNodeProvider(
            {"project_id": "p", "zone": "z", "api_endpoint": url,
             "token": "t", "operation_poll_interval_s": 0.05,
             "head_address": "10.0.0.1:6379", "authkey_hex": "ab12"},
            cluster_name="c")
        p.create_node({"accelerator_type": "v5litepod-8", "num_tpus": 4},
                      {TAG_NODE_KIND: "worker"}, 1)
        nid = p.non_terminated_nodes({})[0]
        script = api.nodes[nid]["metadata"]["startup-script"]
        assert "10.0.0.1:6379" in script and "ab12" in script
        assert "--num-tpus 4" in script
        # and the helper is the same text the provider injects
        assert script == default_startup_script("10.0.0.1:6379", "ab12", 4)
        # declared custom resources are forwarded; bare TPU declarations
        # leave chip count to per-host auto-detection (no --num-tpus)
        p.create_node({"accelerator_type": "v5litepod-8",
                       "resources": {"CPU": 8, "TPU": 4, "fast_ssd": 1}},
                      {TAG_NODE_KIND: "worker"}, 1)
        nid2 = [n for n in p.non_terminated_nodes({}) if n != nid][0]
        s2 = api.nodes[nid2]["metadata"]["startup-script"]
        assert "--num-tpus" not in s2
        assert "fast_ssd" in s2 and "TPU" not in s2.split("--resources")[1]
    finally:
        api.close()


def test_startup_script_authkey_secret_keeps_metadata_clean():
    """With authkey_secret configured, the hex authkey never lands in
    instance metadata — the script fetches it from Secret Manager with
    the VM's own service-account token at boot (ADVICE r4: plaintext
    authkey in startup-script metadata exposes cluster control to any
    project reader)."""
    api = FakeTpuApi()
    url = api.serve()
    try:
        p = TpuVmNodeProvider(
            {"project_id": "p", "zone": "z", "api_endpoint": url,
             "token": "t", "operation_poll_interval_s": 0.05,
             "head_address": "10.0.0.1:6379", "authkey_hex": "deadbeef",
             "authkey_secret": "projects/p/secrets/ray-authkey"},
            cluster_name="c")
        p.create_node({"accelerator_type": "v5litepod-8"},
                      {TAG_NODE_KIND: "worker"}, 1)
        nid = p.non_terminated_nodes({})[0]
        script = api.nodes[nid]["metadata"]["startup-script"]
        assert "deadbeef" not in script
        assert ("secretmanager.googleapis.com/v1/projects/p/secrets/"
                "ray-authkey/versions/latest:access") in script
        assert "Metadata-Flavor: Google" in script   # SA token fetch
        assert "RAY_TPU_AUTHKEY" in script
    finally:
        api.close()


def test_label_unsafe_node_type_rejected():
    api = FakeTpuApi()
    url = api.serve()
    try:
        p = _provider(url)
        with pytest.raises(ValueError, match="label-safe"):
            p.create_node({"accelerator_type": "v5litepod-8"},
                          {TAG_NODE_TYPE: "TPU.Worker"}, 1)
        assert p.non_terminated_nodes({}) == []
    finally:
        api.close()


def test_make_node_provider_registry():
    api = FakeTpuApi()
    url = api.serve()
    try:
        p = make_node_provider(
            {"type": "gcp-tpu", "project_id": "p", "zone": "z",
             "api_endpoint": url, "token": "t",
             "cluster_name": "reg"})
        assert isinstance(p, TpuVmNodeProvider)
        assert p.cluster_name == "reg"
        with pytest.raises(ValueError, match="unknown node provider"):
            make_node_provider({"type": "nope"})
    finally:
        api.close()


def test_autoscaler_scales_slices():
    """StandardAutoscaler drives the TPU provider: min_workers brings
    slices up; removing demand + idle timeout tears them down."""
    from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
    from ray_tpu.autoscaler.load_metrics import LoadMetrics

    api = FakeTpuApi()
    url = api.serve()
    try:
        provider = _provider(url)
        lm = LoadMetrics()
        cfg = {
            "max_workers": 4,
            "idle_timeout_minutes": 0.0,
            "available_node_types": {
                "v5e_16": {
                    "min_workers": 2,
                    "max_workers": 4,
                    "resources": {"CPU": 8, "TPU": 4},
                    "node_config": {"accelerator_type": "v5litepod-16"},
                },
            },
        }
        a = StandardAutoscaler(provider, cfg, lm)
        a.update()
        assert len(provider.non_terminated_nodes({})) == 2
        # idle slices above min_workers get reclaimed; min stays
        a.update()
        assert len(provider.non_terminated_nodes({})) == 2
    finally:
        api.close()


_UP_DRIVER = """
import json, os, subprocess, sys, time
sys.path.insert(0, {repo!r})
sys.path.insert(0, {testdir!r})
from fake_tpu_api import FakeTpuApi

api = FakeTpuApi()
url = api.serve()
cluster_yaml = os.path.join({tmp!r}, "cluster.yaml")
open(cluster_yaml, "w").write(f'''
cluster_name: tpuvm_e2e
max_workers: 4
idle_timeout_minutes: 60
provider:
  type: gcp-tpu
  project_id: proj
  zone: us-central2-b
  api_endpoint: {{url}}
  token: fake
  operation_poll_interval_s: 0.05
available_node_types:
  v5e_8:
    min_workers: 2
    max_workers: 4
    resources: {{{{"CPU": 8, "TPU": 4}}}}
    node_config:
      accelerator_type: v5litepod-8
      num_tpus: 4
''')
env = dict(os.environ)
env["RAY_TPU_CLUSTER_STATE_DIR"] = {tmp!r}
r = subprocess.run(
    [sys.executable, "-m", "ray_tpu.scripts.cli", "up", "-f", cluster_yaml],
    env=env, capture_output=True, text=True, timeout=180)
sys.stderr.write(r.stdout + r.stderr)
assert r.returncode == 0, "up failed"
# the fake cloud now holds two v5e-8 slices tagged for this cluster
slices = {{nid: n for nid, n in api.nodes.items()}}
assert len(slices) == 2, slices
for n in slices.values():
    assert n["acceleratorType"] == "v5litepod-8"
    assert n["labels"]["ray-tpu-cluster"] == "tpuvm_e2e"
    assert "startup-script" in n.get("metadata", {{}})
r = subprocess.run(
    [sys.executable, "-m", "ray_tpu.scripts.cli", "down", "tpuvm_e2e"],
    env=env, capture_output=True, text=True, timeout=60)
sys.stderr.write(r.stdout + r.stderr)
assert r.returncode == 0, "down failed"
# down must terminate the billed slices, not just kill the head
assert api.nodes == {{}}, f"leaked slices: {{list(api.nodes)}}"
assert "terminated 2 provider node(s)" in r.stdout, r.stdout
api.close()
print("UP-GCP-OK")
"""


def test_ray_tpu_up_with_gcp_provider(tmp_path):
    import os
    testdir = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(testdir)
    script = _UP_DRIVER.format(repo=repo, testdir=testdir,
                               tmp=str(tmp_path))
    r = subprocess.run([sys.executable, "-c", script],
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, \
        f"stdout:\n{r.stdout}\nstderr:\n{r.stderr[-6000:]}"
    assert "UP-GCP-OK" in r.stdout
