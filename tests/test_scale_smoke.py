"""Batching-on/off parity smoke (`make scale-smoke`, tier-1).

Runs scale_bench's parity workload in two fresh sessions — coalescing
frame layer + pipelined submission ON (the default) vs the legacy
per-message, per-ack wire — and asserts the OUTPUTS are identical:
every task result and the round-tripped object bytes. The batched
control plane is allowed to change timing, never values."""

import json
import os
import subprocess
import sys

_BENCH = os.path.join(os.path.dirname(__file__), os.pardir,
                      "scale_bench.py")


def _parity_run(batching: str, n_tasks: int = 600, n_puts: int = 60):
    env = dict(os.environ,
               RAY_TPU_CHANNEL_BATCHING=batching,
               RAY_TPU_SUBMIT_PIPELINE=batching,
               JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, _BENCH, "--parity-child",
         str(n_tasks), str(n_puts)],
        env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_batching_on_off_output_parity():
    on = _parity_run("1")
    off = _parity_run("0")
    # the flags really took in each child
    assert on["channel_batching"] and on["submit_pipeline"]
    assert not off["channel_batching"] and not off["submit_pipeline"]
    # same task outputs, same object values
    assert on["task_checksum"] == off["task_checksum"]
    assert on["object_digest"] == off["object_digest"]
    # both modes actually ran the full workload
    assert on["tasks"] == off["tasks"] == 600
    for doc in (on, off):
        assert doc["end_to_end_per_s"] > 0
        assert doc["put_get_per_s"] > 0
