"""State API, task events, timeline, and metrics.

Counterpart of the reference's `python/ray/tests/test_state_api.py` and
`test_metrics_agent.py` coverage: lifecycle records for tasks/actors,
list_* endpoints, chrome-trace export, and the Counter/Gauge/Histogram
application-metrics pipeline (worker flush → driver aggregation →
prometheus text).
"""

import json
import time

import pytest

import ray_tpu
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import state


@pytest.fixture
def cluster(ray_session):
    return ray_session


def test_list_tasks_lifecycle(cluster):
    @ray_tpu.remote
    def traced(x):
        return x + 1

    refs = [traced.remote(i) for i in range(3)]
    assert ray_tpu.get(refs) == [1, 2, 3]
    tasks = state.list_tasks()
    mine = [t for t in tasks if "traced" in t["name"]]
    assert len(mine) >= 3
    assert all(t["state"] == "FINISHED" for t in mine[:3])
    assert all(t["start_ts"] is not None and t["end_ts"] is not None
               for t in mine[:3])
    assert all(t["worker_id"] for t in mine[:3])


def test_failed_task_recorded(cluster):
    @ray_tpu.remote
    def boom():
        raise ValueError("no")

    ref = boom.remote()
    with pytest.raises(ValueError):
        ray_tpu.get(ref)
    deadline = time.time() + 10
    while time.time() < deadline:
        failed = [t for t in state.list_tasks({"state": "FAILED"})
                  if "boom" in t["name"]]
        if failed:
            break
        time.sleep(0.1)
    assert failed and failed[0]["error"] == "application_error"


def test_list_actors_and_workers(cluster):
    @ray_tpu.remote
    class Stateful:
        def ping(self):
            return "pong"

    a = Stateful.remote()
    assert ray_tpu.get(a.ping.remote()) == "pong"
    actors = state.list_actors()
    mine = [x for x in actors if "Stateful" in x["class_name"]]
    assert mine and mine[0]["state"] == "ALIVE"
    workers = state.list_workers()
    assert any(w["alive"] for w in workers)
    objs = state.list_objects()
    assert isinstance(objs, list)
    nodes = state.list_nodes()
    assert nodes and nodes[0]["resources_total"].get("CPU", 0) > 0


def test_summary_and_timeline(cluster, tmp_path):
    @ray_tpu.remote
    def traced2():
        time.sleep(0.05)
        return 1

    ray_tpu.get([traced2.remote() for _ in range(2)])
    summary = state.summarize_tasks()
    key = next(k for k in summary if "traced2" in k)
    assert summary[key].get("FINISHED", 0) >= 2

    out = tmp_path / "timeline.json"
    events = ray_tpu.timeline(str(out))
    assert any("traced2" in e["name"] for e in events)
    loaded = json.loads(out.read_text())
    span = next(e for e in loaded if "traced2" in e["name"])
    assert span["ph"] == "X" and span["dur"] >= 50_000  # >= 50ms in us


def test_metrics_counter_gauge_histogram(cluster):
    c = metrics_mod.Counter("test_requests", "desc", tag_keys=("route",))
    c.inc(2.0, {"route": "/a"})
    c.inc(1.0, {"route": "/b"})
    with pytest.raises(ValueError):
        c.inc(0)
    with pytest.raises(ValueError):
        c.inc(1, {"bogus": "x"})
    g = metrics_mod.Gauge("test_depth", "d")
    g.set(7)
    h = metrics_mod.Histogram("test_lat", "l", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)

    snap = {m["name"]: m for m in state.get_metrics()}
    assert snap["test_requests"]["series"][(("route", "/a"),)] == 2.0
    assert snap["test_depth"]["series"][()] == 7
    buckets, total, count = snap["test_lat"]["series"][()]
    assert buckets == [1, 1, 1] and count == 3 and abs(total - 5.55) < 1e-9

    text = state.prometheus_metrics()
    assert 'ray_tpu_test_requests{route="/a"} 2.0' in text
    assert "ray_tpu_test_lat_count 3" in text
    assert 'ray_tpu_test_lat_bucket{le="+Inf"} 3' in text


def test_metrics_flow_from_workers(cluster):
    @ray_tpu.remote
    def emit(i):
        from ray_tpu.util import metrics as m
        cnt = m.Counter("test_worker_side", "w")
        cnt.inc(1.0)
        m.flush()
        return i

    assert sorted(ray_tpu.get([emit.remote(i) for i in range(3)])) == [0, 1, 2]
    deadline = time.time() + 10
    total = 0
    while time.time() < deadline:
        snap = {m["name"]: m for m in state.get_metrics()}
        if "test_worker_side" in snap:
            total = sum(snap["test_worker_side"]["series"].values())
            if total >= 1.0:
                break
        time.sleep(0.2)
    # counters sum across the worker processes that pushed
    assert total >= 1.0


def test_merge_snapshots_semantics():
    a = [{"name": "c", "type": "counter", "description": "",
          "series": {(): 1.0}}]
    b = [{"name": "c", "type": "counter", "description": "",
          "series": {(): 2.0}}]
    merged = metrics_mod.merge_snapshots([a, b])
    assert merged[0]["series"][()] == 3.0
