"""State API, task events, timeline, and metrics.

Counterpart of the reference's `python/ray/tests/test_state_api.py` and
`test_metrics_agent.py` coverage: lifecycle records for tasks/actors,
list_* endpoints, chrome-trace export, and the Counter/Gauge/Histogram
application-metrics pipeline (worker flush → driver aggregation →
prometheus text).
"""

import json
import time

import pytest

import ray_tpu
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import state


@pytest.fixture
def cluster(ray_session):
    return ray_session


def test_list_tasks_lifecycle(cluster):
    @ray_tpu.remote
    def traced(x):
        return x + 1

    refs = [traced.remote(i) for i in range(3)]
    assert ray_tpu.get(refs) == [1, 2, 3]
    tasks = state.list_tasks()
    mine = [t for t in tasks if "traced" in t["name"]]
    assert len(mine) >= 3
    assert all(t["state"] == "FINISHED" for t in mine[:3])
    assert all(t["start_ts"] is not None and t["end_ts"] is not None
               for t in mine[:3])
    assert all(t["worker_id"] for t in mine[:3])


def test_failed_task_recorded(cluster):
    @ray_tpu.remote
    def boom():
        raise ValueError("no")

    ref = boom.remote()
    with pytest.raises(ValueError):
        ray_tpu.get(ref)
    deadline = time.time() + 10
    while time.time() < deadline:
        failed = [t for t in state.list_tasks({"state": "FAILED"})
                  if "boom" in t["name"]]
        if failed:
            break
        time.sleep(0.1)
    assert failed and failed[0]["error"] == "application_error"


def test_list_actors_and_workers(cluster):
    @ray_tpu.remote
    class Stateful:
        def ping(self):
            return "pong"

    a = Stateful.remote()
    assert ray_tpu.get(a.ping.remote()) == "pong"
    actors = state.list_actors()
    mine = [x for x in actors if "Stateful" in x["class_name"]]
    assert mine and mine[0]["state"] == "ALIVE"
    workers = state.list_workers()
    assert any(w["alive"] for w in workers)
    objs = state.list_objects()
    assert isinstance(objs, list)
    nodes = state.list_nodes()
    assert nodes and nodes[0]["resources_total"].get("CPU", 0) > 0


def test_summary_and_timeline(cluster, tmp_path):
    @ray_tpu.remote
    def traced2():
        time.sleep(0.05)
        return 1

    ray_tpu.get([traced2.remote() for _ in range(2)])
    summary = state.summarize_tasks()
    key = next(k for k in summary if "traced2" in k)
    assert summary[key].get("FINISHED", 0) >= 2

    out = tmp_path / "timeline.json"
    events = ray_tpu.timeline(str(out))
    assert any("traced2" in e["name"] for e in events)
    loaded = json.loads(out.read_text())
    span = next(e for e in loaded if "traced2" in e["name"])
    assert span["ph"] == "X" and span["dur"] >= 50_000  # >= 50ms in us


def test_metrics_counter_gauge_histogram(cluster):
    c = metrics_mod.Counter("test_requests", "desc", tag_keys=("route",))
    c.inc(2.0, {"route": "/a"})
    c.inc(1.0, {"route": "/b"})
    with pytest.raises(ValueError):
        c.inc(0)
    with pytest.raises(ValueError):
        c.inc(1, {"bogus": "x"})
    g = metrics_mod.Gauge("test_depth", "d")
    g.set(7)
    h = metrics_mod.Histogram("test_lat", "l", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)

    snap = {m["name"]: m for m in state.get_metrics()}
    assert snap["test_requests"]["series"][(("route", "/a"),)] == 2.0
    assert snap["test_depth"]["series"][()] == 7
    buckets, total, count = snap["test_lat"]["series"][()]
    assert buckets == [1, 1, 1] and count == 3 and abs(total - 5.55) < 1e-9

    text = state.prometheus_metrics()
    assert 'ray_tpu_test_requests{route="/a"} 2.0' in text
    assert "ray_tpu_test_lat_count 3" in text
    assert 'ray_tpu_test_lat_bucket{le="+Inf"} 3' in text


def test_metrics_flow_from_workers(cluster):
    @ray_tpu.remote
    def emit(i):
        from ray_tpu.util import metrics as m
        cnt = m.Counter("test_worker_side", "w")
        cnt.inc(1.0)
        m.flush()
        return i

    assert sorted(ray_tpu.get([emit.remote(i) for i in range(3)])) == [0, 1, 2]
    deadline = time.time() + 10
    total = 0
    while time.time() < deadline:
        snap = {m["name"]: m for m in state.get_metrics()}
        if "test_worker_side" in snap:
            total = sum(snap["test_worker_side"]["series"].values())
            if total >= 1.0:
                break
        time.sleep(0.2)
    # counters sum across the worker processes that pushed
    assert total >= 1.0


def test_merge_snapshots_semantics():
    a = [{"name": "c", "type": "counter", "description": "",
          "series": {(): 1.0}}]
    b = [{"name": "c", "type": "counter", "description": "",
          "series": {(): 2.0}}]
    merged = metrics_mod.merge_snapshots([a, b])
    assert merged[0]["series"][()] == 3.0


# ---------------------------------------------------------------------------
# Log pipeline (reference: _private/log_monitor.py:102 tail-to-driver +
# dashboard/modules/log/): a remote task's print is captured to a per-
# process file, tailed, and reaches (a) a subscribed driver's stderr and
# (b) the head's log ring serving /api/logs. Subprocess-driven: needs its
# own session with a daemon node and a log_to_driver subscription.
# ---------------------------------------------------------------------------

_LOG_E2E = r"""
import sys, time
import ray_tpu
from ray_tpu.cluster_utils import Cluster

c = Cluster(head_resources={"CPU": 2}, log_to_driver=True)
c.add_node({"CPU": 2, "far": 1})

@ray_tpu.remote
def speak_head():
    print("HELLO-FROM-HEAD-WORKER")
    return 1

@ray_tpu.remote(resources={"far": 1})
def speak_node():
    print("HELLO-FROM-NODE-WORKER")
    return 2

assert ray_tpu.get([speak_head.remote(), speak_node.remote()],
                   timeout=120) == [1, 2]

client = ray_tpu._worker.get_client()
deadline = time.time() + 30
found = set()
while time.time() < deadline and len(found) < 2:
    for row in client.control("list_logs"):
        text = "\n".join(client.control(
            "get_log", {"source": row["source"], "lines": 500}))
        if "HELLO-FROM-HEAD-WORKER" in text:
            found.add("head")
        if "HELLO-FROM-NODE-WORKER" in text:
            found.add("node")
    time.sleep(0.3)
assert found == {"head", "node"}, found
# give the subscription fanout a beat to hit our stderr, then exit; the
# parent asserts on captured stderr
time.sleep(1.5)
print("LOGS-RING-OK")
c.shutdown()
"""


def test_log_pipeline_to_driver_and_ring():
    import os
    import subprocess
    import sys as _sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([_sys.executable, "-c", _LOG_E2E], cwd=repo,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, \
        f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "LOGS-RING-OK" in r.stdout
    # tail-to-driver: the remote prints arrived on the DRIVER's stderr,
    # prefixed with their source process
    assert "HELLO-FROM-HEAD-WORKER" in r.stderr
    assert "HELLO-FROM-NODE-WORKER" in r.stderr


# ---------------------------------------------------------------------------
# On-demand stack dumps (reference: `ray stack` scripts.py:1786 + py-spy
# profile_manager.py — workers self-sample via sys._current_frames) and
# general pubsub channels (reference: src/ray/pubsub/publisher.h:307).
# ---------------------------------------------------------------------------

def test_stack_dump_finds_busy_worker(cluster):
    import threading

    @ray_tpu.remote
    def very_recognizable_busy_loop():
        t0 = time.time()
        while time.time() - t0 < 8.0:
            time.sleep(0.05)
        return 1

    ref = very_recognizable_busy_loop.remote()
    time.sleep(1.0)     # let it get scheduled + running
    client = ray_tpu._worker.get_client()
    dumps = client.control("stack", {"worker_id": None, "timeout": 4.0})
    assert dumps, "no stacks collected"
    text = "\n".join(d["stacks"] for d in dumps.values())
    assert "very_recognizable_busy_loop" in text, \
        f"busy function missing from stacks:\n{text[:2000]}"
    assert ray_tpu.get(ref, timeout=60) == 1


def test_pubsub_publish_poll_across_processes(cluster):
    from ray_tpu.util.pubsub import Publisher, Subscriber

    sub = Subscriber("test_chan")

    @ray_tpu.remote
    def announce(i):
        from ray_tpu.util.pubsub import Publisher as P
        return P("test_chan").publish({"i": i})

    seqs = ray_tpu.get([announce.remote(i) for i in range(3)], timeout=60)
    assert len(set(seqs)) == 3
    got = []
    deadline = time.time() + 20
    while len(got) < 3 and time.time() < deadline:
        got.extend(sub.poll(timeout=5.0))
    assert sorted(m["i"] for m in got) == [0, 1, 2]
    # cursor advanced: nothing new -> empty poll, fast
    assert sub.poll(timeout=0.2) == []


def test_pubsub_ring_cap(cluster, monkeypatch):
    # the cap is re-resolved from the environment at publish time, so a
    # small override actually exercises the trim branch
    monkeypatch.setenv("RAY_TPU_PUBSUB_RING_MESSAGES", "10")
    client = ray_tpu._worker.get_client()
    for i in range(25):
        client.control("pubsub_publish",
                       {"channel": "cap_chan", "message": i})
    last, msgs = client.control(
        "pubsub_poll", {"channel": "cap_chan", "after": 0,
                        "timeout": 0.0})
    assert last == 25
    assert len(msgs) == 10 and msgs == list(range(15, 25))


def test_usage_stats_local_and_optin_report(cluster, monkeypatch):
    """Usage stats (reference: _private/usage/usage_lib.py:92): local
    session snapshot always works; network reporting requires BOTH the
    explicit opt-in env AND a configured URL (zero-egress default)."""
    import os
    from ray_tpu._private import usage_stats as us

    us.record_library_usage("unit_test_lib")
    node = ray_tpu._worker.get_client().node
    path = us.write_local(node)
    assert path and os.path.exists(path)
    with open(path) as f:
        payload = json.load(f)
    assert payload["total_num_nodes"] >= 1
    assert "unit_test_lib" in payload["libraries"]
    assert payload["ray_tpu_version"] == ray_tpu.__version__

    # off by default, even with a URL configured
    monkeypatch.delenv("RAY_TPU_USAGE_STATS_ENABLED", raising=False)
    monkeypatch.setenv("RAY_TPU_USAGE_STATS_URL",
                       "http://127.0.0.1:1/nope")
    assert us.maybe_report(node) is False

    # opted in: POSTs the payload to the configured endpoint
    import http.server
    import threading
    got = {}

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            got["body"] = json.loads(self.rfile.read(n))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.handle_request, daemon=True)
    t.start()
    monkeypatch.setenv("RAY_TPU_USAGE_STATS_ENABLED", "1")
    monkeypatch.setenv(
        "RAY_TPU_USAGE_STATS_URL",
        f"http://127.0.0.1:{srv.server_address[1]}/usage")
    assert us.maybe_report(node) is True
    t.join(timeout=5)
    srv.server_close()
    assert "unit_test_lib" in got["body"]["libraries"]
