"""Paged KV cache tests: block-table attention parity, the paged model
path vs. full forward, BlockAllocator and RadixTree invariants, engine
prefix sharing (shared system prompt prefilled exactly once, COW on
mid-block divergence), chunked-admission stall bounds, cancellation and
abandoned-stream cleanup, eviction under pool pressure, and a seeded
admit/cancel/retire fuzz (small here; the big variant is `slow`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt
from ray_tpu.ops import decode_attention as da
from ray_tpu.ops import quant
from ray_tpu.serve.engine import BlockAllocator, InferenceEngine, RadixTree


def tiny_cfg(**kw):
    return gpt.GPTConfig(**{**dict(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype="float32"), **kw})


def rollout_reference(params, prompt, cfg, steps):
    """Greedy generation via repeated FULL forward passes."""
    toks = list(prompt)
    for _ in range(steps):
        logits = gpt.forward(params, jnp.asarray([toks]), cfg)[0, -1]
        toks.append(int(jnp.argmax(logits)))
    return toks[len(prompt):]


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def make_engine(cfg, params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("block_size", 8)
    return InferenceEngine(params, cfg, **kw)


# ---------------------------------------------------------------------------
# paged decode attention
# ---------------------------------------------------------------------------

class TestPagedAttention:
    def _paged(self, b, s, h, d, bs, seed=0):
        """Random contiguous K/V scattered into a scrambled block pool;
        returns (q, k, v, pools, tables, pos)."""
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(ks[0], (b, h, d))
        k = jax.random.normal(ks[1], (b, s, h, d))
        v = jax.random.normal(ks[2], (b, s, h, d))
        mb = s // bs
        rng = np.random.default_rng(seed)
        # one shared pool; each sequence owns a disjoint scrambled set
        perm = rng.permutation(b * mb) + 1      # keep block 0 unused
        tables = perm.reshape(b, mb).astype(np.int32)
        kp = np.zeros((b * mb + 1, bs, h, d), np.float32)
        vp = np.zeros_like(kp)
        for i in range(b):
            for j in range(mb):
                kp[tables[i, j]] = np.asarray(k[i, j * bs:(j + 1) * bs])
                vp[tables[i, j]] = np.asarray(v[i, j * bs:(j + 1) * bs])
        pos = jnp.array([s - 1, 3][:b], jnp.int32)
        return q, k, v, jnp.asarray(kp), jnp.asarray(vp), \
            jnp.asarray(tables), pos

    def test_gather_reassembles_contiguous_kv(self):
        q, k, v, kp, vp, tables, pos = self._paged(2, 32, 2, 8, 8)
        got = da.gather_kv_pages(kp, tables)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(k))

    def test_paged_matches_unpaged(self):
        """Attention through a scrambled block table == attention over
        the contiguous cache it encodes."""
        q, k, v, kp, vp, tables, pos = self._paged(2, 32, 2, 8, 8)
        ref = da.decode_attention(q, k, v, pos, impl="jax")
        out = da.paged_decode_attention(q, kp, vp, tables, pos,
                                        impl="jax")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_reference_and_auto_agree(self):
        q, k, v, kp, vp, tables, pos = self._paged(2, 64, 2, 16, 16,
                                                   seed=3)
        ref = da.reference_paged_decode_attention(q, kp, vp, tables,
                                                  pos)
        out = da.paged_decode_attention(q, kp, vp, tables, pos)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_masks_beyond_pos(self):
        """Blocks past pos — including live blocks of OTHER sequences
        in the shared pool — must not leak in."""
        q, k, v, kp, vp, tables, pos = self._paged(2, 32, 2, 8, 8)
        # corrupt everything strictly past each row's pos
        kp2, vp2 = np.array(kp), np.array(vp)
        for i in range(2):
            p = int(pos[i])
            for j in range((p // 8), 4):
                off = p + 1 - j * 8
                if off < 8:
                    kp2[tables[i, j], max(off, 0):] = 1e4
                    vp2[tables[i, j], max(off, 0):] = -1e4
        out = da.paged_decode_attention(q, kp, vp, tables, pos,
                                        impl="jax")
        out2 = da.paged_decode_attention(q, jnp.asarray(kp2),
                                         jnp.asarray(vp2), tables, pos,
                                         impl="jax")
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


# ---------------------------------------------------------------------------
# paged model path
# ---------------------------------------------------------------------------

class TestPagedModelPath:
    def test_chunked_prefill_then_decode_matches_full_forward(self,
                                                              setup):
        """Prefill in 2 chunks through a scrambled table, then decode
        greedily — token-for-token equal to full-forward rollout."""
        cfg, params = setup
        bs, chunks = 8, (8, 4)
        prompt = list(np.random.default_rng(0).integers(
            0, cfg.vocab_size, 12))
        pool = gpt.init_kv_pool(cfg, 8, bs)
        table = np.array([5, 2, 7, 1], np.int32)
        start = 0
        for clen in chunks:
            toks = np.zeros((1, 8), np.int32)
            toks[0, :clen] = prompt[start:start + clen]
            logits, pool = gpt.prefill_paged(
                params, jnp.asarray(toks), pool, cfg,
                block_table=jnp.asarray(table), start=start,
                length=jnp.int32(clen))
            start += clen
        toks_out, cur = [], int(jnp.argmax(logits[0]))
        tables = jnp.asarray(table)[None]
        for t in range(len(prompt), len(prompt) + 6):
            toks_out.append(cur)
            logits, pool = gpt.decode_step_paged(
                params, jnp.asarray([cur], jnp.int32), pool,
                jnp.asarray([t], jnp.int32), tables, cfg)
            cur = int(jnp.argmax(logits[0]))
        assert toks_out == rollout_reference(params, prompt, cfg, 6)

    def test_copy_block(self, setup):
        cfg, params = setup
        pool = gpt.init_kv_pool(cfg, 4, 8)
        pool = {k: v + jnp.arange(4, dtype=v.dtype)[None, :, None,
                                                    None, None]
                for k, v in pool.items()}
        out = gpt.copy_block(pool, 3, 1)
        np.testing.assert_array_equal(np.asarray(out["k"][:, 1]),
                                      np.asarray(out["k"][:, 3]))
        np.testing.assert_array_equal(np.asarray(out["v"][:, 2]),
                                      2 * np.ones_like(
                                          np.asarray(out["v"][:, 2])))

    def test_pool_sharding_specs(self, setup):
        from ray_tpu.parallel import MeshSpec
        from ray_tpu.parallel.sharding import kv_pool_specs
        cfg, _ = setup
        mesh = MeshSpec(data=-1).build(jax.devices())
        specs = kv_pool_specs(mesh)
        assert set(specs) == {"k", "v"}
        pool = gpt.init_kv_pool(tiny_cfg(n_layers=1), 4, 8, mesh=mesh)
        assert pool["k"].sharding.spec == specs["k"]


# ---------------------------------------------------------------------------
# block allocator
# ---------------------------------------------------------------------------

class TestBlockAllocator:
    def test_alloc_free_cycle(self):
        a = BlockAllocator(5)        # blocks 1..4 usable
        got = [a.alloc() for _ in range(4)]
        assert sorted(got) == [1, 2, 3, 4]
        assert a.free == 0 and a.used == 4
        with pytest.raises(RuntimeError, match="out of"):
            a.alloc()
        for b in got:
            a.decref(b)
        assert a.free == 4 and a.used == 0
        a.check()

    def test_refcounts(self):
        a = BlockAllocator(3)
        b = a.alloc()
        a.ref(b)
        assert a.refcount(b) == 2
        a.decref(b)
        assert a.used == 1           # still held once
        a.decref(b)
        assert a.used == 0

    def test_double_free_raises(self):
        a = BlockAllocator(3)
        b = a.alloc()
        a.decref(b)
        with pytest.raises(RuntimeError, match="double free"):
            a.decref(b)
        with pytest.raises(RuntimeError, match="ref of free"):
            a.ref(b)
        with pytest.raises(RuntimeError):
            a.decref(0)              # trash block is never freeable
        a.check()

    def test_too_small(self):
        with pytest.raises(ValueError):
            BlockAllocator(1)


# ---------------------------------------------------------------------------
# radix tree
# ---------------------------------------------------------------------------

class TestRadixTree:
    def _tree(self, bs=4, n=32):
        a = BlockAllocator(n)
        return RadixTree(bs, a), a

    def test_insert_match_aligned(self):
        t, a = self._tree()
        x = list(range(8))
        bx = [a.alloc(), a.alloc()]
        t.insert(x, bx)
        assert t.match(x) == (bx, 8)
        assert t.match(x[:4]) == (bx[:1], 4)
        assert t.match(x + [99]) == (bx, 8)
        assert t.match([99]) == ([], 0)
        assert a.refcount(bx[0]) == 2    # ours + the tree's

    def test_partial_block_match(self):
        t, a = self._tree()
        x = list(range(8))
        bx = [a.alloc(), a.alloc()]
        t.insert(x, bx)
        blocks, m = t.match([0, 1, 2, 3, 4, 5, 77])
        assert m == 6                    # diverges inside block 2
        assert blocks == bx              # last block shared partially

    def test_split_on_divergence(self):
        t, a = self._tree()
        x = list(range(8))
        bx = [a.alloc(), a.alloc()]
        t.insert(x, bx)
        y = x[:4] + [9, 9, 9, 9]
        c = a.alloc()
        t.insert(y, [bx[0], c])          # engine passes shared + own
        assert t.n_nodes() == 3          # split: upper + two tails
        assert t.match(x) == (bx, 8)
        assert t.match(y) == ([bx[0], c], 8)
        assert a.refcount(bx[0]) == 2    # shared head ref'd ONCE by tree
        assert a.refcount(c) == 2

    def test_insert_existing_is_noop(self):
        t, a = self._tree()
        x = list(range(8))
        bx = [a.alloc(), a.alloc()]
        t.insert(x, bx)
        t.insert(x, bx)
        assert t.n_nodes() == 1
        assert a.refcount(bx[0]) == 2

    def test_evict_lru_zero_ref_leaves(self):
        t, a = self._tree()
        x = list(range(8))
        bx = [a.alloc(), a.alloc()]
        t.insert(x, bx)
        y = x[:4] + [9, 9, 9, 9]
        c = a.alloc()
        t.insert(y, [bx[0], c])
        for b in (*bx, c):               # drop our refs: tree-only now
            a.decref(b)
        t.match(y)                       # y's path is most recent
        assert t.evict(1) == 1           # LRU victim: x's tail [bx[1]]
        assert t.match(x) == ([bx[0]], 4)
        assert t.match(y) == ([bx[0], c], 8)
        # referenced blocks are never evicted
        a.ref(c)
        assert t.evict(10) == 0
        a.decref(c)
        t.clear()
        assert t.n_blocks() == 0 and a.used == 0


# ---------------------------------------------------------------------------
# engine: prefix sharing
# ---------------------------------------------------------------------------

class TestPrefixSharing:
    def test_shared_system_prompt_prefilled_once(self, setup):
        """The acceptance criterion: two requests sharing a 16-token
        system prompt prefill it exactly once — asserted via the
        engine's prefill-token counter — and both still decode exactly
        what a cold engine decodes."""
        cfg, params = setup
        rng = np.random.default_rng(7)
        sys_p = list(rng.integers(0, cfg.vocab_size, 16))
        a = sys_p + list(rng.integers(0, cfg.vocab_size, 4))
        b = sys_p + list(rng.integers(0, cfg.vocab_size, 4))

        eng = make_engine(cfg, params)
        ra = eng.submit(a, max_new_tokens=4)
        rb = eng.submit(b, max_new_tokens=4)
        eng.run_until_idle()
        s = eng.stats()
        # a: 20 prefilled; b: only its 4-token suffix
        assert s["prefill_tokens"] == len(a) + 4
        assert s["prefix_hit_tokens"] == 16
        assert s["prefix_hit_rate"] == pytest.approx(16 / 40)
        got_a = [eng._out[ra].popleft() for _ in range(4)]
        got_b = [eng._out[rb].popleft() for _ in range(4)]
        assert got_a == rollout_reference(params, a, cfg, 4)
        assert got_b == rollout_reference(params, b, cfg, 4)
        eng.check_invariants()

    def test_cow_on_mid_block_divergence(self, setup):
        """A prefix that diverges inside a cached block is shared
        copy-on-write: one device block copy, identical tokens."""
        cfg, params = setup
        rng = np.random.default_rng(11)
        x = list(rng.integers(0, cfg.vocab_size, 16))
        y = x[:12] + list(rng.integers(0, cfg.vocab_size, 4))
        eng = make_engine(cfg, params)
        got_x = eng.generate(x, max_new_tokens=3)
        got_y = eng.generate(y, max_new_tokens=3)
        s = eng.stats()
        assert s["cow_copies"] == 1
        assert s["prefix_hit_tokens"] == 12
        assert got_x == rollout_reference(params, x, cfg, 3)
        assert got_y == rollout_reference(params, y, cfg, 3)
        eng.check_invariants()

    def test_decode_compiles_once_with_sharing(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(5)
        sys_p = list(rng.integers(0, cfg.vocab_size, 8))
        eng = make_engine(cfg, params)
        for i in range(4):
            tail = list(rng.integers(0, cfg.vocab_size, 2 + i))
            eng.generate(sys_p + tail, max_new_tokens=3)
        assert eng.decode_traces == 1
        assert eng.stats()["prefix_hit_tokens"] > 0

    def test_prefix_cache_off(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params, prefix_cache=False)
        p = list(range(1, 17))
        g1 = eng.generate(p, max_new_tokens=3)
        g2 = eng.generate(p, max_new_tokens=3)
        assert g1 == g2
        s = eng.stats()
        assert s["prefix_hit_tokens"] == 0
        assert s["prefill_tokens"] == 32
        # nothing cached → pool drains completely between requests
        assert s["blocks_in_use"] == 0


# ---------------------------------------------------------------------------
# engine: chunked prefill
# ---------------------------------------------------------------------------

class TestChunkedPrefill:
    def test_admission_never_stalls_decode_more_than_one_chunk(
            self, setup):
        """While a long prompt is being admitted, every scheduler tick
        still advances the in-flight stream by one token and runs at
        most ONE prefill chunk."""
        cfg, params = setup
        eng = make_engine(cfg, params, prefill_chunk=8,
                          prefix_cache=False)
        eng.submit(list(range(1, 5)), max_new_tokens=24)
        eng.step()                      # admit + drain tiny prefill
        assert eng.stats()["decode_steps"] == 1
        # now a 24-token prompt arrives: 3 chunks of 8
        eng.submit(list(range(40, 64)), max_new_tokens=2)
        for tick in range(1, 4):
            before = eng.stats()
            eng.step()
            s = eng.stats()
            assert s["prefill_chunks"] - before["prefill_chunks"] == 1
            assert s["decode_steps"] - before["decode_steps"] == 1
        assert s["prefill_chunks"] == 4     # 1 warm + 3 chunked
        assert s["max_admission_stall_ms"] > 0.0
        eng.run_until_idle()
        eng.check_invariants()

    def test_idle_engine_drains_prefill_freely(self, setup):
        """With nothing decoding there is nobody to stall: one tick
        absorbs every pending chunk."""
        cfg, params = setup
        eng = make_engine(cfg, params, prefill_chunk=8,
                          prefix_cache=False)
        eng.submit(list(range(1, 25)), max_new_tokens=2)
        eng.step()
        s = eng.stats()
        assert s["prefill_chunks"] == 3
        assert s["prefill_tokens"] == 24

    def test_long_prompt_beyond_buckets_decodes_correctly(self, setup):
        """Chunking removed the bucket-length admission limit: a prompt
        longer than the largest prefill bucket works and matches the
        full-forward rollout."""
        cfg, params = setup
        prompt = list(np.random.default_rng(3).integers(
            0, cfg.vocab_size, 26))
        eng = make_engine(cfg, params, prefill_chunk=8)
        assert eng.generate(prompt, max_new_tokens=4) == \
            rollout_reference(params, prompt, cfg, 4)


# ---------------------------------------------------------------------------
# engine: cancellation and cleanup
# ---------------------------------------------------------------------------

class TestCancel:
    def test_cancel_pending(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params)
        rid = eng.submit([1, 2, 3], max_new_tokens=4)
        assert eng.cancel(rid)
        assert not eng.cancel(rid)      # idempotent
        s = eng.stats()
        assert s["pending"] == 0 and s["cancelled"] == 1
        eng.check_invariants()

    def test_cancel_mid_decode_releases_blocks(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params, prefix_cache=False)
        rid = eng.submit(list(range(1, 10)), max_new_tokens=20)
        for _ in range(3):
            eng.step()
        assert eng.stats()["blocks_in_use"] > 0
        assert eng.cancel(rid)
        s = eng.stats()
        assert s["blocks_in_use"] == 0 and s["active"] == 0
        assert rid not in eng._out
        eng.check_invariants()

    def test_cancel_finished_undrained(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params)
        rid = eng.submit([4, 5, 6], max_new_tokens=3)
        eng.run_until_idle()
        assert len(eng._out[rid]) == 3
        assert eng.cancel(rid)
        assert rid not in eng._out and rid not in eng._done

    def test_abandoned_stream_releases_request(self, setup):
        """Breaking out of `tokens_for` (generator finalization) must
        cancel the request and free its blocks — the leak named in the
        issue."""
        cfg, params = setup
        eng = make_engine(cfg, params, prefix_cache=False)
        rid = eng.submit(list(range(1, 9)), max_new_tokens=20)
        it = eng.tokens_for(rid)
        next(it)
        assert eng.stats()["active"] == 1
        it.close()                      # walk away mid-stream
        s = eng.stats()
        assert s["active"] == 0 and s["blocks_in_use"] == 0
        assert s["cancelled"] == 1 and rid not in eng._out
        eng.check_invariants()

    def test_engine_continues_after_cancel(self, setup):
        """Cancelling one stream must not disturb a co-resident one."""
        cfg, params = setup
        p = list(range(20, 28))
        eng = make_engine(cfg, params, prefix_cache=False)
        keep = eng.submit(p, max_new_tokens=6)
        kill = eng.submit(list(range(1, 9)), max_new_tokens=6)
        eng.step()
        eng.cancel(kill)
        eng.run_until_idle()
        got = [eng._out[keep].popleft() for _ in range(6)]
        assert got == rollout_reference(params, p, cfg, 6)

    def test_cancel_mid_spec_frees_draft_blocks(self, setup):
        """Cancel during a mid-flight speculative run (draft backend)
        must free BOTH pools' blocks and roll the slot back cleanly."""
        cfg, params = setup
        eng = make_engine(cfg, params, spec="draft", spec_k=3,
                          draft_params=params, draft_cfg=cfg)
        keep = eng.submit(list(range(20, 29)), max_new_tokens=12)
        kill = eng.submit(list(range(1, 8)), max_new_tokens=12)
        it = eng.tokens_for(keep)
        for _ in range(3):       # both slots are decoding speculatively
            next(it)
        assert eng._draft_alloc.used > 0
        assert eng.cancel(kill)
        eng.check_invariants()   # covers the draft allocator too
        rest = list(it)
        assert len(rest) == 12 - 3
        eng.run_until_idle()
        eng.check_invariants()
        assert eng._draft_alloc.used == 0
        assert eng.stats()["blocks_in_use"] == 0 or \
            eng.stats()["cached_prefix_blocks"] > 0

    def test_abandoned_stream_mid_spec(self, setup):
        """Generator abandonment mid-speculation releases draft blocks
        (the spec-path extension of the abandoned-stream regression)."""
        cfg, params = setup
        eng = make_engine(cfg, params, prefix_cache=False, spec="draft",
                          spec_k=2, draft_params=params, draft_cfg=cfg)
        rid = eng.submit(list(range(1, 9)), max_new_tokens=20)
        it = eng.tokens_for(rid)
        next(it)
        assert eng._draft_alloc.used > 0
        it.close()
        eng.check_invariants()
        s = eng.stats()
        assert s["active"] == 0 and s["blocks_in_use"] == 0
        assert eng._draft_alloc.used == 0 and s["cancelled"] == 1

    def test_cancel_mid_spec_ngram(self, setup):
        """Cancel mid-speculation on the n-gram backend: no draft pool
        involved, slot and main blocks roll back cleanly."""
        cfg, params = setup
        motif = [3, 7, 11, 13]
        eng = make_engine(cfg, params, prefix_cache=False, spec="ngram",
                          spec_k=4)
        rid = eng.submit(motif * 3, max_new_tokens=16)
        it = eng.tokens_for(rid)
        for _ in range(2):
            next(it)
        it.close()
        eng.check_invariants()
        s = eng.stats()
        assert s["active"] == 0 and s["blocks_in_use"] == 0


# ---------------------------------------------------------------------------
# engine: eviction under pressure
# ---------------------------------------------------------------------------

class TestEviction:
    def test_cached_prefix_evicted_under_pressure(self, setup):
        """A pool too small for two cached prompts evicts the zero-ref
        prefix instead of failing admission."""
        cfg, params = setup
        rng = np.random.default_rng(13)
        a = list(rng.integers(0, cfg.vocab_size, 16))
        b = list(rng.integers(0, cfg.vocab_size, 16))
        eng = make_engine(cfg, params, slots=1, cache_blocks=3)
        got_a = eng.generate(a, max_new_tokens=2)
        assert eng.stats()["blocks_in_use"] == 2   # a's prefix cached
        got_b = eng.generate(b, max_new_tokens=2)
        s = eng.stats()
        assert s["evicted_blocks"] >= 2
        assert got_a == rollout_reference(params, a, cfg, 2)
        assert got_b == rollout_reference(params, b, cfg, 2)
        eng.check_invariants()

    def test_admission_waits_when_pool_fully_referenced(self, setup):
        """When live requests hold every block, a newcomer stays
        pending (no eviction possible) and admits once one retires."""
        cfg, params = setup
        eng = make_engine(cfg, params, slots=2, cache_blocks=3,
                          prefix_cache=False)
        r1 = eng.submit(list(range(1, 17)), max_new_tokens=6)  # 3 blocks
        eng.step()
        r2 = eng.submit(list(range(30, 46)), max_new_tokens=6)
        eng.step()
        assert eng.stats()["pending"] == 1      # pool exhausted by r1
        eng.run_until_idle()
        assert len(eng._out[r1]) == 6 and len(eng._out[r2]) == 6
        eng.check_invariants()


# ---------------------------------------------------------------------------
# fuzz: admit / cancel / retire
# ---------------------------------------------------------------------------

def _fuzz(setup, ops, seed, **engine_kw):
    """Random submit/cancel/step/drain storm over a small-alphabet
    token space (to force radix collisions, splits, COW and eviction),
    checking allocator/tree/slot invariants after every operation."""
    cfg, params = setup
    eng = make_engine(cfg, params, slots=3, cache_blocks=9,
                      **engine_kw)
    rng = np.random.default_rng(seed)
    live = []
    for _ in range(ops):
        op = rng.integers(0, 10)
        if op < 4:      # submit (small alphabet → shared prefixes)
            p = list(rng.integers(1, 5, int(rng.integers(1, 25))))
            mnt = int(rng.integers(1, 6))
            try:
                live.append(eng.submit(p, max_new_tokens=mnt))
            except ValueError:
                pass    # footprint exceeds the pool — fine
        elif op < 6 and live:   # cancel a random request
            eng.cancel(live.pop(int(rng.integers(0, len(live)))))
        elif op < 7 and live:   # drain one finished stream
            rid = live.pop(0)
            for _ in eng.tokens_for(rid):
                pass
        else:
            eng.step()
        eng.check_invariants()
    for rid in live:
        eng.cancel(rid)
    eng.run_until_idle()
    eng.check_invariants()
    s = eng.stats()
    assert s["active"] == 0 and s["pending"] == 0
    # every block still allocated is held by the prefix cache only
    assert s["blocks_in_use"] == s["cached_prefix_blocks"]
    if eng._tree is not None:
        eng._tree.clear()
    assert eng.stats()["blocks_in_use"] == 0
    eng.check_invariants()
    return s


def test_fuzz_small(setup):
    s = _fuzz(setup, ops=40, seed=0)
    assert s["decode_tokens"] > 0


def test_fuzz_small_no_prefix_cache(setup):
    _fuzz(setup, ops=30, seed=1, prefix_cache=False)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [2, 3, 4])
def test_fuzz_large(setup, seed):
    _fuzz(setup, ops=300, seed=seed)


# ---------------------------------------------------------------------------
# quantized KV (int8 payload, per-row scales)
# ---------------------------------------------------------------------------

def _peaked(params):
    """Sharpen the tiny random-init model's logits: they are near-uniform
    (greedy argmax gaps below int8 noise), so token-identity tests scale
    the embedding to restore a decisive winner at every step."""
    return {**params, "embed": params["embed"] * 8}


@pytest.fixture(scope="module")
def setup_q(setup):
    """kv_dtype="int8" config + peaked params (shapes are independent of
    kv_dtype, so the module fixture's params are reusable)."""
    return tiny_cfg(kv_dtype="int8"), _peaked(setup[1])


class TestQuantizedPagedAttention:
    def _quantized(self, b, s, h, d, bs, seed=0):
        q, k, v, kp, vp, tables, pos = TestPagedAttention()._paged(
            b, s, h, d, bs, seed=seed)
        kq, ksc = quant.quantize_rows(kp)
        vq, vsc = quant.quantize_rows(vp)
        return q, kp, vp, kq, ksc, vq, vsc, tables, pos

    def test_kernel_matches_reference(self):
        """Pallas (interpret on CPU) dequant-in-VMEM == gather-then-
        dequant reference on an int8 pool."""
        q, _, _, kq, ksc, vq, vsc, tables, pos = self._quantized(
            2, 64, 2, 16, 16, seed=5)
        ref = da.reference_paged_decode_attention(
            q, kq, vq, tables, pos, k_scale=ksc, v_scale=vsc)
        out = da.paged_decode_attention(
            q, kq, vq, tables, pos, k_scale=ksc, v_scale=vsc,
            impl="pallas")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_quantized_close_to_f32(self):
        """Int8+scale attention lands within quantization noise of the
        f32 pool it was built from."""
        q, kp, vp, kq, ksc, vq, vsc, tables, pos = self._quantized(
            2, 32, 2, 8, 8, seed=1)
        f32 = da.paged_decode_attention(q, kp, vp, tables, pos,
                                        impl="jax")
        i8 = da.paged_decode_attention(
            q, kq, vq, tables, pos, k_scale=ksc, v_scale=vsc,
            impl="jax")
        np.testing.assert_allclose(np.asarray(i8), np.asarray(f32),
                                   atol=0.1, rtol=0.1)

    def test_roundtrip_is_deterministic(self):
        """Same f32 rows -> byte-identical int8 payload and scales on
        every call — the property that keeps batched verify bit-equal
        to sequential decode on a quantized pool."""
        x = jax.random.normal(jax.random.PRNGKey(2), (16, 4, 8))
        q1, s1 = quant.quantize_rows(x)
        q2, s2 = quant.quantize_rows(x)
        np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
        # zero rows must dequantize to exact zero, not NaN
        qz, sz = quant.quantize_rows(jnp.zeros((2, 3, 8)))
        assert not np.isnan(np.asarray(sz)).any()
        np.testing.assert_array_equal(
            np.asarray(quant.dequantize_rows(qz, sz)), 0.0)

    def test_scale_validation(self):
        """k_scale/v_scale are both-or-neither on every paged wrapper."""
        q, _, _, kq, ksc, vq, vsc, tables, pos = self._quantized(
            2, 32, 2, 8, 8)
        with pytest.raises(ValueError, match="both k_scale and v_scale"):
            da.paged_decode_attention(q, kq, vq, tables, pos,
                                      k_scale=ksc)
        with pytest.raises(ValueError):
            da.paged_decode_attention(
                q, kq, vq, tables, pos, k_scale=ksc[:, :4],
                v_scale=vsc)


class TestFusedPrefill:
    def _seq(self, s, h, d, bs, seed=0, quantize=False):
        """One sequence's K/V scattered into a scrambled single-table
        pool, plus its full query stack."""
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(ks[0], (s, h, d))
        k = jax.random.normal(ks[1], (s, h, d))
        v = jax.random.normal(ks[2], (s, h, d))
        mb = s // bs
        table = (np.random.default_rng(seed).permutation(mb) + 1) \
            .astype(np.int32)
        kp = np.zeros((mb + 1, bs, h, d), np.float32)
        vp = np.zeros_like(kp)
        for j in range(mb):
            kp[table[j]] = np.asarray(k[j * bs:(j + 1) * bs])
            vp[table[j]] = np.asarray(v[j * bs:(j + 1) * bs])
        kp, vp = jnp.asarray(kp), jnp.asarray(vp)
        if not quantize:
            return q, kp, vp, None, None, jnp.asarray(table)
        kq, ksc = quant.quantize_rows(kp)
        vq, vsc = quant.quantize_rows(vp)
        return q, kq, vq, ksc, vsc, jnp.asarray(table)

    @pytest.mark.parametrize("start,c", [(0, 32), (8, 8), (16, 5)])
    def test_pallas_matches_jax(self, start, c):
        """The fused (mq-kernel) path == the legacy dense gather+einsum,
        including a ragged tail chunk (c=5, padded rows discarded)."""
        q, kp, vp, _, _, table = self._seq(32, 2, 16, 8, seed=4)
        ref = da.paged_prefill_attention(q[start:start + c], kp, vp,
                                         table, start, impl="jax")
        pal = da.paged_prefill_attention(q[start:start + c], kp, vp,
                                         table, start, impl="pallas")
        np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("start,c", [(0, 16), (8, 5)])
    def test_pallas_matches_jax_quantized(self, start, c):
        q, kq, vq, ksc, vsc, table = self._seq(16, 2, 16, 8, seed=7,
                                               quantize=True)
        ref = da.paged_prefill_attention(
            q[start:start + c], kq, vq, table, start,
            k_scale=ksc, v_scale=vsc, impl="jax")
        pal = da.paged_prefill_attention(
            q[start:start + c], kq, vq, table, start,
            k_scale=ksc, v_scale=vsc, impl="pallas")
        np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_rejects_bad_rank(self):
        with pytest.raises(ValueError, match="paged_prefill_attention"):
            da.paged_prefill_attention(
                jnp.zeros((4, 16)), jnp.zeros((4, 8, 2, 16)),
                jnp.zeros((4, 8, 2, 16)), jnp.zeros((4,), jnp.int32), 0)


class TestQuantizedModelPath:
    def test_pool_layout(self, setup_q):
        cfg, _ = setup_q
        pool = gpt.init_kv_pool(cfg, 6, 8)
        assert set(pool) == {"k", "v", "k_scale", "v_scale"}
        assert pool["k"].dtype == jnp.int8
        assert pool["k_scale"].dtype == jnp.float32
        assert pool["k_scale"].shape == pool["k"].shape[:-1]

    def test_f32_pool_unchanged(self, setup):
        """kv_dtype="f32" (the default) keeps the legacy two-array pool
        — no scale arrays, no dtype change."""
        cfg, _ = setup
        pool = gpt.init_kv_pool(cfg, 6, 8)
        assert set(pool) == {"k", "v"}
        assert pool["k"].dtype == jnp.dtype(cfg.dtype)

    def test_bad_kv_dtype_rejected(self, setup):
        with pytest.raises(ValueError, match="kv_dtype"):
            gpt.init_kv_pool(tiny_cfg(kv_dtype="int4"), 6, 8)

    def test_copy_block_carries_scales(self, setup_q):
        """COW block copies move the scale rows with the payload."""
        cfg, _ = setup_q
        pool = gpt.init_kv_pool(cfg, 4, 8)
        pool = {name: arr + jnp.arange(4, dtype=arr.dtype).reshape(
                    (1, 4) + (1,) * (arr.ndim - 2))
                for name, arr in pool.items()}
        out = gpt.copy_block(pool, 3, 1)
        for name in ("k", "v", "k_scale", "v_scale"):
            np.testing.assert_array_equal(np.asarray(out[name][:, 1]),
                                          np.asarray(out[name][:, 3]))
        np.testing.assert_array_equal(
            np.asarray(out["k_scale"][:, 2]),
            2 * np.ones_like(np.asarray(out["k_scale"][:, 2])))

    def test_pool_sharding_specs_quantized(self):
        from ray_tpu.parallel import MeshSpec
        from ray_tpu.parallel.sharding import kv_pool_specs
        mesh = MeshSpec(data=-1).build(jax.devices())
        specs = kv_pool_specs(mesh, quantized=True)
        assert set(specs) == {"k", "v", "k_scale", "v_scale"}
        pool = gpt.init_kv_pool(tiny_cfg(n_layers=1, kv_dtype="int8"),
                                4, 8, mesh=mesh)
        assert pool["k_scale"].sharding.spec == specs["k_scale"]

    def test_prefill_decode_greedy_matches_f32(self, setup):
        """The tentpole criterion at the model-path level: chunked
        prefill + greedy decode through an int8 pool emits the exact
        tokens of the f32 pool AND the full-forward rollout."""
        params = _peaked(setup[1])
        prompt = list(np.random.default_rng(0).integers(
            0, 128, 12))

        def run(cfg):
            pool = gpt.init_kv_pool(cfg, 8, 8)
            table = np.array([5, 2, 7, 1], np.int32)
            start = 0
            for clen in (8, 4):
                toks = np.zeros((1, 8), np.int32)
                toks[0, :clen] = prompt[start:start + clen]
                logits, pool = gpt.prefill_paged(
                    params, jnp.asarray(toks), pool, cfg,
                    block_table=jnp.asarray(table), start=start,
                    length=jnp.int32(clen))
                start += clen
            out, cur = [], int(jnp.argmax(logits[0]))
            tables = jnp.asarray(table)[None]
            for t in range(len(prompt), len(prompt) + 6):
                out.append(cur)
                logits, pool = gpt.decode_step_paged(
                    params, jnp.asarray([cur], jnp.int32), pool,
                    jnp.asarray([t], jnp.int32), tables, cfg)
                cur = int(jnp.argmax(logits[0]))
            return out

        got_q = run(tiny_cfg(kv_dtype="int8"))
        got_f = run(tiny_cfg())
        assert got_q == got_f == rollout_reference(
            params, prompt, tiny_cfg(), 6)

    def test_quantize_params_layout(self, setup):
        """Weight-only int8: every matmul weight gains a per-output-
        channel scale sibling; norms/embeddings stay f32 masters."""
        _, params = setup
        qp = gpt.quantize_params(params)
        for name in gpt.QUANTIZED_WEIGHTS:
            w = qp["layers"][name]
            s = qp["layers"][name + "_scale"]
            assert w.dtype == jnp.int8
            assert s.shape == w.shape[:-2] + w.shape[-1:]
        assert qp["embed"].dtype == params["embed"].dtype
        assert qp["layers"]["ln1_scale"].dtype == jnp.float32


class TestQuantizedEngine:
    def test_greedy_token_identical_to_f32(self, setup, setup_q):
        """Engine-level tentpole criterion: int8-KV greedy decode is
        token-identical to the f32 engine across a shared aligned
        prefix AND a mid-block COW divergence."""
        cfg_q, params = setup_q
        cfg_f = tiny_cfg()
        rng = np.random.default_rng(21)
        x = list(rng.integers(0, 128, 16))
        y = x[:12] + list(rng.integers(0, 128, 4))   # COW split
        z = x + list(rng.integers(0, 128, 4))        # aligned extend

        def run(cfg):
            eng = make_engine(cfg, params)
            outs = [eng.generate(p, max_new_tokens=6) for p in
                    (x, y, z)]
            eng.check_invariants()
            return outs, eng.stats()

        got_q, sq = run(cfg_q)
        got_f, sf = run(cfg_f)
        assert got_q == got_f
        assert got_q[0] == rollout_reference(params, x, cfg_f, 6)
        assert sq["cow_copies"] >= 1 and sq["prefix_hit_tokens"] > 0
        assert sq["decode_traces"] == 1

    def test_weight_int8_quality_and_swap(self, setup):
        """Weight-only int8: greedy logprobs stay tight-allclose to the
        f32 engine (the pinned quality bound), the quantize executable
        compiles exactly once, and a same-shape update_params reuses it
        (RL-flywheel swap path, zero retraces)."""
        cfg_f = tiny_cfg()
        cfg_w = tiny_cfg(weight_dtype="int8")
        params = _peaked(setup[1])
        prompt = list(np.random.default_rng(23).integers(0, 128, 10))
        eng_w = make_engine(cfg_w, params)
        eng_f = make_engine(cfg_f, params)
        a = eng_w.generate(prompt, max_new_tokens=8)
        b = eng_f.generate(prompt, max_new_tokens=8)
        assert list(a) == list(b)           # peaked logits: same argmax
        deltas = [abs(x.logprob - y.logprob) for x, y in zip(a, b)]
        assert max(deltas) < 0.05
        assert eng_w.quantize_traces == 1
        assert eng_w.stats()["quantize_traces"] == 1
        eng_w.update_params(params)         # same shapes: no retrace
        assert eng_w.quantize_traces == 1
        assert list(eng_w.generate(prompt, max_new_tokens=8)) == list(b)
        eng_w.check_invariants()

    def test_pool_gauges(self, setup, setup_q):
        """`pool_bytes`/`kv_bytes_per_token` report the int8 shrink:
        payload bytes per token drop from 4 per element to 1 + the
        amortized scale column."""
        cfg_q, params = setup_q
        sq = make_engine(cfg_q, params).stats()
        sf = make_engine(tiny_cfg(), params).stats()
        assert 0 < sq["pool_bytes"] < sf["pool_bytes"]
        hd = cfg_q.head_dim
        assert sf["kv_bytes_per_token"] / sq["kv_bytes_per_token"] == \
            pytest.approx(4 * hd / (hd + 4))
        # engine invariants audit the scale arrays alongside payloads
        eng = make_engine(cfg_q, params)
        eng.generate([1, 2, 3, 4], max_new_tokens=3)
        eng.check_invariants()


def test_fuzz_small_quantized(setup):
    """The admit/cancel/retire storm on an int8 pool: COW, eviction and
    abandonment with `check_invariants` auditing scale arrays after
    every operation."""
    s = _fuzz((tiny_cfg(kv_dtype="int8"), setup[1]), ops=40, seed=0)
    assert s["decode_tokens"] > 0


@pytest.mark.slow
@pytest.mark.parametrize("seed", [5, 6])
def test_fuzz_large_quantized(setup, seed):
    _fuzz((tiny_cfg(kv_dtype="int8"), setup[1]), ops=300, seed=seed)
