"""Priority-class admission + block-pressure preemption tests: a
preempted greedy stream resumes bitwise token-identical to an
unpreempted run (plain, shared-prefix/COW, and both spec-decode
backends), weighted-share admission ordering with aging (no class ever
starves), class-ordered shedding (lowest queued class evicted first,
same-class behavior unchanged), the seeded engine fault sites
(`engine.alloc` exhaustion drives exactly the planned preemptions;
same seed => identical `fired()` replay), and preempt→resume→cancel
interleavings audited by `check_invariants`."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.exceptions import OverloadedError
from ray_tpu.models import gpt
from ray_tpu.serve.engine import InferenceEngine
from ray_tpu.util import faults


def tiny_cfg(**kw):
    return gpt.GPTConfig(**{**dict(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype="float32"), **kw})


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def make_engine(cfg, params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("block_size", 4)
    return InferenceEngine(params, cfg, **kw)


def drain(eng, rid):
    return [int(t) for t in eng.tokens_for(rid)]


def run_all(eng, steps=500):
    for _ in range(steps):
        if not eng.step():
            return
    raise AssertionError("engine did not go idle")


PROMPT = np.arange(1, 9, dtype=np.int32)          # 8 tokens = 2 blocks


# ---------------------------------------------------------------------------
# token-identical resume
# ---------------------------------------------------------------------------

class TestTokenIdenticalResume:
    def _baseline(self, cfg, params, prompt, n, **kw):
        eng = make_engine(cfg, params, **kw)
        rid = eng.submit(prompt, max_new_tokens=n)
        out = [(int(t), t.logprob) for t in eng.tokens_for(rid)]
        return out

    def test_block_pressure_preempt_token_identical(self, setup):
        """Real block pressure: pool sized so a class-2 arrival can only
        be served by evicting the decoding class-0 stream; the class-0
        consumer still sees the exact unpreempted token sequence AND
        logprobs."""
        cfg, params = setup
        base = self._baseline(cfg, params, PROMPT, 6, cache_blocks=32)
        # 4 blocks per request (prompt 8 + new 6 over block 4);
        # cache_blocks=7 leaves 6 usable (block 0 is trash) — one
        # stream fits, two can't.
        eng = make_engine(cfg, params, cache_blocks=7)
        ra = eng.submit(PROMPT, max_new_tokens=6, priority=0)
        for _ in range(4):      # let the low class reach decode
            eng.step()
        rb = eng.submit(np.full(8, 9, np.int32), max_new_tokens=6,
                        priority=2)
        run_all(eng)
        s = eng.stats()
        assert s["preemptions"] >= 1
        assert s["per_class"]["0"]["preemptions"] >= 1
        got = [(int(t), t.logprob) for t in eng.tokens_for(ra)]
        assert got == base
        assert len(drain(eng, rb)) == 6
        eng.check_invariants()

    def test_forced_preempt_site_token_identical(self, setup):
        """`engine.preempt` fault site: eviction with zero real
        pressure — pure resume-path coverage, no pool math involved."""
        cfg, params = setup
        base = self._baseline(cfg, params, PROMPT, 6, cache_blocks=32)
        faults.install(faults.FaultPlan(seed=3).fail(
            "engine.preempt", at=2, times=1))
        eng = make_engine(cfg, params, cache_blocks=32)
        rid = eng.submit(PROMPT, max_new_tokens=6, priority=0)
        run_all(eng)
        assert eng.stats()["preemptions"] == 1
        assert [(int(t), t.logprob) for t in eng.tokens_for(rid)] == base
        eng.check_invariants()

    def test_shared_prefix_cow_preempt_token_identical(self, setup):
        """The victim shares prefix blocks with a sibling stream (radix
        refs + COW on divergence). Preemption must release only the
        victim's non-shared holds, and the resume — which re-admits the
        shared prefix by reference — must stay token-identical while
        the sibling decodes on."""
        cfg, params = setup
        shared = np.arange(1, 9, dtype=np.int32)        # 2 full blocks
        pa = np.concatenate([shared, [20, 21, 22, 23]]).astype(np.int32)
        pb = np.concatenate([shared, [30, 31, 32, 33]]).astype(np.int32)
        base_a = self._baseline(cfg, params, pa, 6, cache_blocks=64)
        base_b = self._baseline(cfg, params, pb, 6, cache_blocks=64)
        eng = make_engine(cfg, params, cache_blocks=64)
        ra = eng.submit(pa, max_new_tokens=6, priority=0)
        rb = eng.submit(pb, max_new_tokens=6, priority=1)
        for _ in range(2):      # both admitted, prefix shared, decoding
            eng.step()
        faults.install(faults.FaultPlan(seed=5).fail(
            "engine.preempt", at=0, times=1))
        run_all(eng)
        assert eng.stats()["preemptions"] == 1
        # the class-0 stream was the victim; both match their baselines
        assert [(int(t), t.logprob) for t in eng.tokens_for(ra)] == base_a
        assert [(int(t), t.logprob) for t in eng.tokens_for(rb)] == base_b
        eng.check_invariants()

    @pytest.mark.parametrize("spec", ["ngram", "draft"])
    def test_spec_backend_preempt_token_identical(self, setup, spec):
        cfg, params = setup
        kw = {"spec": spec, "spec_k": 3}
        if spec == "draft":
            dcfg = tiny_cfg(n_layers=1)
            kw["draft_cfg"] = dcfg
            kw["draft_params"] = gpt.init_params(
                jax.random.PRNGKey(1), dcfg)
        motif = np.tile([5, 6, 7, 8], 2).astype(np.int32)
        base = self._baseline(cfg, params, motif, 8,
                              cache_blocks=32, **kw)
        faults.install(faults.FaultPlan(seed=9).fail(
            "engine.preempt", at=3, times=1))
        eng = make_engine(cfg, params, cache_blocks=32, **kw)
        rid = eng.submit(motif, max_new_tokens=8, priority=0)
        run_all(eng)
        assert eng.stats()["preemptions"] == 1
        assert [(int(t), t.logprob) for t in eng.tokens_for(rid)] == base
        eng.check_invariants()

    def test_mid_prefill_preempt_token_identical(self, setup):
        """Victim caught while still chunk-prefilling (no tokens emitted
        yet): the resume finishes the prefill and the stream is still
        exact."""
        cfg, params = setup
        long_prompt = np.arange(1, 17, dtype=np.int32)
        base = self._baseline(cfg, params, long_prompt, 4,
                              cache_blocks=32, prefill_chunk=4)
        faults.install(faults.FaultPlan(seed=2).fail(
            "engine.preempt", at=1, times=1))
        eng = make_engine(cfg, params, cache_blocks=32, prefill_chunk=4)
        rid = eng.submit(long_prompt, max_new_tokens=4, priority=0)
        run_all(eng)
        assert eng.stats()["preemptions"] == 1
        assert [(int(t), t.logprob) for t in eng.tokens_for(rid)] == base
        eng.check_invariants()


# ---------------------------------------------------------------------------
# admission ordering: weighted shares + aging
# ---------------------------------------------------------------------------

class TestAdmissionOrder:
    def _admission_sequence(self, eng, rids_by_class, steps=400):
        """Drive the engine one tick at a time and record the class of
        each newly-admitted rid, in order. With slots=1 a short request
        can be admitted AND retired inside one step() (prefill tick +
        decode tick), so completion order — observed via `_done` — is
        the admission order; still-active slots cover the in-flight
        one."""
        seen, order = set(), []
        for _ in range(steps):
            alive = eng.step()
            for s in eng._slots:
                if s.active and s.rid not in seen:
                    seen.add(s.rid)
                    order.append(rids_by_class[s.rid])
            for rid in eng._done:
                if rid not in seen:
                    seen.add(rid)
                    order.append(rids_by_class[rid])
            if not alive:
                break
        return order

    def test_weighted_shares_stride(self, setup):
        """slots=1, classes 0/1 backlogged together, weight base 2:
        the stride scheduler must interleave ~2 class-1 admissions per
        class-0 (never a starved run), not drain class 1 first."""
        cfg, params = setup
        eng = make_engine(cfg, params, slots=1, cache_blocks=64,
                          priority_classes=2, priority_weight_base=2.0,
                          priority_aging_s=3600.0)   # aging disarmed
        rids = {}
        for i in range(6):
            rids[eng.submit(PROMPT + i, max_new_tokens=2,
                            priority=0)] = 0
            rids[eng.submit(PROMPT + 10 + i, max_new_tokens=2,
                            priority=1)] = 1
        order = self._admission_sequence(eng, rids)
        assert len(order) == 12
        assert sorted(order[:3]) == [0, 1, 1], order
        # every prefix holds the 2:1 share (within one stride step)
        for k in range(1, 13):
            c1 = order[:k].count(1)
            if c1 < 6:
                assert c1 >= (2 * k) // 3 - 1, (k, order)
        eng.check_invariants()

    def test_aging_escalates_past_stride(self, setup):
        """A class-0 request older than its aging bound must be admitted
        AHEAD of fresher high-class traffic, even though stride order
        alone would pick class 1 first."""
        cfg, params = setup
        eng = make_engine(cfg, params, slots=1, cache_blocks=64,
                          priority_classes=2, priority_aging_s=0.01)
        rids = {}
        rids[eng.submit(PROMPT, max_new_tokens=2, priority=0)] = 0
        time.sleep(0.05)        # > (2 - 0) * 0.01 bound
        for i in range(3):
            rids[eng.submit(PROMPT + 10 + i, max_new_tokens=2,
                            priority=1)] = 1
        order = self._admission_sequence(eng, rids)
        assert order[0] == 0, order
        assert eng.stats()["aging_promotions"] >= 1
        eng.check_invariants()

    def test_no_starvation_under_sustained_high_load(self, setup):
        """Low-class requests submitted into a continuous stream of
        high-class traffic all complete, with queue wait bounded by the
        aging escalation (the acceptance criterion's starvation
        bound)."""
        cfg, params = setup
        aging_s = 0.2
        eng = make_engine(cfg, params, slots=1, cache_blocks=64,
                          priority_classes=3, priority_aging_s=aging_s)
        t0 = time.perf_counter()
        low = [eng.submit(PROMPT + i, max_new_tokens=2, priority=0)
               for i in range(3)]
        done_at = {}
        fed = 0
        for _ in range(3000):
            alive = eng.step()
            if fed < 30:        # sustained class-2 pressure
                eng.submit(PROMPT + 40 + (fed % 8), max_new_tokens=2,
                           priority=2)
                fed += 1
            for r in low:
                if r not in done_at and r in eng._done:
                    done_at[r] = time.perf_counter() - t0
            if not alive and fed >= 30:
                break
        assert set(done_at) == set(low), "low-class request starved"
        # worst-case wait is bounded: the aging escalation fires at
        # 3 * aging_s for class 0; generous slack for CPU jitter and
        # the in-flight stream it must still wait out
        bound = 3 * aging_s + 10.0
        assert all(w < bound for w in done_at.values()), done_at
        st = eng.stats()
        assert st["per_class"]["0"]["completed"] == 3
        assert st["per_class"]["2"]["completed"] == 30
        eng.check_invariants()


# ---------------------------------------------------------------------------
# class-ordered shedding
# ---------------------------------------------------------------------------

class TestClassOrderedShedding:
    def test_high_class_evicts_lowest_queued(self, setup):
        """Queue full: a class-2 submit sheds the newest class-0 QUEUED
        request (typed OverloadedError through its tokens_for) and takes
        its place — it does not shed itself."""
        cfg, params = setup
        eng = make_engine(cfg, params, slots=1, cache_blocks=64,
                          priority_classes=3, max_queue=2)
        ra = eng.submit(PROMPT, max_new_tokens=8, priority=0)
        eng.step()              # ra admitted — the queue is for rb/rv
        rb = eng.submit(PROMPT + 1, max_new_tokens=2, priority=0)
        rv = eng.submit(PROMPT + 2, max_new_tokens=2, priority=0)
        rh = eng.submit(PROMPT + 3, max_new_tokens=2, priority=2)
        run_all(eng)
        with pytest.raises(OverloadedError):
            drain(eng, rv)      # newest class-0 was the victim
        assert len(drain(eng, ra)) == 8
        for rid in (rb, rh):
            assert len(drain(eng, rid)) == 2
        s = eng.stats()
        assert s["sheds"] == 1
        assert s["per_class"]["0"]["sheds"] == 1
        eng.check_invariants()

    def test_same_class_sheds_incoming(self, setup):
        """All-one-class traffic keeps PR 12 semantics exactly: nothing
        queued ranks below the incoming request, so the incoming submit
        itself raises."""
        cfg, params = setup
        eng = make_engine(cfg, params, slots=1, cache_blocks=64,
                          max_queue=1)
        ra = eng.submit(PROMPT, max_new_tokens=8)
        eng.step()              # ra admitted
        rb = eng.submit(PROMPT + 1, max_new_tokens=2)
        with pytest.raises(OverloadedError):
            eng.submit(PROMPT + 2, max_new_tokens=2)
        run_all(eng)
        assert len(drain(eng, ra)) == 8 and len(drain(eng, rb)) == 2
        assert eng.stats()["sheds"] == 1
        eng.check_invariants()

    def test_shed_victim_error_is_consumed_once(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params, slots=1, cache_blocks=64,
                          priority_classes=2, max_queue=1)
        eng.submit(PROMPT, max_new_tokens=8, priority=0)
        eng.step()              # admitted; queue is for rv
        rv = eng.submit(PROMPT + 1, max_new_tokens=2, priority=0)
        eng.submit(PROMPT + 2, max_new_tokens=2, priority=1)
        with pytest.raises(OverloadedError):
            drain(eng, rv)
        # second poll: rid unknown now (error delivered and cleared) —
        # tokens_for's empty-stream contract, not a second raise
        assert rv not in eng._errors and rv not in eng._out
        assert drain(eng, rv) == []
        run_all(eng)
        eng.check_invariants()


# ---------------------------------------------------------------------------
# seeded engine fault sites
# ---------------------------------------------------------------------------

class TestEngineFaultSites:
    def _chaos_run(self, cfg, params, seed):
        faults.clear()
        faults.install(
            faults.FaultPlan(seed=seed)
            .fail("engine.preempt", p=0.25, times=None)
            .fail("engine.alloc", p=0.2, times=None))
        eng = make_engine(cfg, params, cache_blocks=32,
                          priority_classes=2)
        outs = []
        ra = eng.submit(PROMPT, max_new_tokens=4, priority=0)
        rb = eng.submit(PROMPT + 2, max_new_tokens=4, priority=1)
        run_all(eng, steps=2000)
        outs.append(drain(eng, ra))
        outs.append(drain(eng, rb))
        eng.check_invariants()
        log = faults.fired()
        faults.clear()
        return outs, log, eng.stats()["preemptions"]

    def test_same_seed_identical_fired_log(self, setup):
        """Replay determinism: an identical plan (same seed) fires at
        the identical (site, visit, action) sequence on two independent
        runs, and the engine output is identical too."""
        cfg, params = setup
        outs1, log1, p1 = self._chaos_run(cfg, params, seed=11)
        outs2, log2, p2 = self._chaos_run(cfg, params, seed=11)
        assert log1, "plan never fired — test is vacuous"
        assert log1 == log2
        assert outs1 == outs2 and p1 == p2
        # a different seed produces a different schedule
        _, log3, _ = self._chaos_run(cfg, params, seed=12)
        assert log3 != log1

    def test_alloc_exhaustion_exactly_planned_preemptions(self, setup):
        """The `engine.alloc` site refuses admission exactly where
        planned; each refused high-class admission preempts exactly one
        low-class victim — preemptions == planned failures."""
        cfg, params = setup
        # visits 0,1: the two low-class admissions. The high-class
        # request admits into the third (free) slot: visits 2,3 are the
        # planned failures, each preempting one decoding victim before
        # the retry; the post-preemption retry (visit 4) succeeds.
        faults.install(faults.FaultPlan(seed=1).fail(
            "engine.alloc", at=2, times=2))
        eng = make_engine(cfg, params, slots=3, cache_blocks=64,
                          priority_classes=3)
        ra = eng.submit(PROMPT, max_new_tokens=16, priority=0)
        rb = eng.submit(PROMPT + 1, max_new_tokens=16, priority=0)
        for _ in range(3):      # both low streams mid-decode
            eng.step()
        rh = eng.submit(PROMPT + 2, max_new_tokens=4, priority=2)
        run_all(eng)
        s = eng.stats()
        assert s["preemptions"] == 2, s["preemptions"]
        assert s["per_class"]["0"]["preemptions"] == 2
        assert [v for site, v, a in faults.fired()
                if site == "engine.alloc"] == [2, 3]
        assert len(drain(eng, rh)) == 4
        for rid in (ra, rb):
            assert len(drain(eng, rid)) == 16
        eng.check_invariants()

    def test_alloc_fault_without_victim_defers(self, setup):
        """Exhaustion with no lower-class active stream: the request
        just stays queued for the next tick — no preemption, no error
        to the consumer."""
        cfg, params = setup
        faults.install(faults.FaultPlan(seed=1).fail(
            "engine.alloc", at=0, times=1))
        eng = make_engine(cfg, params, cache_blocks=32)
        rid = eng.submit(PROMPT, max_new_tokens=4)
        run_all(eng)
        assert eng.stats()["preemptions"] == 0
        assert len(drain(eng, rid)) == 4
        eng.check_invariants()

    def test_tick_stall_site_feeds_watchdog(self, setup):
        """The tick-stall chaos site is `engine.tick` with a delay spec:
        the watchdog must count the wedged tick."""
        cfg, params = setup
        faults.install(faults.FaultPlan(seed=1).delay(
            "engine.tick", delay_s=0.25, at=1, times=1))
        eng = make_engine(cfg, params, cache_blocks=32, watchdog_s=0.05)
        rid = eng.submit(PROMPT, max_new_tokens=4)
        run_all(eng)
        assert len(drain(eng, rid)) == 4
        assert eng.stats()["watchdog_stalls"] >= 1
        assert ("engine.tick", 1, "delay") in faults.fired()
        eng.check_invariants()


# ---------------------------------------------------------------------------
# preempt / resume / cancel interleavings
# ---------------------------------------------------------------------------

class TestPreemptCancelInterleavings:
    def _free_blocks(self, eng):
        eng._tree.flush()
        return eng._alloc.free

    def test_cancel_while_resume_pending(self, setup):
        """Cancel lands while the preempted stream sits requeued under
        real block pressure (a forced preempt's resume would be
        re-admitted within the same tick — admission runs after the
        fault consult): everything — blocks, refcounts, _out queue —
        must be released."""
        cfg, params = setup
        # 6 usable blocks, 4 per stream: the class-2 arrival preempts
        # the class-0 stream, whose resume then can't re-admit until
        # the high stream finishes.
        eng = make_engine(cfg, params, cache_blocks=7,
                          priority_classes=3)
        total_free = self._free_blocks(eng)
        rid = eng.submit(PROMPT, max_new_tokens=6, priority=0)
        for _ in range(2):
            eng.step()          # admitted, decoding
        rh = eng.submit(PROMPT + 1, max_new_tokens=6, priority=2)
        eng.step()              # block pressure: rid preempted for rh
        assert eng.stats()["preemptions"] == 1
        assert any(q.rid == rid for q in eng._pending)   # resume queued
        assert eng.cancel(rid)
        run_all(eng)
        eng.check_invariants()
        assert rid not in eng._out
        assert len(drain(eng, rh)) == 6
        assert self._free_blocks(eng) == total_free

    def test_cancel_after_resume_readmitted(self, setup):
        cfg, params = setup
        faults.install(faults.FaultPlan(seed=4).fail(
            "engine.preempt", at=2, times=1))
        eng = make_engine(cfg, params, cache_blocks=32)
        total_free = self._free_blocks(eng)
        rid = eng.submit(PROMPT, max_new_tokens=8)
        for _ in range(5):      # preempt at tick 2, resume re-admitted
            eng.step()
        assert eng.stats()["preemptions"] == 1
        assert eng.cancel(rid)
        run_all(eng)
        eng.check_invariants()
        assert self._free_blocks(eng) == total_free

    def test_repeated_preempt_resume_fuzz(self, setup):
        """Probabilistic forced preemption over a multi-class workload:
        whatever interleaving of preempt/resume/finish happens, streams
        stay token-identical to their baselines, nothing leaks, and
        invariants hold after every tick."""
        cfg, params = setup
        base_eng = make_engine(cfg, params, cache_blocks=64)
        prompts = [(PROMPT + i, 4 + (i % 3)) for i in range(6)]
        base = {}
        for i, (p, n) in enumerate(prompts):
            r = base_eng.submit(p, max_new_tokens=n)
            base[i] = [int(t) for t in base_eng.tokens_for(r)]
        faults.install(faults.FaultPlan(seed=21).fail(
            "engine.preempt", p=0.3, times=None))
        eng = make_engine(cfg, params, cache_blocks=64,
                          priority_classes=3)
        total_free = self._free_blocks(eng)
        rids = {}
        for i, (p, n) in enumerate(prompts):
            rids[i] = eng.submit(p, max_new_tokens=n, priority=i % 3)
        for _ in range(2000):
            alive = eng.step()
            eng.check_invariants()
            if not alive:
                break
        else:
            raise AssertionError("chaos run never went idle")
        assert eng.stats()["preemptions"] >= 1
        for i in rids:
            assert drain(eng, rids[i]) == base[i], i
        eng.check_invariants()
        assert self._free_blocks(eng) == total_free

    def test_preempted_stream_readable_midflight(self, setup):
        """Tokens emitted before the preemption are already in the
        consumer's queue; the post-resume continuation lands in the SAME
        queue — one seamless stream."""
        cfg, params = setup
        faults.install(faults.FaultPlan(seed=6).fail(
            "engine.preempt", at=3, times=1))
        eng = make_engine(cfg, params, cache_blocks=32)
        rid = eng.submit(PROMPT, max_new_tokens=6)
        got = drain(eng, rid)   # pumps step() internally via tokens_for
        assert len(got) == 6
        assert eng.stats()["preemptions"] == 1
        eng.check_invariants()


# ---------------------------------------------------------------------------
# stats / telemetry plumbing
# ---------------------------------------------------------------------------

class TestPriorityStats:
    def test_per_class_counters_and_reset(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params, cache_blocks=64,
                          priority_classes=3)
        ra = eng.submit(PROMPT, max_new_tokens=3, priority=0)
        rb = eng.submit(PROMPT + 1, max_new_tokens=3, priority=2)
        run_all(eng)
        drain(eng, ra), drain(eng, rb)
        s = eng.stats()
        assert s["priority_classes"] == 3
        for c in ("0", "2"):
            pc = s["per_class"][c]
            assert pc["submitted"] == pc["completed"] == 1
            assert pc["decode_tokens"] == 3
            assert pc["queue_wait_ms_p99"] >= pc["queue_wait_ms_p50"] >= 0
        eng.reset_stats()
        s2 = eng.stats()
        assert s2["preemptions"] == s2["reprefill_blocks"] == 0
        assert s2["aging_promotions"] == 0
        assert all(v == 0 for pc in s2["per_class"].values()
                   for k, v in pc.items() if k.endswith(("ed", "s"))
                   and k not in ("pending", "active"))
        eng.check_invariants()

    def test_per_class_series_reach_metrics_bridge(self, setup):
        """The nested per_class dict fans out as class-tagged series on
        the Prometheus bridge (engine_per_class_*{class=...})."""
        cfg, params = setup
        from ray_tpu.util import metrics as _metrics
        from ray_tpu.util import telemetry as _telemetry
        eng = make_engine(cfg, params, cache_blocks=64,
                          priority_classes=2)
        name = _telemetry.register_stats_source(
            _telemetry.next_name("prio-test#"), eng, kind="engine")
        try:
            rid = eng.submit(PROMPT, max_new_tokens=3, priority=1)
            run_all(eng)
            drain(eng, rid)
            text = _metrics.render_prometheus(_metrics.snapshot())
            assert "engine_per_class_decode_tokens" in text
            assert 'class="1"' in text
            assert "engine_preemptions" in text
        finally:
            _telemetry.unregister_stats_source(name)

    def test_reprefill_blocks_counts_uncached_resume_blocks(self, setup):
        """With the radix tree publishing the victim's KV at preemption,
        the resume admits those blocks by reference — reprefill_blocks
        counts only what the cache could NOT cover (the not-yet-full
        trailing block)."""
        cfg, params = setup
        faults.install(faults.FaultPlan(seed=3).fail(
            "engine.preempt", at=2, times=1))
        eng = make_engine(cfg, params, cache_blocks=32)
        rid = eng.submit(PROMPT, max_new_tokens=6)
        run_all(eng)
        drain(eng, rid)
        s = eng.stats()
        assert s["preemptions"] == 1
        # resume footprint is 3-4 blocks; the shared prefix covers the
        # full ones, so the uncached tail is at most 2 blocks
        assert 0 <= s["reprefill_blocks"] <= 2
        eng.check_invariants()

    def test_priority_validation(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params, priority_classes=2)
        with pytest.raises(ValueError):
            eng.submit(PROMPT, max_new_tokens=2, priority=2)
        with pytest.raises(ValueError):
            eng.submit(PROMPT, max_new_tokens=2, priority=-1)
        with pytest.raises(ValueError):
            make_engine(cfg, params, priority_classes=0)
        with pytest.raises(ValueError):
            make_engine(cfg, params, priority_weight_base=0.5)
