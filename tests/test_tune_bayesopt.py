"""Native GP Bayesian-optimization searcher (reference surface:
tune/search/bayesopt/ wrapping the external package; here the GP-EI
loop is implemented in-repo)."""

import math
import random

from ray_tpu import tune
from ray_tpu.tune.bayesopt import BayesOptSearcher


def _quad(cfg):
    return (cfg["x"] - 0.3) ** 2 + (cfg["y"] + 0.1) ** 2


def _drive(searcher, objective, n, metric="loss"):
    best = math.inf
    for i in range(n):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        val = objective(cfg)
        searcher.on_trial_complete(tid, {metric: val})
        best = min(best, val)
    return best


def test_bayesopt_beats_random_on_quadratic():
    """Seeded head-to-head, 60 evaluations: the GP must beat pure random
    on every seed and land much closer at the median."""
    space = {"x": tune.uniform(-1.0, 1.0), "y": tune.uniform(-1.0, 1.0)}
    bo_bests, rand_bests = [], []
    for seed in (0, 7, 9):
        bo_bests.append(_drive(
            BayesOptSearcher(space, metric="loss", mode="min",
                             seed=seed, n_initial=10), _quad, 60))
        rng = random.Random(seed)
        rand_bests.append(min(
            _quad({k: d.sample(rng) for k, d in space.items()})
            for _ in range(60)))
    for b, r in zip(bo_bests, rand_bests):
        assert b < r, (bo_bests, rand_bests)
    assert sorted(bo_bests)[1] * 3 < sorted(rand_bests)[1]


def test_bayesopt_mixed_space_and_max_mode():
    """Categoricals ride one-hot coordinates; log floats normalize in
    log space; max mode flips the objective."""
    space = {"opt": tune.choice(["bad1", "good", "bad2"]),
             "lr": tune.loguniform(1e-5, 1e-1)}

    def objective(cfg):
        bonus = 1.0 if cfg["opt"] == "good" else 0.0
        return bonus - abs(math.log10(cfg["lr"]) + 3.0) / 4.0

    s = BayesOptSearcher(space, metric="score", mode="max", seed=3,
                         n_initial=12)
    best = -math.inf
    for i in range(70):
        cfg = s.suggest(f"t{i}")
        val = objective(cfg)
        s.on_trial_complete(f"t{i}", {"score": val})
        best = max(best, val)
    assert best > 0.8, best


def test_bayesopt_in_tuner(ray_session, tmp_path):
    """End-to-end through the Tuner with lazy suggestion."""
    from ray_tpu.train.config import RunConfig

    def trainable(config):
        tune.report({"loss": (config["x"] - 0.5) ** 2})

    searcher = BayesOptSearcher({"x": tune.uniform(0.0, 1.0)},
                                metric="loss", mode="min", seed=5,
                                n_initial=4)
    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.uniform(0.0, 1.0)},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    search_alg=searcher, num_samples=12,
                                    max_concurrent_trials=1),
        run_config=RunConfig(name="bo_e2e", storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert len(grid) == 12
    assert not grid.errors
    assert len(searcher._y) >= 10       # observations actually recorded
    best = grid.get_best_result("loss", "min")
    assert best.metrics["loss"] < 0.05
