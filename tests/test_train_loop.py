"""Overlapped training loop (ray_tpu/train/loop.py + spmd accum):
accumulation parity, prefetcher ordering/donation under buffer rotation,
fused-dispatch unroll parity, and the no-per-step-host-sync property.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt
from ray_tpu.parallel import MeshSpec
from ray_tpu.train import loop, spmd


def _tiny(**kw):
    return gpt.small(**{**dict(vocab_size=128, d_model=32, n_layers=1,
                               n_heads=2, d_ff=64, max_seq_len=16), **kw})


def _trainer_pieces(cfg, mesh, accum, donate=False):
    opt = spmd.default_optimizer()
    loss = partial(spmd.gpt_loss_fn, cfg=cfg, mesh=mesh)
    state, _ = spmd.create_sharded_state(
        lambda k: gpt.init_params(k, cfg), gpt.param_logical_axes(cfg),
        mesh, jax.random.key(0), opt)
    step = spmd.make_train_step(loss, opt, mesh, donate=donate,
                                accum=accum)
    return state, step


def _tokens(cfg, b, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, cfg.vocab_size, (b, cfg.max_seq_len + 1),
                        np.int32)
    return {"inputs": toks[:, :-1].copy(), "targets": toks[:, 1:].copy()}


# ---------------------------------------------------------------------------
# gradient accumulation
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype,tol", [("float32", 1e-5),
                                       ("bfloat16", 1e-2)])
def test_accum_matches_single_step(dtype, tol):
    """accum=4 on one [8, T] batch == accum=1 on the same batch: same
    loss (>= 4 decimals for f32) and same updated params — the scan over
    microbatches with a running f32 mean is the identical update."""
    cfg = _tiny(dtype=dtype)
    mesh = MeshSpec(data=-1).build()
    state1, step1 = _trainer_pieces(cfg, mesh, accum=1)
    state4, step4 = _trainer_pieces(cfg, mesh, accum=4)
    batch = loop.make_placer(mesh)(_tokens(cfg, 8))

    for _ in range(2):      # two steps so opt-state divergence would show
        state1, m1 = step1(state1, batch)
        state4, m4 = step4(state4, batch)
        l1, l4 = float(m1["loss"]), float(m4["loss"])
        assert l1 == pytest.approx(l4, abs=tol), (l1, l4)
        assert float(m1["grad_norm"]) == pytest.approx(
            float(m4["grad_norm"]), rel=tol)
    for a, b in zip(jax.tree.leaves(state1.params),
                    jax.tree.leaves(state4.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=tol, rtol=tol)


def test_accum_rejects_indivisible_batch():
    cfg = _tiny()
    mesh = MeshSpec(data=1, fsdp=1).build(jax.devices()[:1])
    _, step = _trainer_pieces(cfg, mesh, accum=3)
    with pytest.raises(ValueError, match="not divisible"):
        step(_trainer_pieces(cfg, mesh, accum=1)[0],
             loop.make_placer(mesh)(_tokens(cfg, 8)))


# ---------------------------------------------------------------------------
# prefetcher
# ---------------------------------------------------------------------------

def test_prefetcher_ordering_and_donation_safety():
    """Yielded batches arrive in host order with fresh buffers each time:
    a consumer that DONATES its batch into a jitted step (buffers deleted
    after the call) never corrupts later prefetched batches, because the
    rotation never re-yields or re-fills a buffer."""
    mesh = MeshSpec(data=-1).build()
    place = loop.make_placer(mesh)

    def host():
        for i in range(7):
            yield {"x": np.full((8, 4), i, np.float32)}

    pf = loop.DevicePrefetcher(host(), place, depth=3)
    bump = jax.jit(lambda b: jax.tree.map(lambda a: a + 1, b),
                   donate_argnums=(0,))
    first = next(pf)
    assert pf.issued == 3           # depth transfers in flight ahead
    out = bump(first)               # donates first's buffers
    assert float(np.asarray(out["x"])[0, 0]) == 1.0
    with pytest.raises(RuntimeError):
        np.asarray(first["x"])      # donated buffer really is gone
    for i, b in enumerate(pf, start=1):
        assert float(np.asarray(b["x"])[0, 0]) == i     # order intact
        bump(b)
    assert pf.issued == 7


def test_prefetcher_group_stacks_and_drops_ragged_tail():
    mesh = MeshSpec(data=-1).build()
    place = loop.make_placer(mesh, stacked=True)

    def host():
        for i in range(5):
            yield {"x": np.full((8, 2), i, np.float32)}

    got = list(loop.DevicePrefetcher(host(), place, depth=2, group=2))
    assert len(got) == 2            # 5 host batches -> 2 groups, tail dropped
    for j, g in enumerate(got):
        assert g["x"].shape == (2, 8, 2)
        np.testing.assert_array_equal(
            np.asarray(g["x"])[:, 0, 0], [2 * j, 2 * j + 1])


def test_dataset_iter_device_batches_bridge(ray_session):
    """ray_tpu.data → loop bridge: numpy batches land on the mesh sharded
    over the data-like axes, in dataset order."""
    from ray_tpu import data as rdata

    mesh = MeshSpec(data=-1).build()
    ds = rdata.from_items([{"x": float(i)} for i in range(64)])
    out = list(ds.iter_device_batches(mesh=mesh, batch_size=16))
    assert len(out) == 4
    for b in out:
        assert isinstance(b["x"], jax.Array)
        assert b["x"].sharding.spec[0] == ("data", "fsdp")
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(b["x"]) for b in out]),
        np.arange(64, dtype=np.float64))


# ---------------------------------------------------------------------------
# fused multi-step dispatch
# ---------------------------------------------------------------------------

def test_unroll_parity_with_step_at_a_time():
    """One fused dispatch of 4 steps == 4 single-step dispatches over the
    same batch sequence: identical per-step losses and final params."""
    cfg = _tiny()
    mesh = MeshSpec(data=-1).build()
    state_a, step = _trainer_pieces(cfg, mesh, accum=1)
    state_b, _ = _trainer_pieces(cfg, mesh, accum=1)
    host = [_tokens(cfg, 8, seed=s) for s in range(4)]
    place = loop.make_placer(mesh)

    losses_a = []
    for hb in host:
        state_a, m = step(state_a, place(hb))
        losses_a.append(float(m["loss"]))

    multi = loop.fuse_steps(step, unroll=4, donate=False)
    stacked = loop.make_placer(mesh, stacked=True)(
        jax.tree.map(lambda *xs: np.stack(xs), *host))
    state_b, ms = multi(state_b, stacked)

    np.testing.assert_allclose(np.asarray(ms["loss"]), losses_a,
                               atol=1e-5)
    assert list(np.asarray(ms["step"])) == [1, 2, 3, 4]
    for a, b in zip(jax.tree.leaves(state_a.params),
                    jax.tree.leaves(state_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5)


def test_train_loop_end_to_end_with_prefetch_and_accum():
    """TrainLoop + DevicePrefetcher(group=unroll) + accum: 8 real GPT
    steps in 4 dispatches, metrics arrive per-step and in order."""
    cfg = _tiny()
    mesh = MeshSpec(data=-1).build()
    state, step = _trainer_pieces(cfg, mesh, accum=2, donate=True)

    def host():
        s = 0
        while True:
            yield _tokens(cfg, 8, seed=s)
            s += 1

    pf = loop.DevicePrefetcher(host(), loop.make_placer(mesh,
                                                        stacked=True),
                               depth=2, group=2)
    tl = loop.TrainLoop(step, unroll=2, metrics_interval=3)
    state, metrics = tl.run(state, pf, num_steps=8)
    assert len(metrics) == 8
    assert [int(m["step"]) for m in metrics] == list(range(1, 9))
    assert all(np.isfinite(m["loss"]) for m in metrics)
    assert int(state.step) == 8


# ---------------------------------------------------------------------------
# async metrics ring
# ---------------------------------------------------------------------------

def test_no_per_step_host_sync(monkeypatch):
    """20 steps at metrics_interval=5 cost at most 20/5 + 1 host fetches
    — the loop's ONLY device→host seam is loop._device_get, so counting
    it bounds every sync in the steady-state path."""
    calls = {"n": 0}
    real = loop._device_get

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(loop, "_device_get", counting)
    mesh = MeshSpec(data=-1).build()

    def host():
        for i in range(20):
            yield {"x": np.full((8,), float(i), np.float32)}

    step = jax.jit(lambda s, b: (s + 1, {"loss": b["x"].mean(), "i": s}))
    tl = loop.TrainLoop(step, unroll=1, metrics_interval=5,
                        metrics_lag=2)
    state, hist = tl.run(jnp.zeros((), jnp.int32),
                         loop.DevicePrefetcher(host(),
                                               loop.make_placer(mesh)),
                         num_steps=20)
    assert len(hist) == 20
    assert [float(m["loss"]) for m in hist] == [float(i)
                                                for i in range(20)]
    assert tl.last_ring.fetches == calls["n"]
    assert calls["n"] <= 20 // 5 + 1


def test_metrics_ring_interval_and_lag():
    ring = loop.MetricsRing(interval=4, lag=1)
    for i in range(10):
        ring.push(jnp.asarray(float(i)))
    assert ring.fetches <= 10 // 4 + 1      # lagged, batched syncs
    hist = ring.drain()
    assert [float(h) for h in hist] == [float(i) for i in range(10)]


def test_metrics_ring_unstacks_fused_dispatch():
    ring = loop.MetricsRing(interval=100, lag=0)
    ring.push({"loss": jnp.asarray([0.0, 1.0, 2.0])}, count=3)
    hist = ring.drain()
    assert [float(h["loss"]) for h in hist] == [0.0, 1.0, 2.0]
    assert ring.fetches == 1


def test_prefetcher_surfaces_host_iterator_error():
    """A failing host feed is never masked as a clean epoch end: already
    transferred batches drain in order, then the ORIGINAL exception
    raises — and keeps raising on every subsequent next()."""
    mesh = MeshSpec(data=-1).build()
    place = loop.make_placer(mesh)

    def host():
        for i in range(3):
            yield {"x": np.full((8,), float(i), np.float32)}
        raise OSError("data shard unreachable")

    pf = loop.DevicePrefetcher(host(), place, depth=2)
    seen = []
    with pytest.raises(OSError, match="data shard unreachable"):
        for b in pf:
            seen.append(float(np.asarray(b["x"])[0]))
    assert seen == [0.0, 1.0, 2.0]      # buffered batches not lost
    with pytest.raises(OSError, match="data shard unreachable"):
        next(pf)                        # persistent, not one-shot


def test_prefetcher_skipped_ragged_counter():
    mesh = MeshSpec(data=-1).build()
    place = loop.make_placer(mesh, stacked=True)

    def host(n):
        for i in range(n):
            yield {"x": np.full((8, 2), i, np.float32)}

    pf = loop.DevicePrefetcher(host(7), place, depth=2, group=3)
    assert len(list(pf)) == 2           # 7 batches -> 2 groups of 3
    assert pf.skipped_ragged == 1       # the dropped tail is observable
    pf = loop.DevicePrefetcher(host(6), place, depth=2, group=3)
    assert len(list(pf)) == 2
    assert pf.skipped_ragged == 0


def test_metrics_ring_drain_resets_cadence():
    """drain() resets the interval counters, so a ring reused across
    back-to-back runs neither fires an early fetch nor defers one for an
    extra interval (regression: _steps_pushed leaked across runs)."""
    ring = loop.MetricsRing(interval=5, lag=0)
    for i in range(3):
        ring.push(jnp.asarray(float(i)))
    assert [float(x) for x in ring.drain()] == [0.0, 1.0, 2.0]
    base = ring.fetches
    for i in range(4):                  # second run: 4 < interval pushes
        ring.push(jnp.asarray(float(10 + i)))
    assert ring.fetches == base         # no premature fetch
    ring.push(jnp.asarray(14.0))        # 5th push of THIS run
    assert ring.fetches == base + 1     # cadence restarted from zero
    hist = ring.drain()
    assert [float(x) for x in hist] == \
        [0.0, 1.0, 2.0, 10.0, 11.0, 12.0, 13.0, 14.0]
