"""Serve-plane fault tolerance under seeded chaos (ISSUE 13 tentpole):
mid-stream replica failover splices a token-identical continuation,
the strike-based health plane survives transient ping failures, a
crash-looping deployment gets quarantined by the circuit breaker,
overload sheds typed errors instead of queueing unboundedly, and every
resilience counter reaches /metrics through the stats bridge."""

import json
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.exceptions import GetTimeoutError, OverloadedError
from ray_tpu.serve.handle import HANDLE_STATS
from ray_tpu.util.faults import FaultPlan

CFG = dict(vocab_size=128, d_model=32, n_layers=1, n_heads=2,
           d_ff=64, max_seq_len=64, dtype="float32")


@pytest.fixture
def serve_session(ray_session):
    yield serve
    serve.shutdown()


def _controller():
    from ray_tpu.serve.controller import get_controller
    return get_controller()


def _replicas(dep, app):
    c = _controller()
    _, reps = ray_tpu.get(c.get_replicas.remote(dep, app, -1), timeout=30)
    return reps


# ---------------------------------------------------------------------------
# tentpole proof: mid-stream failover is token-identical
# ---------------------------------------------------------------------------

def test_midstream_kill_failover_token_identical(serve_session):
    """Two same-seed replicas serve greedy decode; the serving replica is
    killed (deterministically, via a FaultPlan shipped into its process)
    after 20 tokens have been consumed. handle.stream must resubmit
    prompt + emitted tokens to the surviving replica and splice the
    continuation so the full stream equals an unkilled run."""
    from ray_tpu.serve.engine import InferenceReplica
    app = serve.deployment(InferenceReplica, num_replicas=2).bind(
        CFG, slots=2, max_len=64, seed=0)
    h = serve.run(app, name="t_chaos")
    prompt, n_tok = [5, 9, 3], 40          # 40 > SERVE_STREAM_BATCH (16)

    # control run: no faults, full stream
    expected = list(h.stream(list(prompt), n_tok))
    assert len(expected) == n_tok

    # chaos run: consume 20 tokens, then kill the serving replica at its
    # next emit tick
    before = HANDLE_STATS.stats()["failovers"]
    it = h.stream(list(prompt), n_tok)
    got = [next(it) for _ in range(20)]
    serving = [r for r in _replicas("InferenceReplica", "t_chaos")
               if ray_tpu.get(r.stats.remote(), timeout=30)
               .get("streams", 0) > 0]
    assert len(serving) == 1, "exactly one replica should hold the stream"
    ray_tpu.get(serving[0].install_faults.remote(
        FaultPlan(seed=13).kill("engine.emit", at=0)), timeout=30)
    got.extend(it)                         # drains through the failover
    assert got == expected
    assert HANDLE_STATS.stats()["failovers"] >= before + 1


# ---------------------------------------------------------------------------
# satellite: stream-handle leak — abandon and timeout both cancel
# ---------------------------------------------------------------------------

def _streams_of(dep, app):
    return sum(ray_tpu.get(r.stats.remote(), timeout=30)
               .get("streams", 0) for r in _replicas(dep, app))


def _assert_no_leaked_streams(dep, app):
    deadline = time.time() + 10
    while time.time() < deadline:
        if _streams_of(dep, app) == 0:      # cancel_stream is async
            return
        time.sleep(0.2)
    pytest.fail("replica still holds a registered stream (leak)")


def test_stream_abandon_and_timeout_cancel_replica_stream(serve_session):
    @serve.deployment(num_replicas=1)
    class Leaky:
        def __call__(self, mode):
            def infinite():
                i = 0
                while True:
                    yield i
                    i += 1

            def stall():
                yield 0
                time.sleep(8)
                yield 1
            return infinite() if mode == "infinite" else stall()

    h = serve.run(Leaky.bind(), name="t_leak")

    # abandoned generator: close() must release the replica-side stream
    s = h.stream("infinite")
    assert next(s) == 0
    s.close()
    _assert_no_leaked_streams("Leaky", "t_leak")

    # timed-out drain: the regression this PR fixes — a GetTimeoutError
    # used to exit the generator WITHOUT cancel_stream, pinning the
    # producer on the replica until the idle TTL
    with pytest.raises(GetTimeoutError):
        list(h.stream("stall", timeout=1.5))
    _assert_no_leaked_streams("Leaky", "t_leak")


# ---------------------------------------------------------------------------
# satellite: health plane — strikes, probation, fault-injected rounds
# ---------------------------------------------------------------------------

def test_one_transient_health_failure_does_not_kill_replica(serve_session):
    """Regression for the one-strike health check: a single failed ping
    (transient GC pause, slow tick) must strike, not replace."""
    @serve.deployment(num_replicas=1)
    class Blip:
        def __init__(self):
            self.pings = 0

        def check_health(self):
            self.pings += 1
            if self.pings == 2:        # first ping passes (replica is
                raise RuntimeError("transient blip")   # healthy), 2nd blips

        def __call__(self, x):
            return x + 1

    h = serve.run(Blip.bind(), name="t_blip")
    assert h.call(1) == 2
    aid = _replicas("Blip", "t_blip")[0]._actor_id
    # Poll for the strike instead of a fixed sleep: the 1s reconcile
    # cadence stretches arbitrarily on a loaded runner, so any fixed
    # window can close before ping #2 (the blip) has fired — the strike
    # itself is the event "survived it" is only meaningful after.
    deadline = time.time() + 30
    while time.time() < deadline:
        st = ray_tpu.get(_controller().stats.remote(), timeout=30)
        if st["health_check_failures"] >= 1:
            break
        time.sleep(0.25)
    assert st["health_check_failures"] >= 1, \
        "health plane never pinged the replica a second time"
    time.sleep(1.0)        # grace: a (wrong) replacement would land now
    survivors = _replicas("Blip", "t_blip")
    assert [r._actor_id for r in survivors] == [aid], \
        "a single transient health failure replaced the replica"
    assert h.call(2) == 3
    st = ray_tpu.get(_controller().stats.remote(), timeout=30)
    assert st["replicas_restarted"] == 0


def test_controller_side_ping_fault_round_strikes_not_kills(serve_session):
    """controller.health_ping chaos: one round where the controller's
    whole probe fan-out fails (partitioned control plane) must strike
    every replica once — and kill none."""
    @serve.deployment(num_replicas=1)
    class Ok:
        def __call__(self, x):
            return x

    h = serve.run(Ok.bind(), name="t_round")
    assert h.call(7) == 7
    aid = _replicas("Ok", "t_round")[0]._actor_id
    c = _controller()
    base = ray_tpu.get(c.stats.remote(),
                       timeout=30)["health_check_failures"]
    try:
        ray_tpu.get(c.inject_faults.remote(
            FaultPlan().fail("controller.health_ping", at=0, times=1)),
            timeout=30)
        # Poll for the faulted probe round to actually fire (counted as
        # a health-check failure) rather than sleeping a fixed 4s — the
        # health cadence has no latency guarantee on a loaded runner.
        deadline = time.time() + 30
        while time.time() < deadline:
            st = ray_tpu.get(c.stats.remote(), timeout=30)
            if st["health_check_failures"] >= base + 1:
                break
            time.sleep(0.25)
        assert st["health_check_failures"] >= base + 1, \
            "the faulted health round never fired"
        time.sleep(1.0)    # grace: a (wrong) replacement would land now
        assert [r._actor_id for r in _replicas("Ok", "t_round")] == [aid]
        assert h.call(8) == 8
    finally:
        ray_tpu.get(c.inject_faults.remote(None), timeout=30)


def test_breaker_quarantines_crash_looping_deployment(serve_session):
    """A deployment whose replicas die shortly after start must trip the
    circuit breaker: restarts STOP (quarantine) instead of burning the
    cluster respawning forever."""
    @serve.deployment(num_replicas=1)
    class CrashLoop:
        def __init__(self):
            import os
            import threading
            threading.Timer(1.0, lambda: os._exit(1)).start()

        def __call__(self, x):
            return x

    serve.run(CrashLoop.bind(), name="t_loop")
    c = _controller()
    ray_tpu.get(c.configure_fault_tolerance.remote(
        breaker_threshold=2, breaker_window_s=60.0,
        breaker_cooldown_s=300.0), timeout=30)

    deadline = time.time() + 60
    while time.time() < deadline:
        st = serve.status().get("t_loop:CrashLoop", {})
        if st.get("breaker") == "open":
            break
        time.sleep(0.5)
    else:
        pytest.fail(f"breaker never opened: {serve.status()}")
    assert st["status"] == "QUARANTINED"

    stats = ray_tpu.get(c.stats.remote(), timeout=30)
    assert stats["breaker_trips"] >= 1
    assert stats["quarantined"] == 1
    # quarantine means NO further replacements: the restart counter
    # freezes while the breaker stays open (cooldown is 300s)
    restarted = stats["replicas_restarted"]
    time.sleep(3)
    stats2 = ray_tpu.get(c.stats.remote(), timeout=30)
    assert stats2["replicas_restarted"] == restarted


# ---------------------------------------------------------------------------
# overload shedding: typed errors at the engine and 429 at the proxy
# ---------------------------------------------------------------------------

def test_engine_overload_sheds_typed_error():
    """Queue-bound admission: past max_queue, submit raises
    OverloadedError (typed, counted) instead of queueing unboundedly —
    and draining the queue reopens admission."""
    import jax
    from ray_tpu.models import gpt
    from ray_tpu.serve.engine import InferenceEngine
    cfg = gpt.small(**CFG)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, slots=1, max_len=64, max_queue=2)
    r1 = eng.submit([1, 2, 3], max_new_tokens=2)
    eng.submit([1, 2, 3], max_new_tokens=2)
    with pytest.raises(OverloadedError):
        eng.submit([1, 2, 3], max_new_tokens=2)
    assert eng.stats()["sheds"] == 1
    assert len(list(eng.tokens_for(r1))) == 2    # queue drains fine
    # block-pool high water: a tiny budget sheds on projected usage
    eng2 = InferenceEngine(params, cfg, slots=1, max_len=64,
                           shed_high_water=0.01)
    with pytest.raises(OverloadedError):
        eng2.submit(list(range(32)), max_new_tokens=16)
    assert eng2.stats()["sheds"] == 1


def test_engine_watchdog_counts_stuck_ticks():
    import jax
    from ray_tpu.models import gpt
    from ray_tpu.serve.engine import InferenceEngine
    cfg = gpt.small(**CFG)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, slots=1, max_len=64,
                          watchdog_s=0.2)
    assert eng.stats()["watchdog_stalls"] == 0
    # simulate a tick wedged past the watchdog window
    eng._tick_seq += 1
    eng._tick_started = time.perf_counter() - 1.0
    deadline = time.time() + 5
    while time.time() < deadline and \
            eng.stats()["watchdog_stalls"] == 0:
        time.sleep(0.05)
    eng._tick_started = None
    assert eng.stats()["watchdog_stalls"] >= 1


def test_proxy_maps_overload_to_429_and_timeout_to_504(serve_session):
    @serve.deployment
    class Full:
        def __call__(self, req):
            raise OverloadedError("synthetic: engine full")

    serve.run(Full.bind(), name="t_shed")
    proxy = serve.start(http_options={"port": 0})
    info = ray_tpu.get(proxy.ready.remote(), timeout=30)
    serve.set_route("/full", "Full", "t_shed")
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{info['port']}/full", timeout=30)
        pytest.fail("expected HTTP 429")
    except urllib.error.HTTPError as e:
        assert e.code == 429
        assert e.headers.get("Retry-After") == "1"
        assert json.loads(e.read())["error"] == "overloaded"
    # the timeout mapping, unit-level (a real 300s proxy-side get
    # timeout has no place in a test)
    from ray_tpu.serve.http_proxy import HTTPProxy
    resp = HTTPProxy._error_response(
        object.__new__(HTTPProxy), GetTimeoutError("slow"))
    assert resp.status == 504


# ---------------------------------------------------------------------------
# acceptance: resilience counters reach /metrics
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def dashboard_port(ray_session):
    from ray_tpu.dashboard import start_dashboard
    return start_dashboard(0)


def _scrape(port):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        return r.read().decode()


def test_fault_counters_reach_metrics(serve_session, dashboard_port):
    """retries / failovers / sheds / breaker_trips series on /metrics,
    fed by the handle (driver), a driver-side engine, and the
    controller (worker process -> carried by the metrics flusher)."""
    import jax
    from ray_tpu.models import gpt
    from ray_tpu.serve.engine import InferenceEngine

    # a real retry: kill the only replica, then call through the death
    @serve.deployment(num_replicas=1)
    class Svc:
        def __call__(self, x):
            return x * 2

    h = serve.run(Svc.bind(), name="t_metrics")
    assert h.call(3) == 6
    before = HANDLE_STATS.stats()["retries"]
    ray_tpu.kill(_replicas("Svc", "t_metrics")[0])
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if h.call(4, timeout=10) == 8:
                break
        except Exception:
            time.sleep(0.5)
    else:
        pytest.fail("replica never recovered")
    assert HANDLE_STATS.stats()["retries"] >= before + 1

    # a real shed on a driver-local engine (direct scrape path)
    cfg = gpt.small(**CFG)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, slots=1, max_len=64, max_queue=1)
    eng.submit([1, 2], max_new_tokens=2)
    with pytest.raises(OverloadedError):
        eng.submit([1, 2], max_new_tokens=2)

    want = ("ray_tpu_serve_handle_retries",
            "ray_tpu_serve_handle_failovers",
            "ray_tpu_engine_sheds",
            "ray_tpu_serve_controller_breaker_trips")
    deadline = time.time() + 20        # controller series ride the 5s
    missing = want                     # metrics flusher from its worker
    while time.time() < deadline:
        text = _scrape(dashboard_port)
        missing = tuple(w for w in want if w not in text)
        if not missing:
            break
        time.sleep(1)
    assert not missing, f"series never appeared on /metrics: {missing}"


# ---------------------------------------------------------------------------
# heavy chaos variant
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_double_failover_token_identical(serve_session):
    """Two sequential mid-stream kills (the SERVE_STREAM_FAILOVERS=2
    budget exactly) still complete token-identical."""
    from ray_tpu.serve.engine import InferenceReplica
    app = serve.deployment(InferenceReplica, num_replicas=3).bind(
        CFG, slots=2, max_len=64, seed=0)
    h = serve.run(app, name="t_chaos2")
    prompt, n_tok = [7, 2], 48

    expected = list(h.stream(list(prompt), n_tok))
    assert len(expected) == n_tok

    it = h.stream(list(prompt), n_tok)
    got = [next(it) for _ in range(17)]
    for consumed in (17, 34):
        serving = [r for r in _replicas("InferenceReplica", "t_chaos2")
                   if ray_tpu.get(r.stats.remote(), timeout=30)
                   .get("streams", 0) > 0]
        assert len(serving) == 1
        ray_tpu.get(serving[0].install_faults.remote(
            FaultPlan(seed=consumed).kill("engine.emit", at=0)),
            timeout=30)
        if consumed == 17:
            got.extend(next(it) for _ in range(17))
    got.extend(it)
    assert got == expected
