"""Runtime environments: working_dir, py_modules, pip venvs, env_vars.

Counterpart of the reference's `test_runtime_env*.py` suites over
`_private/runtime_env/` (working_dir.py, pip.py, uri_cache.py): the node
materializes the environment into a content-addressed cache before the
worker execs, so tasks/actors see packages and files the driver doesn't.
"""

import base64
import hashlib
import os
import zipfile

import pytest

import ray_tpu
from ray_tpu.exceptions import RuntimeEnvSetupError


def _make_wheel(tmp_path, name="rttestpkg", version="1.0",
                body=b"MAGIC = 12345\n"):
    """Craft a minimal pure-python wheel offline (a .whl is just a zip
    with dist-info metadata) so pip can install it with zero egress."""
    wheel_path = str(tmp_path / f"{name}-{version}-py3-none-any.whl")
    records = []

    def add(zf, arcname, data):
        zf.writestr(arcname, data)
        digest = base64.urlsafe_b64encode(
            hashlib.sha256(data).digest()).rstrip(b"=").decode()
        records.append(f"{arcname},sha256={digest},{len(data)}")

    di = f"{name}-{version}.dist-info"
    with zipfile.ZipFile(wheel_path, "w") as zf:
        add(zf, f"{name}/__init__.py", body)
        add(zf, f"{di}/METADATA",
            f"Metadata-Version: 2.1\nName: {name}\n"
            f"Version: {version}\n".encode())
        add(zf, f"{di}/WHEEL",
            b"Wheel-Version: 1.0\nGenerator: test\n"
            b"Root-Is-Purelib: true\nTag: py3-none-any\n")
        records.append(f"{di}/RECORD,,")
        zf.writestr(f"{di}/RECORD", "\n".join(records) + "\n")
    return wheel_path


def test_env_vars_reach_task(ray_session):
    @ray_tpu.remote(runtime_env={"env_vars": {"RTENV_PROBE": "ping"}})
    def probe():
        return os.environ.get("RTENV_PROBE")

    assert ray_tpu.get(probe.remote(), timeout=120) == "ping"


def test_working_dir_import_and_cwd(ray_session, tmp_path):
    wd = tmp_path / "app"
    wd.mkdir()
    (wd / "localmod.py").write_text("ANSWER = 41\n")
    (wd / "data.txt").write_text("payload")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd)})
    def use_it():
        import localmod                      # only on the worker's path
        with open("data.txt") as f:          # cwd is the working_dir
            return localmod.ANSWER + 1, f.read()

    val, data = ray_tpu.get(use_it.remote(), timeout=120)
    assert val == 42 and data == "payload"
    with pytest.raises(ImportError):
        import localmod  # noqa: F401  (driver must NOT see it)


def test_py_modules(ray_session, tmp_path):
    pkg = tmp_path / "extpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("WHO = 'py_modules'\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(pkg)]})
    def who():
        import extpkg
        return extpkg.WHO

    assert ray_tpu.get(who.remote(), timeout=120) == "py_modules"


@pytest.mark.slow
def test_pip_wheel_in_actor(ray_session, tmp_path):
    """An actor imports a pip package the driver doesn't have — the
    VERDICT's acceptance criterion for runtime envs (venv created with
    --system-site-packages, wheel installed offline)."""
    wheel = _make_wheel(tmp_path)

    @ray_tpu.remote(runtime_env={"pip": [wheel]})
    class UsesPkg:
        def magic(self):
            import rttestpkg
            return rttestpkg.MAGIC

        def has_numpy(self):
            import numpy                     # system site-packages intact
            return numpy.__name__

    a = UsesPkg.remote()
    assert ray_tpu.get(a.magic.remote(), timeout=300) == 12345
    assert ray_tpu.get(a.has_numpy.remote(), timeout=120) == "numpy"
    ray_tpu.kill(a)
    with pytest.raises(ImportError):
        import rttestpkg  # noqa: F401

    # cache hit: the same env resolves to the same venv without a rebuild
    from ray_tpu._private.runtime_env import get_manager
    mgr = get_manager()
    exe1, site1 = mgr._setup_pip([wheel])
    exe2, _ = mgr._setup_pip([wheel])
    assert exe1 == exe2 and os.path.exists(exe1)
    assert site1 and os.path.isdir(site1)
    # a REBUILT wheel at the same path must get a fresh venv
    os.utime(wheel, (os.path.getmtime(wheel) + 5,) * 2)
    exe3, _ = mgr._setup_pip([wheel])
    assert exe3 != exe1


def test_bad_pip_env_fails_cleanly(ray_session):
    @ray_tpu.remote(
        runtime_env={"pip": ["definitely-not-a-package-xyz-000"]})
    def f():
        return 1

    with pytest.raises(RuntimeEnvSetupError):
        ray_tpu.get(f.remote(), timeout=300)


def test_working_dir_on_remote_node(ray_session, tmp_path):
    """A daemon materializes the env for its own workers."""
    from ray_tpu.cluster_utils import Cluster
    wd = tmp_path / "napp"
    wd.mkdir()
    (wd / "nodemod.py").write_text("V = 'remote-env'\n")
    c = Cluster.attach()
    nid = c.add_node({"CPU": 2, "envres": 1})
    try:
        @ray_tpu.remote(resources={"envres": 1},
                        runtime_env={"working_dir": str(wd)})
        def use_it():
            import nodemod
            return os.environ.get("RAY_TPU_NODE_ID"), nodemod.V

        host, v = ray_tpu.get(use_it.remote(), timeout=180)
        assert host == nid and v == "remote-env"
    finally:
        c.kill_node(nid)
