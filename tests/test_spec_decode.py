"""Speculative decoding tests: masked multi-query verify attention
(pallas-interpret vs jax parity, single-query equivalence), the batched
`verify_step_paged` forward vs W sequential decode steps (bit-identical
logits AND cache), greedy token-parity with speculation on vs off for
both backends (n-gram lookahead and draft model, incl. shared-prefix /
COW prompts and mid-flight joins), the compile-exactly-once guarantee
(`decode_traces`/`verify_traces`), the temperature accept path, and the
acceptance/tokens-per-step stats contract."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt
from ray_tpu.ops import decode_attention as da
from ray_tpu.serve.engine import InferenceEngine


def tiny_cfg(**kw):
    return gpt.GPTConfig(**{**dict(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype="float32"), **kw})


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def make_engine(cfg, params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("block_size", 8)
    return InferenceEngine(params, cfg, **kw)


def rollout_reference(params, prompt, cfg, steps):
    toks = list(prompt)
    for _ in range(steps):
        logits = gpt.forward(params, jnp.asarray([toks]), cfg)[0, -1]
        toks.append(int(jnp.argmax(logits)))
    return toks[len(prompt):]


def motif_prompt(rng, vocab, n, motif_len=4):
    motif = rng.integers(1, vocab, motif_len)
    return np.tile(motif, -(-n // motif_len))[:n].astype(np.int32)


# ---------------------------------------------------------------------------
# verify attention kernel
# ---------------------------------------------------------------------------

class TestVerifyAttention:
    def _paged(self, b, s, h, d, bs, seed=0):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        k = jax.random.normal(ks[1], (b, s, h, d))
        v = jax.random.normal(ks[2], (b, s, h, d))
        mb = s // bs
        rng = np.random.default_rng(seed)
        perm = rng.permutation(b * mb) + 1
        tables = perm.reshape(b, mb).astype(np.int32)
        kp = np.zeros((b * mb + 1, bs, h, d), np.float32)
        vp = np.zeros_like(kp)
        for i in range(b):
            for j in range(mb):
                kp[tables[i, j]] = np.asarray(k[i, j * bs:(j + 1) * bs])
                vp[tables[i, j]] = np.asarray(v[i, j * bs:(j + 1) * bs])
        return jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(tables)

    @pytest.mark.parametrize("w", [2, 5, 8])
    def test_pallas_matches_jax(self, w):
        b, s, h, d, bs = 3, 48, 2, 16, 8
        kp, vp, tables = self._paged(b, s, h, d, bs)
        q = jax.random.normal(jax.random.PRNGKey(7), (b, w, h, d))
        pos = jnp.asarray([5, 17, 40 - w], jnp.int32)
        ref = da.paged_verify_attention(q, kp, vp, tables, pos,
                                        impl="jax")
        pal = da.paged_verify_attention(q, kp, vp, tables, pos,
                                        impl="pallas")
        np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_rows_match_single_query_decode(self):
        """Row i of the W-query verify must equal a plain decode-step
        attention issued at pos + i — same mask, same math."""
        b, s, h, d, bs, w = 2, 32, 2, 16, 8, 4
        kp, vp, tables = self._paged(b, s, h, d, bs, seed=3)
        q = jax.random.normal(jax.random.PRNGKey(9), (b, w, h, d))
        pos = jnp.asarray([6, 20], jnp.int32)
        out = da.paged_verify_attention(q, kp, vp, tables, pos,
                                        impl="jax")
        for i in range(w):
            single = da.paged_decode_attention(
                q[:, i], kp, vp, tables, pos + i, impl="jax")
            np.testing.assert_allclose(
                np.asarray(out[:, i]), np.asarray(single),
                atol=1e-5, rtol=1e-5)

    def test_rejects_bad_rank(self):
        with pytest.raises(ValueError):
            da.paged_verify_attention(
                jnp.zeros((2, 2, 16)), jnp.zeros((4, 8, 2, 16)),
                jnp.zeros((4, 8, 2, 16)), jnp.zeros((2, 4), jnp.int32),
                jnp.zeros((2,), jnp.int32))


# ---------------------------------------------------------------------------
# verify_step_paged vs sequential decode steps
# ---------------------------------------------------------------------------

class TestVerifyStepPaged:
    def test_matches_sequential_decode(self, setup):
        """One W-token verify forward == W sequential single-token
        decode steps: logits AND the updated cache, bit-identical."""
        cfg, params = setup
        bs, max_blocks, w = 8, 4, 4
        pool_blocks = 2 * max_blocks + 1
        rng = np.random.default_rng(0)
        prompt = rng.integers(1, cfg.vocab_size, 10).astype(np.int32)
        window = rng.integers(1, cfg.vocab_size, (2, w)) \
            .astype(np.int32)
        tables = np.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], np.int32)
        pos = np.asarray([prompt.size, prompt.size], np.int32)

        def prefilled():
            cache = gpt.init_kv_pool(cfg, pool_blocks, bs)
            for row in range(2):
                _, cache = gpt.prefill_paged(
                    params, jnp.asarray(prompt[None]), cache, cfg,
                    block_table=jnp.asarray(tables[row]),
                    start=0, length=prompt.size)
            return cache

        # path A: batched verify
        va, cache_a = gpt.verify_step_paged(
            params, jnp.asarray(window), prefilled(),
            jnp.asarray(pos), jnp.asarray(tables), cfg)
        # path B: W sequential decode steps
        cache_b = prefilled()
        seq_logits = []
        for j in range(w):
            lg, cache_b = gpt.decode_step_paged(
                params, jnp.asarray(window[:, j]), cache_b,
                jnp.asarray(pos + j), jnp.asarray(tables), cfg)
            seq_logits.append(np.asarray(lg))
        vb = np.stack(seq_logits, axis=1)
        np.testing.assert_array_equal(np.asarray(va), vb)
        for la, lb in zip(jax.tree.leaves(cache_a),
                          jax.tree.leaves(cache_b)):
            np.testing.assert_array_equal(np.asarray(la),
                                          np.asarray(lb))


# ---------------------------------------------------------------------------
# engine: greedy parity + compile-exactly-once
# ---------------------------------------------------------------------------

class TestSpecParity:
    def _run(self, cfg, params, prompts, new, ekw):
        eng = make_engine(cfg, params, **ekw)
        outs = [eng.generate(p, max_new_tokens=new) for p in prompts]
        eng.check_invariants()
        return outs, eng.stats()

    def test_greedy_token_identical_both_backends(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(0)
        prompts = [motif_prompt(rng, cfg.vocab_size, 12),
                   motif_prompt(rng, cfg.vocab_size, 9),
                   rng.integers(1, cfg.vocab_size, 10).astype(np.int32)]
        base, bs = self._run(cfg, params, prompts, 12, {})
        ng, ns = self._run(cfg, params, prompts, 12,
                           dict(spec="ngram", spec_k=4))
        dr, ds = self._run(cfg, params, prompts, 12,
                           dict(spec="draft", spec_k=3,
                                draft_params=params, draft_cfg=cfg))
        assert base == ng == dr
        assert bs["decode_traces"] == 1 and bs["verify_traces"] == 0
        assert ns["verify_traces"] == 1 and ns["decode_traces"] <= 1
        assert ds["verify_traces"] == 1 and ds["draft_traces"] == 1
        # ...and they match the ground-truth full-forward rollout.
        assert base[2] == rollout_reference(params, prompts[2], cfg, 12)

    def test_shared_prefix_cow_parity(self, setup):
        """Two prompts diverging mid-block: the second admits through
        the radix tree with a COW copy; speculation must not perturb
        either stream."""
        cfg, params = setup
        rng = np.random.default_rng(1)
        shared = rng.integers(1, cfg.vocab_size, 19)
        p_a = np.concatenate([shared, rng.integers(1, 128, 6)]) \
            .astype(np.int32)
        p_b = np.concatenate([shared, rng.integers(1, 128, 3)]) \
            .astype(np.int32)
        base, bs = self._run(cfg, params, [p_a, p_b], 7, {})
        ng, ns = self._run(cfg, params, [p_a, p_b], 7,
                           dict(spec="ngram", spec_k=4))
        dr, ds = self._run(cfg, params, [p_a, p_b], 7,
                           dict(spec="draft", spec_k=3,
                                draft_params=params, draft_cfg=cfg))
        assert base == ng == dr
        for s in (bs, ns, ds):
            assert s["cow_copies"] >= 1
        assert ns["verify_traces"] == 1 and ds["verify_traces"] == 1

    @pytest.mark.parametrize("k", [1, 3, 6])
    def test_mixed_k_compiles_once(self, setup, k):
        """Each spec_k is a distinct static verify shape — but within
        one engine the verify executable compiles exactly once no
        matter how ragged the accepted spans get."""
        cfg, params = setup
        rng = np.random.default_rng(2)
        prompts = [motif_prompt(rng, cfg.vocab_size, 11),
                   rng.integers(1, cfg.vocab_size, 7).astype(np.int32),
                   motif_prompt(rng, cfg.vocab_size, 13, motif_len=3)]
        base, _ = self._run(cfg, params, prompts, 10, {})
        got, s = self._run(cfg, params, prompts, 10,
                           dict(spec="ngram", spec_k=k))
        assert got == base
        assert s["verify_traces"] == 1 and s["decode_traces"] <= 1

    def test_mid_flight_join(self, setup):
        """A request admitted while another is mid-speculation joins
        the verify batch without recompiles or cross-talk."""
        cfg, params = setup
        rng = np.random.default_rng(3)
        p1 = motif_prompt(rng, cfg.vocab_size, 12)
        p2 = motif_prompt(rng, cfg.vocab_size, 9)
        p3 = rng.integers(1, cfg.vocab_size, 8).astype(np.int32)
        eng = make_engine(cfg, params, spec="ngram", spec_k=4)
        r1 = eng.submit(p1, max_new_tokens=14)
        it = eng.tokens_for(r1)
        got1 = [next(it) for _ in range(4)]     # r1 is decoding
        r2 = eng.submit(p2, max_new_tokens=10)  # joins mid-flight
        got1 += [next(it) for _ in range(4)]
        r3 = eng.submit(p3, max_new_tokens=6)
        got1 += list(it)
        eng.run_until_idle()
        got2 = list(eng._out[r2])
        got3 = list(eng._out[r3])
        assert got1 == rollout_reference(params, p1, cfg, 14)
        assert got2 == rollout_reference(params, p2, cfg, 10)
        assert got3 == rollout_reference(params, p3, cfg, 6)
        s = eng.stats()
        assert s["verify_traces"] == 1 and s["decode_traces"] <= 1
        assert s["prefill_traces"] <= len(eng.chunk_buckets)
        eng.check_invariants()

    def test_temperature_path_runs(self, setup):
        """Rejection-sampling accept: sampled runs terminate with valid
        tokens on both backends (distributional exactness is argued in
        the engine docstring; this pins the plumbing)."""
        cfg, params = setup
        rng = np.random.default_rng(4)
        p = motif_prompt(rng, cfg.vocab_size, 12)
        for ekw in (dict(spec="ngram", spec_k=4),
                    dict(spec="draft", spec_k=3,
                         draft_params=params, draft_cfg=cfg)):
            eng = make_engine(cfg, params, **ekw)
            out = eng.generate(p, max_new_tokens=10, temperature=0.7)
            assert len(out) == 10
            assert all(0 <= t < cfg.vocab_size for t in out)
            eng.check_invariants()


# ---------------------------------------------------------------------------
# engine: stats contract
# ---------------------------------------------------------------------------

class TestSpecStats:
    def test_acceptance_and_tokens_per_step(self, setup):
        """Self-drafting (draft == target) accepts everything under
        greedy: tokens_per_step approaches k+1."""
        cfg, params = setup
        eng = make_engine(cfg, params, spec="draft", spec_k=3,
                          draft_params=params, draft_cfg=cfg)
        rng = np.random.default_rng(5)
        eng.generate(rng.integers(1, cfg.vocab_size, 10),
                     max_new_tokens=13)
        s = eng.stats()
        assert s["acceptance_rate"] > 0.9
        assert s["tokens_per_step"] > 2.0
        assert s["spec_steps"] > 0 and s["spec"] == "draft"

    def test_spec_off_tokens_per_step_is_one(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params)
        eng.generate([1, 2, 3, 4], max_new_tokens=6)
        s = eng.stats()
        assert s["tokens_per_step"] == 1.0
        assert s["acceptance_rate"] == 0.0 and s["spec"] == ""

    def test_windowed_load_stats_and_reset(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params, spec="ngram", spec_k=2)
        rng = np.random.default_rng(6)
        eng.generate(motif_prompt(rng, cfg.vocab_size, 10),
                     max_new_tokens=8)
        s = eng.stats()
        assert s["decode_tok_s"] > 0
        assert s["queue_wait_ms_p50"] > 0
        assert s["queue_wait_ms_p99"] >= s["queue_wait_ms_p50"]
        assert s["queue_depth"] == 0
        eng.reset_stats()
        s = eng.stats()
        # every satellite stat zeroes; the trace counters do NOT
        assert s["decode_tok_s"] == 0.0 and s["tokens_per_step"] == 0.0
        assert s["queue_wait_ms_p50"] == 0.0
        assert s["acceptance_rate"] == 0.0 and s["spec_steps"] == 0
        assert s["verify_traces"] == 1

    def test_queue_depth_counts_pending(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params, slots=1)
        for _ in range(3):
            eng.submit([1, 2, 3], max_new_tokens=4)
        eng.step()   # admits one, two stay queued
        assert eng.stats()["queue_depth"] == 2
        eng.run_until_idle()
        assert eng.stats()["queue_depth"] == 0

    def test_ngram_propose_unit(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params, spec="ngram", spec_k=3,
                          ngram_max=3, ngram_min=1)
        from ray_tpu.serve.engine import _Slot
        s = _Slot(history=[5, 6, 7, 9, 5, 6, 7])
        # suffix [5,6,7] recurs at position 0; continuation is [9,5,6]
        assert eng._ngram_propose(s) == [9, 5, 6]
        s = _Slot(history=[1, 2, 3, 4])     # no repeat -> no proposal
        assert eng._ngram_propose(s) is None

    def test_bad_spec_config_rejected(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError):
            make_engine(cfg, params, spec="bogus")
        with pytest.raises(ValueError):
            make_engine(cfg, params, spec="draft")   # no draft model
        with pytest.raises(ValueError):
            make_engine(cfg, params, spec="ngram", spec_k=0)


# ---------------------------------------------------------------------------
# quantized cache (int8 KV) through the speculative path
# ---------------------------------------------------------------------------

class TestQuantizedSpec:
    def test_verify_kernel_quantized_parity(self):
        """The W-query verify kernel's in-VMEM dequant == the gather-
        then-dequant jax path on an int8 pool."""
        from ray_tpu.ops import quant
        b, s, h, d, bs, w = 3, 48, 2, 16, 8, 5
        kp, vp, tables = TestVerifyAttention()._paged(b, s, h, d, bs)
        kq, ksc = quant.quantize_rows(kp)
        vq, vsc = quant.quantize_rows(vp)
        q = jax.random.normal(jax.random.PRNGKey(7), (b, w, h, d))
        pos = jnp.asarray([5, 17, 40 - w], jnp.int32)
        ref = da.paged_verify_attention(
            q, kq, vq, tables, pos, k_scale=ksc, v_scale=vsc,
            impl="jax")
        pal = da.paged_verify_attention(
            q, kq, vq, tables, pos, k_scale=ksc, v_scale=vsc,
            impl="pallas")
        np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_verify_step_quantized_matches_sequential(self, setup):
        """Batched verify on an int8 pool == W sequential decode steps,
        bit-identical logits AND cache INCLUDING scale arrays: both
        paths quantize each token's K/V row once at write through the
        same deterministic round-trip, so speculative acceptance on a
        quantized cache stays distribution-exact, not merely close."""
        _, params = setup
        cfg = tiny_cfg(kv_dtype="int8")
        bs, w = 8, 4
        rng = np.random.default_rng(0)
        prompt = rng.integers(1, cfg.vocab_size, 10).astype(np.int32)
        window = rng.integers(1, cfg.vocab_size, (2, w)) \
            .astype(np.int32)
        tables = np.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], np.int32)
        pos = np.asarray([prompt.size, prompt.size], np.int32)

        def prefilled():
            cache = gpt.init_kv_pool(cfg, 9, bs)
            for row in range(2):
                _, cache = gpt.prefill_paged(
                    params, jnp.asarray(prompt[None]), cache, cfg,
                    block_table=jnp.asarray(tables[row]),
                    start=0, length=prompt.size)
            return cache

        va, cache_a = gpt.verify_step_paged(
            params, jnp.asarray(window), prefilled(),
            jnp.asarray(pos), jnp.asarray(tables), cfg)
        cache_b = prefilled()
        seq_logits = []
        for j in range(w):
            lg, cache_b = gpt.decode_step_paged(
                params, jnp.asarray(window[:, j]), cache_b,
                jnp.asarray(pos + j), jnp.asarray(tables), cfg)
            seq_logits.append(np.asarray(lg))
        np.testing.assert_array_equal(np.asarray(va),
                                      np.stack(seq_logits, axis=1))
        assert set(cache_a) == {"k", "v", "k_scale", "v_scale"}
        for name in cache_a:
            np.testing.assert_array_equal(np.asarray(cache_a[name]),
                                          np.asarray(cache_b[name]))

    def test_greedy_spec_parity_quantized(self, setup):
        """Speculation on/off over an int8 cache: token-identical to
        each other AND to the f32 no-spec engine (peaked params keep
        the argmax gaps above quantization noise)."""
        _, base_params = setup
        params = {**base_params, "embed": base_params["embed"] * 8}
        cfg_q = tiny_cfg(kv_dtype="int8")
        rng = np.random.default_rng(8)
        prompts = [motif_prompt(rng, cfg_q.vocab_size, 12),
                   rng.integers(1, cfg_q.vocab_size, 9)
                   .astype(np.int32)]

        def run(cfg, **ekw):
            eng = make_engine(cfg, params, **ekw)
            outs = [eng.generate(p, max_new_tokens=10) for p in prompts]
            eng.check_invariants()
            return outs, eng.stats()

        f32, _ = run(tiny_cfg())
        base, bs = run(cfg_q)
        ng, ns = run(cfg_q, spec="ngram", spec_k=4)
        dr, ds = run(cfg_q, spec="draft", spec_k=3,
                     draft_params=params, draft_cfg=cfg_q)
        assert f32 == base == ng == dr
        assert ns["verify_traces"] == 1 and ds["verify_traces"] == 1
        assert bs["decode_traces"] == 1
