"""Deterministic fault-injection harness (`ray_tpu/util/faults.py`):
seeded plans replay the identical fire sequence, netaddr delay/drop
present exactly like a slow/lossy control channel, and a dropped
control message surfaces as a TYPED timeout at the attach client — not
a hang and not a spurious dead-channel error."""

import os
import pickle
import threading
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError
from ray_tpu.util import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _drive(plan, site, n):
    """Install `plan`, hit `site` n times, return the fired log."""
    faults.install(plan)
    for _ in range(n):
        try:
            faults.check(site)
        except faults.FaultInjected:
            pass
    return faults.fired()


def test_seeded_plan_replays_identically():
    def build():
        return (faults.FaultPlan(seed=7)
                .fail("x", p=0.3, times=None)
                .delay("x", delay_s=0.0, at=5, times=2))

    first = _drive(build(), "x", 40)
    assert first, "a p=0.3 spec over 40 visits must fire at least once"
    assert ("x", 5, "delay") in first and ("x", 6, "delay") in first
    # same seed, same plan -> byte-identical fire sequence
    assert _drive(build(), "x", 40) == first
    # a different seed flips some coins
    other = _drive(faults.FaultPlan(seed=8).fail("x", p=0.3, times=None),
                   "x", 40)
    assert [v for (_, v, a) in other if a == "fail"] != \
           [v for (_, v, a) in first if a == "fail"]


def test_count_gated_specs_and_clear():
    plan = faults.FaultPlan().fail("s", at=2, times=2)
    faults.install(plan)
    fired_at = []
    for visit in range(6):
        try:
            faults.check("s")
        except faults.FaultInjected:
            fired_at.append(visit)
    assert fired_at == [2, 3]
    faults.clear()
    assert faults.active() is None
    assert faults.check("s") is None      # no plan: fast no-op


def test_plan_pickles_for_actor_shipping():
    plan = (faults.FaultPlan(seed=3)
            .kill("engine.emit", at=20)
            .drop("netaddr.send", at=1, times=3)
            .delay("engine.tick", delay_s=0.25, p=0.5))
    back = pickle.loads(pickle.dumps(plan))
    assert back.seed == 3
    assert [(s.site, s.action, s.at, s.times, s.p, s.delay_s)
            for s in back.specs] == \
           [(s.site, s.action, s.at, s.times, s.p, s.delay_s)
            for s in plan.specs]


@pytest.fixture
def conn_pair(tmp_path):
    """A netaddr listener/client pair over UDS (accept runs on a side
    thread — `netaddr.client` blocks in the authkey handshake)."""
    from ray_tpu._private import netaddr
    addr = str(tmp_path / "chan.sock")
    lst = netaddr.listener(addr, b"k")
    box = {}

    def accept():
        box["server"] = lst.accept()

    t = threading.Thread(target=accept, daemon=True)
    t.start()
    client = netaddr.client(addr, b"k")
    t.join(timeout=10)
    assert "server" in box
    yield client, box["server"]
    client.close()
    box["server"].close()
    lst.close()


def test_netaddr_drop_loses_exactly_the_planned_message(tmp_path):
    from ray_tpu._private import netaddr
    faults.install(faults.FaultPlan().drop("netaddr.send", at=0))
    addr = str(tmp_path / "chan.sock")
    lst = netaddr.listener(addr, b"k")
    box = {}
    t = threading.Thread(target=lambda: box.update(s=lst.accept()),
                         daemon=True)
    t.start()
    client = netaddr.client(addr, b"k")   # wrapped: plan declares sites
    t.join(timeout=10)
    server = box["s"]
    try:
        client.send("lost")               # visit 0: dropped on the floor
        assert not server.poll(0.3)
        client.send("kept")               # visit 1: passes through
        assert server.poll(5)
        assert server.recv() == "kept"
    finally:
        client.close()
        server.close()
        lst.close()


def test_netaddr_delay_adds_planned_latency(conn_pair):
    client, server = conn_pair
    # the pair was dialed with no plan -> unwrapped; wrap explicitly so
    # the test controls exactly one side
    faults.install(faults.FaultPlan().delay("netaddr.send", delay_s=0.3))
    slow = faults.maybe_wrap_connection(client, "netaddr")
    t0 = time.perf_counter()
    slow.send("late")
    assert time.perf_counter() - t0 >= 0.3    # send blocked by the plan
    assert server.poll(5)
    assert server.recv() == "late"
    assert faults.fired() == [("netaddr.send", 0, "delay")]


def test_dropped_control_message_is_typed_timeout(ray_session):
    """Satellite: a lost control request must surface as GetTimeoutError
    (retryable, typed) at the attach client — not an indefinite hang,
    not ConnectionError (the channel is fine; one message vanished)."""
    from ray_tpu._private.attach import AttachClient
    session_dir = ray_tpu._worker.get_client().node.session_dir
    # visit 0 is RegisterWorker (must survive); visit 1 is the first
    # control request — that one vanishes
    faults.install(faults.FaultPlan().drop("netaddr.send", at=1))
    client = AttachClient(session_dir)
    try:
        with pytest.raises(GetTimeoutError):
            client.control("list_nodes", timeout=2.0)
        assert ("netaddr.send", 1, "drop") in faults.fired()
        faults.clear()
        # channel is still healthy: the next request round-trips
        nodes = client.control("list_nodes", timeout=30.0)
        assert any(n.get("alive") for n in nodes)
    finally:
        faults.clear()
        client.close()


def test_batched_frame_faults_stay_per_logical_message(tmp_path):
    """A coalesced burst rides ONE wire frame, but the fault proxy sits
    OUTSIDE the frame layer: seeded drop decisions hit individual
    logical messages, and the survivors keep FIFO order."""
    from ray_tpu._private import netaddr
    faults.install(faults.FaultPlan(seed=11)
                   .drop("netaddr.send", at=2)
                   .drop("netaddr.send", at=5))
    addr = str(tmp_path / "chan.sock")
    lst = netaddr.listener(addr, b"k")
    box = {}
    t = threading.Thread(target=lambda: box.update(s=lst.accept()),
                         daemon=True)
    t.start()
    client = netaddr.client(addr, b"k")
    t.join(timeout=10)
    server = box["s"]
    bc = client._conn          # the BatchedConnection under the proxy
    try:
        # Hold the wire so the burst queues behind it — the flusher then
        # drains all survivors into a single _Batch frame.
        with bc._wire_lock:
            for i in range(8):
                client.send(i)
        bc.flush(timeout=5.0)
        got = []
        while server.poll(1.0):
            got.append(server.recv())
            if server._in:
                # unpacked siblings from the same wire frame: proof the
                # burst really coalesced
                box["framed"] = True
        assert got == [0, 1, 3, 4, 6, 7]   # visits 2 and 5 vanished
        assert box.get("framed"), "burst did not coalesce into a frame"
        assert [(s, v) for s, v, a in faults.fired() if a == "drop"] \
            == [("netaddr.send", 2), ("netaddr.send", 5)]
    finally:
        client.close()
        server.close()
        lst.close()
