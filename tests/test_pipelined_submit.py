"""Pipelined-submission coverage: the windowed credit/nack/replay path
must deliver every task exactly once — through worker crashes mid-window
and through replayed seq streams — and the batched dispatch fastpath
must keep PR 7's blocked-workers-release-their-slot invariant."""

import os
import subprocess
import sys
import textwrap
from types import SimpleNamespace

import pytest

import ray_tpu
from ray_tpu._private import protocol
from ray_tpu._private.node import NodeServer


# ---------------------------------------------------------------------------
# head-side seq state machine (deterministic, no cluster)
# ---------------------------------------------------------------------------

def _fake_head_and_worker():
    applied = []
    errors = []
    sent = []
    head = SimpleNamespace(
        submit=lambda spec, submitter=None: applied.append(spec),
        _store_error=lambda rids, e, spec=None: errors.append((rids, e)),
        _SUBMIT_CREDIT_EVERY=NodeServer._SUBMIT_CREDIT_EVERY,
    )
    w = SimpleNamespace(sub_next=0, sub_nacked=False,
                        send=lambda msg: sent.append(msg) or True)
    return head, w, applied, errors, sent


def _req(seq):
    return SimpleNamespace(seq=seq, spec=f"spec{seq}", req_id=-1)


def test_seq_gap_nacks_once_and_replay_applies_exactly_once():
    head, w, applied, _, sent = _fake_head_and_worker()
    step = NodeServer._on_pipelined_submit
    for seq in (0, 1):
        step(head, w, _req(seq))
    assert applied == ["spec0", "spec1"]
    # seqs 2 and 3 vanish mid-window; 4 and 5 arrive — ONE nack for the
    # whole gap, nothing out of order applied
    step(head, w, _req(4))
    step(head, w, _req(5))
    assert applied == ["spec0", "spec1"]
    nacks = [m for m in sent if isinstance(m, protocol.SubmitNack)]
    assert [n.expected_seq for n in nacks] == [2]
    # sender replays its ring from the nacked seq: every spec lands
    # exactly once, in order
    for seq in (2, 3, 4, 5):
        step(head, w, _req(seq))
    assert applied == [f"spec{i}" for i in range(6)]
    # late duplicates (replay overlap / lost credit) re-credit the
    # watermark but never re-apply
    step(head, w, _req(3))
    assert applied == [f"spec{i}" for i in range(6)]
    credits = [m for m in sent if isinstance(m, protocol.SubmitCredit)]
    assert credits and credits[-1].ack_seq == 5


def test_failed_submit_stores_error_but_advances_seq():
    head, w, applied, errors, sent = _fake_head_and_worker()

    def boom(spec, submitter=None):
        raise RuntimeError("no capacity ledger")

    head.submit = boom
    msg = SimpleNamespace(seq=0, spec=SimpleNamespace(return_ids=["o1"]),
                          req_id=-1)
    NodeServer._on_pipelined_submit(head, w, msg)
    # the stream must not wedge on a bad spec: seq advanced, error
    # stored under the return ids for the eventual get()
    assert w.sub_next == 1
    assert errors and errors[0][0] == ["o1"]


# ---------------------------------------------------------------------------
# end-to-end: crash a worker mid-window
# ---------------------------------------------------------------------------

def test_worker_crash_mid_window_delivers_exactly_once(ray_session,
                                                       tmp_path):
    """SIGKILL-shaped worker death while a full submission window is in
    flight: the retry path re-runs the victim's task, every other task
    runs once, and every TaskDone is delivered exactly once (no result
    lost, none duplicated)."""
    log = str(tmp_path / "ran.log")
    crash_marker = str(tmp_path / "crashed")

    @ray_tpu.remote(max_retries=2)
    def tracked(i):
        if i == 7 and not os.path.exists(crash_marker):
            # first attempt dies before any side effect: the retried
            # attempt is the only one that logs
            with open(crash_marker, "w"):
                pass
            os._exit(1)
        fd = os.open(log, os.O_WRONLY | os.O_CREAT | os.O_APPEND)
        os.write(fd, f"{i}\n".encode())  # O_APPEND: one atomic line
        os.close(fd)
        return i

    n = 120
    refs = [tracked.remote(i) for i in range(n)]
    out = ray_tpu.get(refs, timeout=180)
    assert out == list(range(n))
    with open(log) as f:
        ran = sorted(int(x) for x in f.read().split())
    assert ran == list(range(n)), "a task ran twice or never"
    assert os.path.exists(crash_marker), "crash never fired; test " \
                                         "proved nothing"


# ---------------------------------------------------------------------------
# PR 7 regression under batched dispatch
# ---------------------------------------------------------------------------

def test_blocked_workers_dont_pin_pool_cap_under_batched_dispatch():
    """Nested gets with MAX_WORKERS_CAP=1 (every level needs a
    replacement worker while its parent blocks) must still resolve with
    channel batching + pipelined submission + the freed-slot dispatch
    fastpath all on — the batched paths must observe the same
    lease-release rules as the per-task ones."""
    child = textwrap.dedent("""
        import ray_tpu
        ray_tpu.init(num_cpus=4)

        @ray_tpu.remote
        def leaf():
            return 1

        @ray_tpu.remote
        def mid():
            return ray_tpu.get(leaf.remote()) + 1

        @ray_tpu.remote
        def top():
            return ray_tpu.get(mid.remote()) + 1

        print("RESULT", ray_tpu.get(top.remote(), timeout=90))
        ray_tpu.shutdown()
    """)
    env = dict(os.environ,
               RAY_TPU_MAX_WORKERS_CAP="1",
               RAY_TPU_CHANNEL_BATCHING="1",
               RAY_TPU_SUBMIT_PIPELINE="1")
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    assert "RESULT 3" in r.stdout
