"""TCP transport tier.

The cluster must span machines (reference: gRPC-over-TCP for every
cross-host edge, src/ray/rpc/grpc_server.h; node IP assembly
services.py:1353). Everything here runs over 127.0.0.1:PORT — same code
path a real multi-host deployment takes, minus the wire:

- `test_tcp_cluster_end_to_end`: head TCP listener + two HostDaemons
  registering over TCP, cross-node object transfer via TCP peer pulls,
  and a second driver process joining over `init(address="host:port")`
  with the authkey handed via RAY_TPU_AUTHKEY.
- `test_multi_node_matrix_over_tcp` / `test_chaos_matrix_over_tcp`: the
  FULL existing multi-node + chaos suites re-run with
  RAY_TPU_TRANSPORT=tcp, so every scheduling/placement/failure behavior
  is exercised on the TCP tier too.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tcp_env():
    env = dict(os.environ)
    env["RAY_TPU_TRANSPORT"] = "tcp"
    env["RAY_TPU_HEAD_BIND_HOST"] = "127.0.0.1"
    return env


_E2E_DRIVER = """
import os, subprocess, sys, time
import numpy as np
import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu._private.worker import get_client

c = Cluster(head_resources={"CPU": 2})
n1 = c.add_node({"CPU": 2, "left": 1})
n2 = c.add_node({"CPU": 2, "right": 1})

node = get_client().node
assert node.tcp_address is not None and ":" in node.tcp_address, \\
    f"head has no TCP address: {node.tcp_address!r}"
# daemons must have advertised dialable TCP peer addresses, not paths
for nid in (n1, n2):
    addr = node.nodes[nid].address
    assert not addr.startswith("/"), f"node {nid} advertised a path: {addr}"
    assert ":" in addr, addr

@ray_tpu.remote(resources={"left": 1})
def produce():
    return np.arange(300_000, dtype=np.float32)   # > inline cap

@ray_tpu.remote(resources={"right": 1})
def consume(a):
    return float(a.sum())

# produced on n1, consumed on n2: the bytes cross a TCP peer pull
ref = produce.remote()
total = ray_tpu.get(consume.remote(ref), timeout=120)
assert total == float(np.arange(300_000, dtype=np.float32).sum()), total

# driver-side get of a remote object crosses node->head TCP
arr = ray_tpu.get(ref, timeout=120)
assert arr.shape == (300_000,)

# second driver joins over TCP like a process on another machine
client_env = dict(os.environ)
client_env["RAY_TPU_AUTHKEY"] = node._authkey.hex()
client_env["RAY_TPU_HEAD"] = node.tcp_address
r = subprocess.run([sys.executable, "-c", CLIENT], env=client_env,
                   capture_output=True, text=True, timeout=180)
sys.stderr.write(r.stdout + r.stderr)
assert r.returncode == 0, "tcp client driver failed"
c.shutdown()
print("E2E-OK")
"""

_E2E_CLIENT = """
import os
import numpy as np
import ray_tpu

ray_tpu.init(address=os.environ["RAY_TPU_HEAD"])

@ray_tpu.remote
def double(a):
    return a * 2

# put > inline cap: exercises the oversized-inline re-materialization
big = np.ones(200_000, dtype=np.float32)
ref = ray_tpu.put(big)
out = ray_tpu.get(double.remote(ref), timeout=120)
assert out.sum() == 2 * big.sum()
assert ray_tpu.get(ray_tpu.put(123)) == 123
ray_tpu.shutdown()
print("CLIENT-OK")
"""


def test_tcp_cluster_end_to_end():
    env = _tcp_env()
    script = f"CLIENT = {_E2E_CLIENT!r}\n" + _E2E_DRIVER
    r = subprocess.run([sys.executable, "-c", script], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "E2E-OK" in r.stdout
    assert "CLIENT-OK" in (r.stdout + r.stderr)


_BLIP_DRIVER = """
import sys, time
import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu._private.worker import get_client

c = Cluster(head_resources={"CPU": 1})
nid = c.add_node({"CPU": 3, "left": 1, "right": 1})
node = get_client().node

@ray_tpu.remote(resources={"left": 1}, num_cpus=1)
def slow():
    import time as t
    t.sleep(6)
    return 7

@ray_tpu.remote(resources={"right": 1}, num_cpus=1)
def quick():
    return 42

ref = slow.remote()
time.sleep(3.5)             # leased; worker spawned and running
rn = node.nodes[nid]
assert rn.inflight, "task not inflight on the daemon yet"

# Half-open channel blip, worst-case ordering: the daemon reconnects and
# re-registers BEFORE the head observes the old channel's EOF. The shim
# delays the head's EOF handler past the re-registration; the daemon's
# NodeTaskDone lands inside the blip window, where TCP swallows the
# first write into a half-closed socket without an error.
orig_death = node._on_node_death
def late_death(n):
    time.sleep(6)
    orig_death(n)
node._on_node_death = late_death
rn.conn.close()

# a lease dispatched INTO the dead channel: the daemon never receives
# it, so its absence from the re-registration's lease list must requeue
# it onto the new channel (without this it waits in inflight forever)
ref2 = quick.remote()

# the completion must arrive via the seq-ring replay on the new channel
# and be found in the MIGRATED inflight table — either missing piece
# hangs this get() forever
assert ray_tpu.get(ref, timeout=60) == 7
assert ray_tpu.get(ref2, timeout=60) == 42
time.sleep(7)               # let the late EOF fire against the old object

# the superseded registration's teardown must be a no-op: node alive,
# resources balanced, and fresh work still runs there
new_rn = node.nodes[nid]
assert new_rn.alive and new_rn is not rn and not rn.alive
assert new_rn.available.get("CPU") == 3.0, new_rn.available
assert new_rn.available.get("left") == 1.0, new_rn.available
assert new_rn.available.get("right") == 1.0, new_rn.available
assert ray_tpu.get(slow.remote(), timeout=60) == 7
c.shutdown()
print("BLIP-OK")
"""


def test_channel_blip_replay_and_supersede():
    """Daemon channel blip + reconnect: blip-window completions replay
    exactly once (NodeSeq ring), the superseded registration's inflight
    migrates, and its late EOF never tears down the live node."""
    env = _tcp_env()
    env["RAY_TPU_DAEMON_RECONNECT_GRACE_S"] = "30"
    r = subprocess.run([sys.executable, "-c", _BLIP_DRIVER], env=env,
                       cwd=REPO, capture_output=True, text=True,
                       timeout=240)
    assert r.returncode == 0, \
        f"stdout:\n{r.stdout}\nstderr:\n{r.stderr[-4000:]}"
    assert "BLIP-OK" in r.stdout


def _run_matrix(path: str, timeout: int):
    r = subprocess.run(
        [sys.executable, "-m", "pytest", path, "-x", "-q",
         "-p", "no:cacheprovider"],
        env=_tcp_env(), cwd=REPO, capture_output=True, text=True,
        timeout=timeout)
    assert r.returncode == 0, \
        f"{path} failed over TCP\nstdout:\n{r.stdout[-8000:]}\n" \
        f"stderr:\n{r.stderr[-4000:]}"


@pytest.mark.slow
def test_multi_node_matrix_over_tcp():
    _run_matrix("tests/test_multi_node.py", timeout=1500)


@pytest.mark.slow
def test_chaos_matrix_over_tcp():
    _run_matrix("tests/test_chaos.py", timeout=1500)
