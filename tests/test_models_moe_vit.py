"""MoE transformer (expert parallel) and ViT model families.

Route correctness (dispatch/combine mass, capacity drops), overfit
smoke-regressions, and the expert-sharded train step on the virtual mesh
(SURVEY.md §4.2 fixture trick).
"""

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import moe, vit
from ray_tpu.models.moe import _route


def test_moe_forward_shapes_and_finite():
    cfg = moe.small()
    params = moe.init_params(jax.random.key(0), cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits, aux = moe.forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    assert float(aux) > 0.0           # load-balance loss is positive


def test_route_combine_mass():
    """Every non-dropped token's combine weights sum to ~1 (renormalized
    over its top-k picks); dispatch entries are one-hot per (token, pick)."""
    cfg = moe.small(n_experts=4, top_k=2, capacity_factor=4.0)  # no drops
    h = jax.random.normal(jax.random.key(1), (32, cfg.d_model))
    router = jax.random.normal(jax.random.key(2),
                               (cfg.d_model, cfg.n_experts)) * 0.1
    dispatch, combine, aux = _route(h, router, cfg)
    mass = np.asarray(jnp.sum(combine, axis=(1, 2)))
    np.testing.assert_allclose(mass, 1.0, atol=1e-5)
    picks = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    np.testing.assert_allclose(picks, cfg.top_k, atol=1e-5)
    # each expert buffer slot holds at most one token
    slot_fill = np.asarray(jnp.sum(dispatch, axis=0))
    assert slot_fill.max() <= 1.0 + 1e-5


def test_route_capacity_drops():
    """With capacity_factor << 1 tokens overflow and are dropped."""
    cfg = moe.small(n_experts=4, top_k=1, capacity_factor=0.25)
    h = jax.random.normal(jax.random.key(3), (64, cfg.d_model))
    router = jnp.zeros((cfg.d_model, cfg.n_experts))   # uniform router
    dispatch, combine, _ = _route(h, router, cfg)
    picks = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert picks.sum() < 64          # some tokens dropped
    assert picks.max() <= 1.0 + 1e-5


def test_moe_overfits_tiny_batch():
    cfg = moe.small(remat=False)
    params = moe.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 33)))
    batch = {"tokens": tokens}
    import optax
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)
    loss_grad = jax.jit(jax.value_and_grad(
        lambda p: moe.loss_fn(p, batch, cfg)))
    first = None
    for i in range(30):
        loss, grads = loss_grad(params)
        if first is None:
            first = float(loss)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
    assert float(loss) < first * 0.7, (first, float(loss))


def test_moe_expert_parallel_step():
    """Train step with experts sharded over the mesh's expert axis."""
    from ray_tpu.parallel import MeshSpec
    from ray_tpu.train import spmd
    devices = jax.devices()[:8]
    if len(devices) < 8:
        import pytest
        pytest.skip("needs 8 virtual devices")
    mesh = MeshSpec(data=2, expert=4).build(devices)
    cfg = moe.small(n_experts=4)
    import optax
    # no-warmup optimizer: the default's LR schedule starts at 0, which
    # would make the improving-loss assertion vacuous at step 2
    state, step_fn, shard = spmd.make_moe_trainer(
        cfg, mesh, optimizer=optax.adam(3e-3))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (4, cfg.max_seq_len + 1),
                        np.int32)
    batch = shard({"inputs": toks[:, :-1].copy(),
                   "targets": toks[:, 1:].copy()})
    state, m1 = step_fn(state, batch)
    state, m2 = step_fn(state, batch)
    assert np.isfinite(float(m2["loss"]))
    assert float(m2["loss"]) < float(m1["loss"])   # same batch, improving


def test_vit_forward_and_overfit():
    cfg = vit.small(remat=False)
    params = vit.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(size=(8, 32, 32, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, cfg.num_classes, 8))
    logits = vit.forward(params, images, cfg)
    assert logits.shape == (8, cfg.num_classes)

    import optax
    batch = {"images": images, "labels": labels}
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    loss_grad = jax.jit(jax.value_and_grad(
        lambda p: vit.loss_fn(p, batch, cfg)))
    first = None
    for _ in range(40):
        loss, grads = loss_grad(params)
        if first is None:
            first = float(loss)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
    assert float(loss) < first * 0.5, (first, float(loss))


def test_vit_sharded_dp_step():
    from ray_tpu.parallel import MeshSpec
    from ray_tpu.parallel.sharding import tree_shardings
    devices = jax.devices()[:8]
    if len(devices) < 8:
        import pytest
        pytest.skip("needs 8 virtual devices")
    mesh = MeshSpec(data=4, tensor=2).build(devices)
    cfg = vit.small(remat=False)
    shardings = tree_shardings(mesh, vit.param_logical_axes(cfg))
    params = jax.jit(lambda k: vit.init_params(k, cfg),
                     out_shardings=shardings)(jax.random.key(0))
    rng = np.random.default_rng(0)
    batch = {"images": jnp.asarray(rng.normal(size=(8, 32, 32, 3)),
                                   jnp.float32),
             "labels": jnp.asarray(rng.integers(0, 10, 8))}
    loss = jax.jit(lambda p, b: vit.loss_fn(p, b, cfg))(params, batch)
    assert np.isfinite(float(loss))
