"""Cluster launcher CLI: `ray_tpu up / submit / down` from a YAML config.

Counterpart of the reference's cluster launcher
(`scripts/scripts.py:1235-1728` up/down/attach/exec/submit driving
`autoscaler/_private/commands.py`): `up` starts a standalone head +
attaches the autoscaler (min_workers populate via
LocalDaemonNodeProvider), `submit` runs a script as a job wired to the
cluster, `down` tears it all down.
"""

import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

JOB_SCRIPT = """
import os
import ray_tpu
ray_tpu.init(address=os.environ["RAY_TPU_ADDRESS"])

@ray_tpu.remote(resources={"launcher_worker": 1})
def where():
    return os.environ.get("RAY_TPU_NODE_ID", "head")

node = ray_tpu.get(where.remote(), timeout=120)
assert node != "head", node
print("JOB-RAN-ON", node)
ray_tpu.shutdown()
"""


def _cli(*argv, timeout=180, env=None):
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", *argv],
        cwd=REPO, env=env or dict(os.environ), capture_output=True,
        text=True, timeout=timeout)


def test_up_submit_down(tmp_path):
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    cfg = tmp_path / "cluster.yaml"
    cfg.write_text(f"""
cluster_name: launcher_test
max_workers: 2
idle_timeout_minutes: 30
head:
  port: {port}
  num_cpus: 2
available_node_types:
  worker:
    resources: {{CPU: 2, launcher_worker: 1}}
    min_workers: 1
    max_workers: 2
""")
    script = tmp_path / "job.py"
    script.write_text(JOB_SCRIPT)

    env = dict(os.environ)
    env["HOME"] = str(tmp_path)           # isolate ~/.ray_tpu state
    env["RAY_TPU_HEAD_BIND_HOST"] = "127.0.0.1"
    up = down = None
    try:
        up = _cli("up", "-f", str(cfg), env=env, timeout=240)
        assert up.returncode == 0, up.stdout + up.stderr
        assert "1 worker node(s)" in up.stdout, up.stdout

        state = json.load(open(
            tmp_path / ".ray_tpu" / "clusters" / "launcher_test.json"))
        session = state["session"]
        assert os.path.exists(os.path.join(session, "head_address"))

        sub = _cli("submit", "launcher_test", str(script), env=env,
                   timeout=240)
        assert sub.returncode == 0, sub.stdout + sub.stderr
        assert "JOB-RAN-ON" in sub.stdout
        assert "SUCCEEDED" in sub.stdout
    finally:
        down = _cli("down", "launcher_test", env=env, timeout=60)
        # teardown must report success and actually kill the head
        assert down.returncode == 0, down.stdout + down.stderr
        time.sleep(2.0)
        assert not os.path.exists(
            tmp_path / ".ray_tpu" / "clusters" / "launcher_test.json")
