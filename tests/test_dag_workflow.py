"""DAG API (.bind graphs) and durable workflows.

Counterpart of the reference's `python/ray/dag/tests/` (bind/execute,
InputNode, class nodes, diamond sharing) and `python/ray/workflow/tests/`
(checkpointed steps, resume-after-failure, output retrieval).
"""

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode, MultiOutputNode


@pytest.fixture
def cluster(ray_session):
    return ray_session


def test_function_dag_execute(cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def mul(a, b):
        return a * b

    dag = mul.bind(add.bind(1, 2), add.bind(3, 4))
    assert ray_tpu.get(dag.execute()) == 21


def test_diamond_shared_subtree_runs_once(cluster):
    @ray_tpu.remote
    def source():
        import os
        return os.urandom(8).hex()   # unique per invocation

    @ray_tpu.remote
    def pair(a, b):
        return (a, b)

    s = source.bind()
    a, b = ray_tpu.get(pair.bind(s, s).execute())
    assert a == b   # memoized: one task for the shared node


def test_input_node(cluster):
    @ray_tpu.remote
    def scale(x, k):
        return x * k

    with InputNode() as inp:
        dag = scale.bind(inp, 10)
    assert ray_tpu.get(dag.execute(7)) == 70
    assert ray_tpu.get(dag.execute(3)) == 30


def test_input_attribute_access(cluster):
    @ray_tpu.remote
    def use(a, b):
        return a - b

    with InputNode() as inp:
        dag = use.bind(inp["x"], inp["y"])
    assert ray_tpu.get(dag.execute({"x": 9, "y": 4})) == 5


def test_class_node_and_methods(cluster):
    @ray_tpu.remote
    class Accum:
        def __init__(self, start):
            self.v = start

        def add(self, x):
            self.v += x
            return self.v

    node = Accum.bind(100)
    dag = node.add.bind(5)
    assert ray_tpu.get(dag.execute()) == 105


def test_multi_output(cluster):
    @ray_tpu.remote
    def f(i):
        return i * 2

    dag = MultiOutputNode([f.bind(1), f.bind(2), f.bind(3)])
    assert ray_tpu.get(dag.execute()) == [2, 4, 6]


# ---------------------------------------------------------------------------
# workflows
# ---------------------------------------------------------------------------

@pytest.fixture
def wf_store(tmp_path):
    workflow.init(str(tmp_path))
    yield str(tmp_path)


def test_workflow_run_and_replay(cluster, wf_store):
    @ray_tpu.remote
    def step_a():
        import os
        return os.urandom(8).hex()   # unique per actual execution

    @ray_tpu.remote
    def step_b(x):
        return "out:" + x

    dag = step_b.bind(step_a.bind())
    first = workflow.run(dag, workflow_id="w1")
    assert workflow.get_status("w1") == "SUCCESSFUL"
    assert workflow.get_output("w1") == first
    # re-running replays from storage: same value => steps NOT re-executed
    assert workflow.run(dag, workflow_id="w1") == first


def test_workflow_resume_after_failure(cluster, wf_store):
    @ray_tpu.remote
    def first():
        return 1

    @ray_tpu.remote
    def flaky(x, fail_marker):
        import os
        if os.path.exists(fail_marker):
            raise RuntimeError("injected failure")
        return x + 100

    marker = wf_store + "/fail_on"
    open(marker, "w").close()
    dag = flaky.bind(first.bind(), marker)
    with pytest.raises(RuntimeError):
        workflow.run(dag, workflow_id="w2")
    assert workflow.get_status("w2") == "FAILED"

    # clear the fault; resume executes only the failed step (step 'first'
    # replays from its checkpoint)
    import os
    os.remove(marker)
    assert workflow.resume("w2") == 101
    assert workflow.get_status("w2") == "SUCCESSFUL"


def test_workflow_with_input(cluster, wf_store):
    @ray_tpu.remote
    def double(x):
        return x * 2

    with InputNode() as inp:
        dag = double.bind(inp)
    assert workflow.run(dag, workflow_id="w3", dag_input=21) == 42


def test_workflow_parallel_siblings(cluster, wf_store):
    """Independent branches are submitted together, not serialized: the
    execution windows of sibling steps must overlap (timestamp evidence,
    not wall-clock bounds, so cold worker spawn can't flake the test)."""
    import time as _time

    @ray_tpu.remote
    def slow(i):
        start = _time.time()
        _time.sleep(0.5)
        return (start, _time.time())

    @ray_tpu.remote
    def gather(a, b, c):
        return [a, b, c]

    dag = gather.bind(slow.bind(1), slow.bind(2), slow.bind(3))
    spans = workflow.run(dag, workflow_id="wpar")
    overlaps = sum(
        1 for i in range(3) for j in range(i + 1, 3)
        if spans[i][0] < spans[j][1] and spans[j][0] < spans[i][1])
    assert overlaps >= 1, f"no sibling steps overlapped: {spans}"


def test_workflow_input_mismatch_rejected(cluster, wf_store):
    @ray_tpu.remote
    def fail_step(x):
        raise RuntimeError("fail")

    with InputNode() as inp:
        dag = fail_step.bind(inp)
    with pytest.raises(RuntimeError):
        workflow.run(dag, workflow_id="wmix", dag_input=1)
    # retry with a DIFFERENT input under the same id must be rejected
    with pytest.raises(ValueError, match="different"):
        workflow.run(dag, workflow_id="wmix", dag_input=2)


def test_workflow_stale_running_is_resumable(cluster, wf_store):
    @ray_tpu.remote
    def one():
        return 1

    workflow.run(one.bind(), workflow_id="wstale")
    # simulate a kill -9 mid-run: status RUNNING with a dead runner pid
    import json as _json
    meta_path = wf_store + "/wstale/meta.json"
    meta = _json.loads(open(meta_path).read())
    meta["status"] = "RUNNING"
    meta["pid"] = 2 ** 22 + 12345   # beyond pid_max on this box
    open(meta_path, "w").write(_json.dumps(meta))
    assert workflow.get_status("wstale") == "RESUMABLE"
    assert workflow.resume("wstale") == 1
    assert workflow.get_status("wstale") == "SUCCESSFUL"


def test_workflow_list_and_delete(cluster, wf_store):
    @ray_tpu.remote
    def one():
        return 1

    workflow.run(one.bind(), workflow_id="wlist")
    ids = [w.workflow_id for w in workflow.list_all()]
    assert "wlist" in ids
    workflow.delete("wlist")
    assert "wlist" not in [w.workflow_id for w in workflow.list_all()]


# ---------------------------------------------------------------------------
# dynamic workflows (continuations), content-based identity, management
# (reference: workflow_executor.py:32 continuations; api.cancel/resume_all)
# ---------------------------------------------------------------------------


def test_workflow_recursive_continuation(cluster, wf_store):
    """A step that returns a DAG recurses durably: factorial via
    continuation, checkpointed at every level."""
    @ray_tpu.remote
    def fact(n, acc=1):
        if n <= 1:
            return acc
        return fact.bind(n - 1, acc * n)

    assert workflow.run(fact.bind(5), workflow_id="wrec") == 120
    assert workflow.get_status("wrec") == "SUCCESSFUL"
    # every recursion level left a namespaced checkpoint
    import os
    steps = os.listdir(os.path.join(wf_store, "wrec", "steps"))
    assert sum(1 for s in steps if "fact" in s) >= 5, steps


def test_workflow_continuation_resume(cluster, wf_store, tmp_path):
    """Crash mid-continuation: completed sub-steps replay from their
    namespaced checkpoints on resume."""
    marker = tmp_path / "boom"
    count = tmp_path / "count"

    @ray_tpu.remote
    def chain(n):
        with open(count, "a") as f:
            f.write("x")
        if n == 2 and marker.exists():
            raise RuntimeError("boom")
        if n <= 0:
            return "done"
        return chain.bind(n - 1)

    marker.write_text("1")
    with pytest.raises(Exception):
        workflow.run(chain.bind(4), workflow_id="wcr")
    assert workflow.get_status("wcr") == "FAILED"
    ran_before = len(count.read_text())
    marker.unlink()
    assert workflow.resume("wcr") == "done"
    # levels 4 and 3 replayed from checkpoints; only the failed level
    # (2) and deeper re-ran
    ran_after = len(count.read_text()) - ran_before
    assert ran_after == 3, (ran_before, ran_after)


def test_workflow_edit_invalidates_step(cluster, wf_store, tmp_path):
    """Content-based identity: editing a step's CODE re-executes it on
    the next run instead of silently replaying the stale checkpoint
    (the positional-id failure mode)."""
    a_runs = tmp_path / "a_runs"
    b_runs = tmp_path / "b_runs"

    @ray_tpu.remote
    def upstream():
        with open(a_runs, "a") as f:
            f.write("x")
        return 10

    @ray_tpu.remote
    def downstream(x):
        with open(b_runs, "a") as f:
            f.write("x")
        return x + 1

    assert workflow.run(downstream.bind(upstream.bind()),
                        workflow_id="wedit") == 11
    assert (len(a_runs.read_text()), len(b_runs.read_text())) == (1, 1)

    # unchanged DAG: pure replay, nothing re-executes
    assert workflow.run(downstream.bind(upstream.bind()),
                        workflow_id="wedit") == 11
    assert (len(a_runs.read_text()), len(b_runs.read_text())) == (1, 1)

    # EDIT downstream's code: it (and only it) must re-execute
    @ray_tpu.remote
    def downstream(x):  # noqa: F811
        with open(b_runs, "a") as f:
            f.write("x")
        return x + 2

    assert workflow.run(downstream.bind(upstream.bind()),
                        workflow_id="wedit") == 12
    assert (len(a_runs.read_text()), len(b_runs.read_text())) == (1, 2)

    # EDIT upstream's code: upstream re-runs AND downstream's identity
    # changes with its input lineage, so both re-execute
    @ray_tpu.remote
    def upstream():  # noqa: F811
        with open(a_runs, "a") as f:
            f.write("x")
        return 20
    assert workflow.run(downstream.bind(upstream.bind()),
                        workflow_id="wedit") == 22
    assert (len(a_runs.read_text()), len(b_runs.read_text())) == (2, 3)


def test_workflow_cancel_and_resume_all(cluster, wf_store, tmp_path):
    """cancel() stops the run at a step boundary keeping checkpoints;
    resume_all() picks up every non-successful workflow."""
    import threading
    import time as _time

    @ray_tpu.remote
    def slow(i):
        import time as _t
        _t.sleep(0.5)
        return i

    @ray_tpu.remote
    def combine(a, b):
        return a + b

    # cancel from the driver while steps are in flight; the executor
    # observes it at its next step boundary
    canceller = threading.Timer(0.2, workflow.cancel, args=("wcancel",))
    dag = combine.bind(slow.bind(1), slow.bind(2))
    canceller.start()
    try:
        with pytest.raises(workflow.WorkflowCancelledError):
            workflow.run(dag, workflow_id="wcancel")
    finally:
        canceller.join()
    assert workflow.get_status("wcancel") == "CANCELED"

    out = workflow.resume_all()
    assert out.get("wcancel") == 3
    assert workflow.get_status("wcancel") == "SUCCESSFUL"
