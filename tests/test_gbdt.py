"""GBDT trainers over the worker-group spine (reference:
train/xgboost/xgboost_trainer.py, train/lightgbm/lightgbm_trainer.py).

The load-bearing test is multi-worker == single-process parity: the
native histogram GBDT takes every split decision on ALLREDUCED
histograms, so a 2-worker fit on shards must produce the identical
model to a local fit on the full data — the same invariant rabit gives
distributed xgboost.
"""

import numpy as np
import pytest

from ray_tpu.train.gbdt import _HistGBDT


def _blobs(n=400, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, 4))
    y = ((X[:, 0] + 0.5 * X[:, 1] - 0.25 * X[:, 2]) > 0).astype(float)
    return X, y


def test_hist_gbdt_classification_learns():
    X, y = _blobs()
    m = _HistGBDT(objective="binary:logistic", n_estimators=30,
                  max_depth=3).fit(X, y)
    acc = float((m.predict(X) == y).mean())
    assert acc > 0.93, acc


def test_hist_gbdt_regression_learns():
    rng = np.random.default_rng(1)
    X = rng.normal(0, 1, (500, 3))
    y = 2.0 * X[:, 0] - X[:, 1] + 0.1 * rng.normal(size=500)
    m = _HistGBDT(objective="squared_error", n_estimators=60,
                  max_depth=3).fit(X, y)
    rmse = float(np.sqrt(np.mean((m.predict_raw(X) - y) ** 2)))
    assert rmse < 0.6, rmse


def _model_signature(m):
    return [(t.feature, [round(v, 10) for v in t.threshold],
             [round(v, 10) for v in t.value]) for t in m.trees]


def test_gbdt_trainer_multiworker_parity(ray_session):
    """2-worker distributed fit == single-process fit on the full data
    (bit-identical trees), proving the histogram allreduce carries ALL
    the split information."""
    from ray_tpu import data as rtd
    from ray_tpu.train import ScalingConfig
    from ray_tpu.train.gbdt import GBDTTrainer

    X, y = _blobs(300)
    rows = [{**{f"f{i}": float(v) for i, v in enumerate(r)},
             "label": float(t)} for r, t in zip(X, y)]
    params = {"objective": "binary:logistic", "n_estimators": 12,
              "max_depth": 3, "n_bins": 32}

    trainer = GBDTTrainer(
        label_column="label", params=params,
        datasets={"train": rtd.from_items(rows)},
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None, result.error
    ck = result.checkpoint.to_dict()
    dist_model = ck["model"]
    assert ck["feature_columns"] == [f"f{i}" for i in range(4)]

    # single-process reference on the SAME full data (order-insensitive:
    # histograms are sums)
    local = _HistGBDT(**params).fit(X, y)
    assert _model_signature(dist_model) == _model_signature(local)
    assert result.metrics["train_accuracy"] > 0.9


def test_gbdt_trainer_single_worker(ray_session):
    from ray_tpu import data as rtd
    from ray_tpu.train import ScalingConfig
    from ray_tpu.train.gbdt import GBDTTrainer

    rng = np.random.default_rng(3)
    rows = [{"a": float(a), "b": float(b),
             "label": float(3 * a - b)}
            for a, b in rng.normal(0, 1, (200, 2))]
    trainer = GBDTTrainer(
        label_column="label",
        params={"objective": "squared_error", "n_estimators": 40},
        datasets={"train": rtd.from_items(rows)},
        scaling_config=ScalingConfig(num_workers=1))
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["train_rmse"] < 0.7


def test_xgboost_lightgbm_trainers_gated():
    """The library adapters exist and explain themselves when the libs
    are absent (this image has neither); with the libs installed the
    same classes fit for real."""
    from ray_tpu.train.gbdt import LightGBMTrainer, XGBoostTrainer
    for cls, lib in ((XGBoostTrainer, "xgboost"),
                     (LightGBMTrainer, "lightgbm")):
        try:
            __import__(lib)
            pytest.skip(f"{lib} installed; gating path not applicable")
        except ImportError:
            pass
        with pytest.raises(ImportError, match="native GBDTTrainer"):
            cls(label_column="y", datasets={})


def test_tf_config_rendezvous_renderer():
    """The TF_CONFIG renderer (reference: train/tensorflow/config.py:21)
    builds a consistent single-host cluster spec and refuses multi-host
    (which would list unbindable addresses). The full MWMS gradient-sync
    path is covered end-to-end in test_train.py."""
    import json
    import os

    import pytest

    from ray_tpu.train.worker_group import TrainWorker
    w = TrainWorker.__new__(TrainWorker)
    old = os.environ.pop("TF_CONFIG", None)
    try:
        n = w.setup_tf_config("127.0.0.1:29500", 3, 1)
        assert n == 3
        tf_config = json.loads(os.environ["TF_CONFIG"])
        assert tf_config["cluster"]["worker"] == [
            "127.0.0.1:29501", "127.0.0.1:29502", "127.0.0.1:29503"]
        assert tf_config["task"] == {"type": "worker", "index": 1}
        # multi-host coordinator: refused up front (the v1 spec would
        # list every rank on the coordinator's host)
        with pytest.raises(NotImplementedError, match="single-host"):
            w.setup_tf_config("10.9.9.9:29500", 2, 1)
    finally:
        if old is not None:
            os.environ["TF_CONFIG"] = old
        else:
            os.environ.pop("TF_CONFIG", None)
