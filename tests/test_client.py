"""Remote-driver client mode (reference: Ray Client,
`util/client/worker.py:81` — ray.init("ray://...")): a second process
joins a live session with the full get/put/remote/actor API and leaves it
running on disconnect."""

import os
import subprocess
import sys
import textwrap

import ray_tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_client_driver_full_api(ray_session):
    @ray_tpu.remote
    class KV:
        def __init__(self):
            self.d = {}

        def put(self, k, v):
            self.d[k] = v
            return True

        def get(self, k):
            return self.d.get(k)

    KV.options(name="client_kv", max_restarts=0).remote()

    script = textwrap.dedent(f"""
        import sys; sys.path.insert(0, {REPO!r})
        import numpy as np
        import ray_tpu

        client = ray_tpu.init(address="auto")
        assert client.mode == "worker"

        # tasks
        @ray_tpu.remote
        def double(x):
            return x * 2
        assert ray_tpu.get(double.remote(21), timeout=120) == 42

        # objects (big enough for the shm path)
        ref = ray_tpu.put(np.arange(300000, dtype=np.int32))
        assert int(ray_tpu.get(ref, timeout=60).sum()) == \\
            int(np.arange(300000).sum())

        # named actor created by the PRIMARY driver
        h = ray_tpu.get_actor("client_kv")
        assert ray_tpu.get(h.put.remote("x", 7), timeout=60)
        assert ray_tpu.get(h.get.remote("x"), timeout=60) == 7

        # actors created BY the client
        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0
            def inc(self):
                self.n += 1
                return self.n
        c = Counter.remote()
        assert ray_tpu.get(c.inc.remote(), timeout=120) == 1

        # cluster state visible
        assert ray_tpu.cluster_resources().get("CPU", 0) > 0
        ray_tpu.shutdown()      # disconnect; session must survive
        print("CLIENT-OK")
    """)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    assert "CLIENT-OK" in r.stdout

    # the session is still alive and the client's writes persisted
    h = ray_tpu.get_actor("client_kv")
    assert ray_tpu.get(h.get.remote("x"), timeout=60) == 7
    ray_tpu.kill(h)
