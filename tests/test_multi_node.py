"""Multi-node cluster tests: scheduling across daemons, cross-node object
transfer, placement strategies, and node-death recovery.

Counterpart of the reference's `test_multi_node*.py` +
`test_placement_group*.py` over the one-host multi-raylet Cluster fixture
(`python/ray/cluster_utils.py:99`): each "node" is a real HostDaemon
subprocess with its own object store and worker pool, only the resource
shapes are fake.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

BIG = 512 * 1024    # > INLINE_OBJECT_MAX_BYTES: forces the store/data plane


def where():
    return os.environ.get("RAY_TPU_NODE_ID", "head")


@pytest.fixture(scope="module")
def cluster(ray_session):
    c = Cluster.attach()
    c.add_node({"CPU": 2, "red": 2})
    c.add_node({"CPU": 2, "blue": 2})
    yield c
    for nid in list(c.node_ids):
        try:
            c.kill_node(nid)
        except Exception:
            pass
    time.sleep(0.5)


def test_node_registration(cluster):
    nodes = cluster.list_nodes()
    assert sum(1 for n in nodes if n.get("head")) == 1
    # dead nodes from other test modules may linger in the shared
    # session's membership table; check only this fixture's nodes
    mine = [n for n in nodes if n["node_id"] in cluster.node_ids]
    assert len(mine) == 2
    assert all(n["alive"] for n in mine)
    total = ray_tpu.cluster_resources()
    assert total.get("red") == 2.0
    assert total.get("blue") == 2.0


def test_remote_node_execution(cluster):
    @ray_tpu.remote(resources={"red": 1})
    def f(x):
        return where(), x * 2

    node, val = ray_tpu.get(f.remote(21), timeout=60)
    assert val == 42
    assert node == cluster.node_ids[0]


def test_cross_node_object_transfer(cluster):
    """Driver-put array consumed on a node; node-produced array read by the
    driver — both directions of the pull plane."""
    arr = np.arange(BIG, dtype=np.uint8)
    ref = ray_tpu.put(arr)

    @ray_tpu.remote(resources={"red": 1})
    def consume(a):
        return where(), int(a.sum()), np.ones(BIG, np.uint8)

    node, s, ones = ray_tpu.get(consume.remote(ref), timeout=60)
    assert node == cluster.node_ids[0]
    assert s == int(arr.sum())
    assert ones.shape == (BIG,)
    assert int(ones.sum()) == BIG


def test_node_to_node_transfer(cluster):
    """Object produced on red is consumed on blue: peer-to-peer pull."""
    @ray_tpu.remote(resources={"red": 1})
    def produce():
        return np.full(BIG, 7, np.uint8)

    @ray_tpu.remote(resources={"blue": 1})
    def consume(a):
        return where(), int(a[:10].sum())

    ref = produce.remote()
    node, s = ray_tpu.get(consume.remote(ref), timeout=60)
    assert node == cluster.node_ids[1]
    assert s == 70


def test_spillback_when_head_full(cluster):
    """More concurrent CPU=1 tasks than the head has CPUs: the cluster
    scheduler spills the surplus to daemon nodes
    (cluster_task_manager.cc:44 spillback equivalent)."""
    @ray_tpu.remote(num_cpus=1)
    def slow():
        time.sleep(1.0)
        return where()

    n = 8   # head has 4 CPUs, each extra node 2
    hosts = ray_tpu.get([slow.remote() for _ in range(n)], timeout=120)
    assert len(set(hosts)) >= 2, hosts


def test_node_affinity(cluster):
    nid = cluster.node_ids[1]

    @ray_tpu.remote(num_cpus=1)
    def f():
        return where()

    pinned = f.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(node_id=nid))
    assert ray_tpu.get(pinned.remote(), timeout=60) == nid
    head = f.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(node_id="head"))
    assert ray_tpu.get(head.remote(), timeout=60) == "head"


def test_spread_strategy(cluster):
    @ray_tpu.remote(num_cpus=1)
    def f(i):
        time.sleep(0.2)
        return where()

    spread = f.options(scheduling_strategy="SPREAD")
    hosts = ray_tpu.get([spread.remote(i) for i in range(6)], timeout=120)
    assert len(set(hosts)) >= 2, hosts


def test_strict_spread_placement_group(cluster):
    from ray_tpu.util.placement_group import (
        placement_group, remove_placement_group)
    pg = placement_group([{"CPU": 1}] * 3, strategy="STRICT_SPREAD")

    @ray_tpu.remote(num_cpus=1)
    def f():
        time.sleep(0.3)
        return where()

    refs = [f.options(scheduling_strategy=PlacementGroupSchedulingStrategy(placement_group=pg)).remote() for _ in range(3)]
    hosts = ray_tpu.get(refs, timeout=120)
    assert len(set(hosts)) == 3, hosts
    remove_placement_group(pg)


def test_strict_spread_infeasible(cluster):
    from ray_tpu.exceptions import PlacementGroupError
    from ray_tpu.util.placement_group import placement_group
    with pytest.raises(PlacementGroupError):
        placement_group([{"CPU": 1}] * 10, strategy="STRICT_SPREAD")


def test_strict_pack_stays_on_one_node(cluster):
    from ray_tpu.util.placement_group import (
        placement_group, remove_placement_group)
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_PACK")

    @ray_tpu.remote(num_cpus=1)
    def f():
        time.sleep(0.2)
        return where()

    hosts = ray_tpu.get(
        [f.options(scheduling_strategy=PlacementGroupSchedulingStrategy(placement_group=pg)).remote() for _ in range(2)],
        timeout=120)
    assert len(set(hosts)) == 1, hosts
    remove_placement_group(pg)


def test_actor_on_remote_node(cluster):
    @ray_tpu.remote(resources={"blue": 1})
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self, k=1):
            self.n += k
            return self.n

        def host(self):
            return where()

    c = Counter.remote()
    assert ray_tpu.get(c.host.remote(), timeout=60) == cluster.node_ids[1]
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
    assert ray_tpu.get(c.incr.remote(5), timeout=60) == 6
    ray_tpu.kill(c)


def test_named_actor_on_remote_node(cluster):
    @ray_tpu.remote(resources={"red": 1})
    class KV:
        def __init__(self):
            self.d = {}

        def put(self, k, v):
            self.d[k] = v
            return True

        def get(self, k):
            return self.d.get(k)

    KV.options(name="mnkv").remote()
    h = ray_tpu.get_actor("mnkv")
    assert ray_tpu.get(h.put.remote("a", 1), timeout=60)
    assert ray_tpu.get(h.get.remote("a"), timeout=60) == 1
    ray_tpu.kill(h)


def test_nested_submission_from_node_worker(cluster):
    """A task on a daemon submits a subtask (scheduled anywhere) and gets
    its result — the proxied submit/get path."""
    @ray_tpu.remote(num_cpus=1)
    def inner(x):
        return x + 1

    @ray_tpu.remote(resources={"blue": 1})
    def outer():
        ref = inner.remote(41)
        return where(), ray_tpu.get(ref, timeout=60)

    node, val = ray_tpu.get(outer.remote(), timeout=120)
    assert node == cluster.node_ids[1]
    assert val == 42


def _train_loop_report_host(config):
    from ray_tpu.train import session
    rank = session.get_world_rank()
    # metrics_history only carries rank 0's reports (reference behavior),
    # so every rank records its host on the shared filesystem instead
    with open(os.path.join(config["out"], f"rank{rank}.txt"), "w") as f:
        f.write(where())
    session.report({"host": where(), "rank": rank})


def test_trainer_spans_nodes(cluster, tmp_path):
    """JaxTrainer with STRICT_SPREAD places its worker gang on distinct
    nodes and completes the jax.distributed rendezvous across them — the
    multi-host Train path (worker_group.py setup_distributed seam)."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    trainer = JaxTrainer(
        _train_loop_report_host,
        train_loop_config={"out": str(tmp_path)},
        scaling_config=ScalingConfig(
            num_workers=2,
            resources_per_worker={"CPU": 1},
            placement_strategy="STRICT_SPREAD"),
        run_config=RunConfig(name="span", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None, result.error
    hosts = {open(os.path.join(tmp_path, f"rank{r}.txt")).read()
             for r in range(2)}
    assert len(hosts) == 2, hosts


class TestNodeFailure:
    """Chaos: SIGKILL a whole daemon (its workers die with it) and assert
    recovery — the NodeKillerActor pattern (test_utils.py:1400)."""

    def test_task_retry_on_node_death(self, ray_session):
        c = Cluster.attach()
        n1 = c.add_node({"CPU": 2, "green": 2})
        n2 = c.add_node({"CPU": 2, "green": 2})

        @ray_tpu.remote(resources={"green": 1}, max_retries=2)
        def slow_ok():
            time.sleep(3.0)
            return where()

        # occupy n1 first by locality of nothing — both fit; pin attempt 1
        # to n1 via soft affinity so the kill hits the running attempt
        ref = slow_ok.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=n1, soft=True)).remote()
        time.sleep(1.0)     # let it start on n1
        c.kill_node(n1)
        host = ray_tpu.get(ref, timeout=120)
        assert host in (n2, "head")
        c.kill_node(n2)

    def test_object_lost_and_copy_promotion(self, ray_session):
        from ray_tpu.exceptions import ObjectLostError
        c = Cluster.attach()
        n1 = c.add_node({"CPU": 2, "purple": 2})

        @ray_tpu.remote(resources={"purple": 1})
        def produce(tag):
            return np.full(BIG, tag, np.uint8)

        @ray_tpu.remote(resources={"purple": 1})
        def put_obj():
            # ray_tpu.put inside a task: the object lives in the node's
            # store with NO lineage (puts are not reconstructable, as in
            # the reference) — losing the node loses it for good
            return ray_tpu.put(np.full(BIG, 4, np.uint8))

        # (a) object pulled to head before the kill survives via promotion
        survivor = produce.remote(3)
        a = ray_tpu.get(survivor, timeout=60)    # head now caches a copy
        # (b) a put object never pulled is lost with the node
        doomed = ray_tpu.get(put_obj.remote(), timeout=60)
        time.sleep(1.0)  # let it finish sealing on the node
        c.kill_node(n1)
        time.sleep(0.5)
        again = ray_tpu.get(survivor, timeout=60)
        assert int(again[0]) == 3 and np.array_equal(a, again)
        with pytest.raises(ObjectLostError):
            ray_tpu.get(doomed, timeout=10)

    def test_object_reconstruction_on_node_death(self, ray_session):
        """The only copy of a task-produced object dies with its node;
        get() still returns it — lineage resubmission re-executes the
        producing task on a surviving node."""
        c = Cluster.attach()
        n1 = c.add_node({"CPU": 2, "silver": 2})

        @ray_tpu.remote(resources={"silver": 1})
        def produce(tag):
            return np.full(BIG, tag, np.uint8), where()

        ref = produce.remote(9)
        ray_tpu.wait([ref], timeout=60)     # sealed on n1, never pulled
        n2 = c.add_node({"CPU": 2, "silver": 2})
        c.kill_node(n1)
        arr, host = ray_tpu.get(ref, timeout=120)
        assert int(arr[0]) == 9 and arr.shape == (BIG,)
        assert host == n2       # re-executed on the surviving node
        c.kill_node(n2)

    def test_reconstruction_chain_feeds_consumer(self, ray_session):
        """A consumer whose dependency is lost mid-flight gets requeued
        (without burning a retry) and completes once the dep is rebuilt."""
        c = Cluster.attach()
        n1 = c.add_node({"CPU": 2, "iron": 2})

        @ray_tpu.remote(resources={"iron": 1})
        def produce():
            return np.full(BIG, 5, np.uint8)

        @ray_tpu.remote(num_cpus=1)
        def consume(arr):
            return int(arr[0]) + len(arr)

        ref = produce.remote()
        ray_tpu.wait([ref], timeout=60)     # sealed on n1 (only iron node)
        n2 = c.add_node({"CPU": 2, "iron": 2})
        c.kill_node(n1)
        time.sleep(1.0)     # let the head observe the death
        out = ray_tpu.get(consume.remote(ref), timeout=120)
        assert out == 5 + BIG
        c.kill_node(n2)

    def test_hard_affinity_to_dead_node_fails_fast(self, ray_session):
        from ray_tpu.exceptions import SchedulingError
        c = Cluster.attach()
        n1 = c.add_node({"CPU": 1, "pink": 1})
        c.kill_node(n1)
        time.sleep(1.0)

        @ray_tpu.remote(num_cpus=1)
        def f():
            return 1

        ref = f.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=n1)).remote()
        with pytest.raises(SchedulingError):
            ray_tpu.get(ref, timeout=30)

    def test_actor_restart_on_node_death(self, ray_session):
        c = Cluster.attach()
        n1 = c.add_node({"CPU": 2, "orange": 2})

        @ray_tpu.remote(num_cpus=1, max_restarts=1, max_task_retries=1)
        class Svc:
            def host(self):
                return where()

        svc = Svc.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=n1, soft=True)).remote()
        assert ray_tpu.get(svc.host.remote(), timeout=60) == n1
        c.kill_node(n1)
        # restarted incarnation lands wherever resources exist (head);
        # max_task_retries lets a call that raced the death be retried
        host = ray_tpu.get(svc.host.remote(), timeout=120)
        assert host == "head"
