"""PG / SlateQ / SimpleQ / A3C — registry-completing algorithms.

References: `rllib/algorithms/pg/`, `rllib/algorithms/slateq/` (+ its
RecSim interest-evolution validation), `rllib/algorithms/simple_q/`,
`rllib/algorithms/a3c/`.
"""

import jax
import numpy as np
import pytest

from ray_tpu.rllib.algorithms import get_algorithm_class


def test_registry_has_all():
    for name in ("PG", "SlateQ", "SimpleQ", "A3C"):
        assert get_algorithm_class(name) is not None


def test_pg_learns_cartpole():
    """REINFORCE with reward-to-go solves easy CartPole levels — the
    reference's PG learning test is the same bar."""
    from ray_tpu.rllib.algorithms.pg import PGConfig
    algo = (PGConfig().environment("CartPole-v1")
            .rollouts(num_envs_per_worker=16, rollout_fragment_length=128)
            .training(lr=4e-3, model={"fcnet_hiddens": (32,)})
            .debugging(seed=0).build())
    best = 0.0
    for _ in range(40):
        r = algo.train()
        rew = r["episode_reward_mean"]
        if rew == rew:
            best = max(best, rew)
        if best >= 100:
            break
    assert best >= 100, best


def test_slate_env_choice_model():
    """Clicks follow the conditional logit: an aligned slate must click
    (and pay) far more often than an anti-aligned one."""
    from ray_tpu.rllib.algorithms.slateq import SlateDocEnv
    env = SlateDocEnv({"n_docs": 8, "slate_size": 2})
    key = jax.random.PRNGKey(0)
    state, obs = env.reset(key)
    u = np.asarray(state["u"])
    docs = np.asarray(env.docs)
    affin = docs @ u
    best = np.argsort(affin)[-2:].astype(np.int32)
    worst = np.argsort(affin)[:2].astype(np.int32)
    step = jax.jit(env.step)

    def run(slate, n=120):
        s, total = state, 0.0
        k = jax.random.PRNGKey(1)
        for _ in range(n):
            k, kk = jax.random.split(k)
            s, o, r, d, info = step(s, slate, kk)
            total += float(r)
        return total

    assert run(best) > 3 * max(run(worst), 0.5)


def test_slateq_learns_recsys():
    """SlateQ's decomposition learns to recommend interest-aligned
    slates: engagement per episode climbs well above the random-slate
    baseline (reference: slateq validated on RecSim the same way)."""
    from ray_tpu.rllib.algorithms.slateq import SlateQConfig

    algo = (SlateQConfig().environment(
                "SlateDoc", env_config={"n_docs": 10, "slate_size": 3})
            .training(lr=2e-3, n_updates_per_iter=16,
                      learning_starts=512, epsilon_timesteps=8000)
            .rollouts(num_envs_per_worker=32, rollout_fragment_length=16)
            .debugging(seed=0).build())
    # random baseline: epsilon starts at 1.0, so iteration 1 is random
    first = algo.train()
    baseline = first["episode_reward_mean"]
    best = 0.0
    for _ in range(40):
        r = algo.train()
        rew = r["episode_reward_mean"]
        if rew == rew:
            best = max(best, rew)
    assert np.isfinite(r["loss"])
    assert best > max(1.5 * baseline, baseline + 3), (baseline, best)
    # greedy slate for a user aligned with doc 0 contains doc 0
    env = algo.env
    u = np.asarray(env.docs[0])
    obs = np.concatenate([u, np.asarray(env.docs).reshape(-1)])
    slate = algo.compute_slate(obs)
    assert 0 in slate.tolist(), slate


def test_simpleq_learns_cartpole():
    from ray_tpu.rllib.algorithms.simple_q import SimpleQConfig
    algo = (SimpleQConfig().environment("CartPole-v1")
            .training(learning_starts=500, train_batch_size=64,
                      n_updates_per_iter=16,
                      target_network_update_freq=200,
                      model={"fcnet_hiddens": (32, 32)})
            .debugging(seed=0).build())
    assert algo.algo_config.double_q is False
    assert algo.algo_config.prioritized_replay is False
    best = 0.0
    for _ in range(40):
        r = algo.train()
        rew = r["episode_reward_mean"]
        if rew == rew:
            best = max(best, rew)
        if best >= 80:
            break
    assert best >= 80, best


def test_a3c_runs_async_workers(ray_session):
    from ray_tpu.rllib.algorithms.simple_q import A3CConfig
    algo = (A3CConfig().environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=8,
                      rollout_fragment_length=32)
            .debugging(seed=0).build())
    try:
        assert algo.workers is not None      # async actor path active
        r = algo.train()
        assert np.isfinite(r.get("policy_loss", 0.0))
    finally:
        algo.cleanup()
