"""Native shared-memory arena store (ray_tpu/_private/native/store.cc).

Counterpart of the reference's plasma tests
(src/ray/object_manager/plasma/test/, python/ray/tests/test_object_store.py):
create/seal visibility, zero-copy reads, delete/coalescing, LRU eviction of
unpinned objects, pin protection, cross-process sharing, and the
ObjectStore integration (arena-backed descriptors end to end).
"""

import multiprocessing as mp
import os
import sys

import numpy as np
import pytest

from ray_tpu._private.native.arena import Arena
from ray_tpu._private.object_store import ObjectStore


@pytest.fixture
def arena(tmp_path):
    a = Arena.open(str(tmp_path), capacity=4 * 1024 * 1024)
    if a is None:
        pytest.skip("native toolchain unavailable")
    yield a
    a.close()


def test_create_seal_lookup(arena):
    buf = arena.create("obj_a", 100)
    assert buf is not None and len(buf) == 100
    buf[:3] = b"xyz"
    # invisible until sealed (plasma create->seal contract)
    assert arena.lookup("obj_a") is None
    assert not arena.contains("obj_a")
    assert arena.seal("obj_a")
    view = arena.lookup("obj_a")
    assert bytes(view[:3]) == b"xyz"
    assert view.readonly
    assert arena.contains("obj_a")


def test_duplicate_create_rejected(arena):
    assert arena.create("obj_d", 10) is not None
    assert arena.create("obj_d", 10) is None


def test_delete_frees_and_coalesces(arena):
    used0 = arena.stats()["used"]
    for i in range(8):
        arena.create(f"obj_{i}", 50_000)
        arena.seal(f"obj_{i}")
    for i in range(8):
        assert arena.delete(f"obj_{i}")
    assert arena.stats()["used"] == used0
    # freed space is reusable as one large block (coalescing)
    assert arena.create("obj_big", 350_000) is not None


def test_lru_eviction_unpinned_only(arena):
    cap = arena.stats()["capacity"]
    n = 0
    while True:
        buf = arena.create(f"obj_e{n}", 100_000)
        if buf is None:
            break
        arena.seal(f"obj_e{n}")
        n += 1
        if n > 200:
            break
    st = arena.stats()
    assert st["num_evictions"] > 0          # old ones were evicted to fit
    assert not arena.contains("obj_e0")     # LRU victim
    assert st["used"] <= cap


def test_pin_blocks_eviction(arena):
    arena.create("obj_pinned", 100_000)
    arena.seal("obj_pinned")
    assert arena.pin("obj_pinned", 1) == 1
    for i in range(100):
        if arena.create(f"obj_f{i}", 100_000) is None:
            break
        arena.seal(f"obj_f{i}")
    assert arena.contains("obj_pinned")
    assert arena.pin("obj_pinned", -1) == 0


def test_acquire_protects_live_views_from_delete(arena):
    buf = arena.create("obj_live", 50_000)
    buf[:4] = b"data"
    arena.seal("obj_live")
    view = arena.acquire("obj_live")          # reader pin
    assert arena.delete("obj_live")           # condemned, not freed
    # object invisible to new readers
    assert arena.lookup("obj_live") is None
    assert not arena.contains("obj_live")
    # but the pinned view's bytes must still be intact after new allocations
    for i in range(10):
        w = arena.create(f"obj_churn{i}", 50_000)
        if w is None:
            break
        w[:4] = b"XXXX"
        arena.seal(f"obj_churn{i}")
    assert bytes(view[:4]) == b"data"


def test_condemned_block_freed_on_release(arena):
    arena.create("obj_rel", 60_000)
    arena.seal("obj_rel")
    arena.pin("obj_rel", 1)                   # owner pin (put() path)
    view = arena.acquire("obj_rel")           # reader pin -> refcnt 2
    used_full = arena.stats()["used"]
    assert arena.pin("obj_rel", -1) == 1      # owner releases (delete path)
    assert arena.delete("obj_rel")            # reader remains -> condemned
    assert arena.stats()["used"] == used_full  # still allocated (reader)
    view.release()
    assert arena.pin("obj_rel", -1) == 0      # reader releases -> freed
    assert arena.stats()["used"] < used_full


def test_create_failure_cleanup_path(tmp_path):
    """put() must reclaim the reservation if serialization fails midway."""
    store = ObjectStore(str(tmp_path))
    if store._arena is None:
        pytest.skip("native toolchain unavailable")

    class Evil:
        def __reduce__(self):
            raise RuntimeError("unpicklable")

    used0 = store._arena.stats()["used"]
    big = np.zeros(200_000, dtype=np.uint8)
    with pytest.raises(Exception):
        store.put("obj_evil", [big, Evil()])
    assert store._arena.stats()["used"] == used0
    store.close()


def test_payload_cacheline_alignment(arena):
    """Zero-copy numpy views get 64-byte-aligned buffers."""
    import ctypes
    for name, size in (("obj_al1", 100), ("obj_al2", 70_000)):
        buf = arena.create(name, size)
        addr = ctypes.addressof(ctypes.c_char.from_buffer(buf))
        assert addr % 64 == 0
        arena.seal(name)


def _crash_child_pins(session_dir):
    """Simulate a worker that pins objects then dies without releasing:
    owner pin on its own put, reader pin on another object, plus an
    unsealed create (crash mid-put)."""
    a = Arena.open(session_dir)
    a.create("obj_mine", 50_000)
    a.pin("obj_mine", 1)         # put-time owner pin
    a.seal("obj_mine")
    a.acquire("obj_theirs")      # reader pin
    a.create("obj_unsealed", 50_000)   # crash before seal
    os._exit(1)                  # no cleanup — hard crash


def test_release_all_reclaims_dead_process_pins(tmp_path):
    """A crashed client's pins are force-released (plasma disconnected-
    client analog): condemned blocks free, unsealed creations reclaim."""
    a = Arena.open(str(tmp_path), capacity=4 * 1024 * 1024)
    if a is None:
        pytest.skip("native toolchain unavailable")
    used0 = a.stats()["used"]
    buf = a.create("obj_theirs", 50_000)
    a.seal("obj_theirs")

    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_crash_child_pins, args=(str(tmp_path),))
    p.start()
    p.join(60)                   # child pins, then hard-exits
    child_pid = p.pid

    # Without reclamation both deletes would condemn forever.
    a.delete("obj_theirs")       # child reader pin -> condemned
    a.delete("obj_mine")         # child owner pin -> condemned
    assert a.stats()["used"] > used0
    touched = a.release_all(child_pid)
    assert touched >= 3          # reader pin + owner pin + unsealed create
    assert a.stats()["used"] == used0
    a.close()


def _xproc_child(session_dir, q):
    a = Arena.open(session_dir)
    v = a.lookup("obj_shared")
    q.put(bytes(v[:6]) if v is not None else None)
    a.close()


def test_cross_process_visibility(tmp_path):
    a = Arena.open(str(tmp_path), capacity=2 * 1024 * 1024)
    if a is None:
        pytest.skip("native toolchain unavailable")
    buf = a.create("obj_shared", 150_000)
    buf[:6] = b"shared"
    a.seal("obj_shared")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_xproc_child, args=(str(tmp_path), q))
    p.start()
    assert q.get(timeout=60) == b"shared"
    p.join(60)
    a.close()


def test_object_store_arena_roundtrip(tmp_path):
    store = ObjectStore(str(tmp_path))
    if store._arena is None:
        pytest.skip("native toolchain unavailable")
    arr = np.arange(200_000, dtype=np.float32)   # > inline threshold
    desc = store.put("obj_np", arr)
    assert desc.arena and desc.path is None
    out = store.get(desc)
    np.testing.assert_array_equal(out, arr)
    # zero-copy: result is read-only (backed by the shm mapping)
    assert not out.flags.writeable
    payload = store.raw_bytes(desc)
    desc2 = store.put_serialized("obj_np2", payload)
    np.testing.assert_array_equal(store.get(desc2), arr)
    store.delete(desc)
    store.close()


def test_zero_copy_view_survives_delete_and_reuse(tmp_path):
    """Freeing an object while a deserialized zero-copy array still
    borrows its bytes must NOT let the allocator reuse them: the free
    path probes the per-object mmap for live exports and condemns the
    block instead (the bug this guards against surfaced as replay
    batches whose int columns held float bit patterns)."""
    store = ObjectStore(str(tmp_path))
    if store._arena is None:
        pytest.skip("native toolchain unavailable")
    arr = np.arange(100_000, dtype=np.int32)
    desc = store.put("victim", arr)
    assert desc.arena
    out = store.get(desc)            # zero-copy borrower
    store.delete(desc)               # freed while borrowed
    # hammer the allocator: without the borrow probe these allocations
    # reuse the victim's block and corrupt `out`
    descs = []
    for i in range(20):
        d = store.put(f"churn{i}", np.full(100_000, i, np.float32))
        descs.append(d)
    np.testing.assert_array_equal(out, arr)
    # once the borrower dies, a later store operation reclaims the block
    del out
    import gc
    gc.collect()
    for d in descs:
        store.delete(d)
    store._sweep_condemned()
    assert not store._condemned
    store.close()


def test_object_store_file_fallback_when_arena_full(tmp_path):
    os.environ["RAY_TPU_OBJECT_STORE_BYTES"] = "1048576"
    try:
        store = ObjectStore(str(tmp_path))
        if store._arena is None:
            pytest.skip("native toolchain unavailable")
        # bigger than the whole arena -> file-backed, still readable
        arr = np.arange(1_000_000, dtype=np.float64)
        desc = store.put("obj_huge", arr)
        assert not desc.arena and desc.path is not None
        np.testing.assert_array_equal(store.get(desc), arr)
        store.close()
    finally:
        del os.environ["RAY_TPU_OBJECT_STORE_BYTES"]
