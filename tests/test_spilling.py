"""Object spilling, memory pressure, and Data byte-budget backpressure.

Counterpart of the reference's `test_object_spilling.py` +
`test_memory_pressure.py` suites: arena overflow and proactive high-water
spilling land objects on real disk (bounded shm), the memory monitor kills
a retriable worker instead of letting the OS OOM, and the Data executor's
byte budget caps in-flight bytes.
"""

import os
import subprocess
import sys
import textwrap
import time

import pytest

import ray_tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script: str, env_extra: dict) -> str:
    env = dict(os.environ)
    env.update(env_extra)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    return r.stdout


def test_arena_overflow_and_proactive_spill(tmp_path):
    """With a 4 MiB arena: overflow puts land on disk, and a spill pass
    drains the arena below the low-water mark while every value stays
    readable; shutdown removes the spill dir."""
    script = textwrap.dedent(f"""
        import sys; sys.path.insert(0, {REPO!r})
        import glob, os
        import numpy as np
        import ray_tpu
        from ray_tpu._private.worker import get_client

        ray_tpu.init(num_cpus=2)
        node = get_client().node
        refs = [ray_tpu.put(np.full(1_000_000, i, np.uint8))
                for i in range(12)]
        node._maybe_spill()
        st = node.store.arena_stats()
        if st is not None:
            assert st["used"] <= 0.5 * st["capacity"] + 1_100_000, st
        spilled = glob.glob(os.path.join(node.store._spill_dir, "obj_*"))
        assert spilled, "expected spill files on disk"
        # tmpfs per-object fallback must stay unused (bounded shm)
        assert not os.listdir(node.store._dir)
        for i, r in enumerate(refs):
            a = ray_tpu.get(r)
            assert int(a[0]) == i and len(a) == 1_000_000
        spill_dir = node.store._spill_dir
        ray_tpu.shutdown()
        assert not os.path.exists(spill_dir)
        print("SPILL-OK")
    """)
    out = _run(script, {
        "RAY_TPU_OBJECT_STORE_BYTES": str(4 * 1024 * 1024),
        "RAY_TPU_OBJECT_SPILL_ROOT": str(tmp_path),
        "RAY_TPU_SPILL_HIGH_WATER": "0.5",
        "RAY_TPU_SPILL_LOW_WATER": "0.2",
    })
    assert "SPILL-OK" in out


def test_data_pipeline_4x_arena_completes(tmp_path):
    """A Data pipeline whose working set is ~4x the arena finishes with
    bounded shm usage (the VERDICT churn criterion): blocks overflow to
    the disk spill dir, never to tmpfs fallback files."""
    script = textwrap.dedent(f"""
        import sys; sys.path.insert(0, {REPO!r})
        import os
        import numpy as np
        import ray_tpu
        from ray_tpu import data as rtd
        from ray_tpu._private.worker import get_client

        ray_tpu.init(num_cpus=2)
        node = get_client().node

        def blow_up(row):
            return {{"z": np.full(1_000_000, row["item"], np.uint8)}}

        ds = rtd.from_items(list(range(16)), parallelism=16).map(blow_up)
        total = 0
        for row in ds.iter_rows():
            total += int(row["z"][0])
        assert total == sum(range(16)), total
        assert not os.listdir(node.store._dir)   # no tmpfs overflow
        ray_tpu.shutdown()
        print("CHURN-OK")
    """)
    out = _run(script, {
        "RAY_TPU_OBJECT_STORE_BYTES": str(4 * 1024 * 1024),
        "RAY_TPU_OBJECT_SPILL_ROOT": str(tmp_path),
    })
    assert "CHURN-OK" in out


def test_memory_monitor_kills_and_task_retries(ray_session):
    """Forced memory pressure kills the newest retriable worker; the task
    retries and completes (worker_killing_policy_retriable_fifo.h)."""
    from ray_tpu._private.memory_monitor import MemoryMonitor
    from ray_tpu._private.worker import get_client

    node = get_client().node

    @ray_tpu.remote(max_retries=2, num_cpus=1)
    def sleepy():
        time.sleep(3.0)
        return "done"

    ref = sleepy.remote()
    deadline = time.time() + 30
    mon = MemoryMonitor(node, threshold=0.5, usage_fn=lambda: 0.99)
    while time.time() < deadline:
        if mon.tick():
            break
        time.sleep(0.2)
    else:
        pytest.fail("monitor never found a busy worker to kill")
    assert mon.kills == 1
    assert ray_tpu.get(ref, timeout=120) == "done"


def test_memory_monitor_noop_below_threshold(ray_session):
    from ray_tpu._private.memory_monitor import MemoryMonitor
    from ray_tpu._private.worker import get_client

    mon = MemoryMonitor(get_client().node, threshold=0.9,
                        usage_fn=lambda: 0.1)
    assert not mon.tick()
    assert mon.kills == 0


def test_data_byte_budget_correctness(ray_session):
    """A 1-byte in-flight budget degrades to serial execution but keeps
    results correct and ordered."""
    from ray_tpu import data as rtd
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    old = ctx.max_bytes_in_flight
    ctx.max_bytes_in_flight = 1
    try:
        ds = rtd.from_items(list(range(8))).map(
            lambda r: {"v": r["item"] * 2})
        vals = [r["v"] for r in ds.iter_rows()]
        assert vals == [i * 2 for i in range(8)]
    finally:
        ctx.max_bytes_in_flight = old


def test_inflight_budget_math():
    from ray_tpu.data._internal.execution import _InFlightBudget
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    b = _InFlightBudget(ctx, max_tasks=4)
    b.max_bytes = 100
    assert b.admit(60)          # empty window always admits
    b.add(60)
    assert b.admit(40)
    b.add(40)
    assert not b.admit(1)       # byte-capped
    b.remove(60)
    assert b.admit(10)
    b.add(10)
    b.add(10)
    b.add(10)                   # 4 tasks now
    assert not b.admit(1)       # slot-capped
