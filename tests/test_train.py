"""JaxTrainer tests (reference: `train/tests/test_data_parallel_trainer.py`,
`test_backend_executor.py` coverage shapes)."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import (
    Checkpoint,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


def _simple_loop(config):
    from ray_tpu.train import session
    for i in range(config["iters"]):
        session.report({"iter": i, "loss": 1.0 / (i + 1),
                        "rank": session.get_world_rank(),
                        "world": session.get_world_size()})


def test_single_worker_metrics(ray_session, tmp_path):
    trainer = JaxTrainer(
        _simple_loop,
        train_loop_config={"iters": 3},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t1", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert len(result.metrics_history) == 3
    assert result.metrics["loss"] == pytest.approx(1 / 3)
    assert result.metrics["world"] == 1


def _ckpt_loop(config):
    from ray_tpu.train import Checkpoint, session
    start = 0
    ck = session.get_checkpoint()
    if ck is not None:
        start = ck.to_dict()["step"] + 1
    for i in range(start, config["iters"]):
        if config.get("crash_at") == i and not os.path.exists(
                config["marker"]):
            open(config["marker"], "w").close()
            os._exit(1)
        session.report(
            {"step": i},
            checkpoint=Checkpoint.from_dict(
                {"step": i, "weights": {"w": np.ones(4) * i}}))


def test_checkpoint_and_restore_after_crash(ray_session, tmp_path):
    marker = str(tmp_path / "crashed")
    trainer = JaxTrainer(
        _ckpt_loop,
        train_loop_config={"iters": 4, "crash_at": 2, "marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="t2", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1)))
    result = trainer.fit()
    assert result.error is None, result.error
    assert os.path.exists(marker)          # it really crashed once
    final = result.checkpoint.to_dict()
    assert final["step"] == 3
    np.testing.assert_allclose(final["weights"]["w"], np.ones(4) * 3)
    # steps: 0,1 (first attempt) then resume from ckpt step=1 -> 2,3
    steps = [m["step"] for m in result.metrics_history]
    assert steps[-1] == 3


def test_failure_exhausted_returns_error(ray_session, tmp_path):
    def always_fails(config):
        raise RuntimeError("nope")

    trainer = JaxTrainer(
        always_fails,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t3", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is not None and "nope" in result.error


def _dp_loop(config):
    """Real 2-process DP: jax.distributed is initialized by the trainer;
    both workers build one global mesh and psum-average gradients."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu.parallel import MeshSpec, global_from_local, replicate_tree
    from ray_tpu.train import session

    # 2 processes; each contributes its local devices (8 virtual CPU devs
    # inherited from the test env) to one global mesh.
    assert jax.process_count() == 2, jax.process_count()
    mesh = MeshSpec(data=-1).build()
    rank = session.get_world_rank()

    params = replicate_tree(mesh, {"w": np.zeros(3, np.float32)})
    target = np.array([1.0, 2.0, 3.0], np.float32)

    @jax.jit
    def step(p, batch):
        def loss_fn(p):
            pred = batch["x"] * p["w"]
            return jnp.mean((pred - batch["y"]) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(p)
        return loss, jax.tree.map(lambda w, gw: w - 0.1 * gw, p, g)

    rng = np.random.default_rng(rank)
    for i in range(150):
        x = rng.standard_normal((8, 3)).astype(np.float32)
        batch = global_from_local(mesh, {"x": x, "y": x * target})
        loss, params = step(params, batch)
        session.report({"loss": float(loss), "iter": i})
    w = np.asarray(jax.device_get(params["w"]))
    session.report({"final_w": w.tolist(), "loss": float(loss)})


@pytest.mark.slow
def test_two_worker_dp_converges(ray_session, tmp_path):
    trainer = JaxTrainer(
        _dp_loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="dp", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None, result.error
    final_w = result.metrics["final_w"]
    np.testing.assert_allclose(final_w, [1.0, 2.0, 3.0], atol=0.05)



# ---------------------------------------------------------------------------
# framework trainers beyond JAX (reference: train/torch/torch_trainer.py
# over gloo rendezvous; train/sklearn/sklearn_trainer.py)
# ---------------------------------------------------------------------------

def _torch_ddp_loop(config):
    import numpy as np
    import torch
    import torch.distributed as dist
    from ray_tpu.train import Checkpoint, session
    from ray_tpu.train.torch_trainer import prepare_model

    torch.manual_seed(0)                      # same init on every rank
    model = prepare_model(torch.nn.Linear(4, 1))
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    rank = session.get_world_rank()
    # rank-DIFFERENT data: only DDP gradient averaging can keep the
    # ranks' parameters identical afterwards
    x = torch.full((8, 4), float(rank + 1))
    y = torch.full((8, 1), float(rank))
    for _ in range(3):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
    params = torch.cat([p.detach().reshape(-1)
                        for p in model.parameters()])
    # the REAL DDP assertion, made inside the group: ranks trained on
    # different data, so identical parameters prove gradient averaging
    # actually ran (an unwrapped model would diverge here and fail the
    # whole fit)
    gathered = [torch.zeros_like(params)
                for _ in range(dist.get_world_size())]
    dist.all_gather(gathered, params)
    for other in gathered[1:]:
        assert torch.allclose(gathered[0], other, atol=1e-6), \
            "DDP ranks diverged: gradient sync did not happen"
    # prepare_data_loader derives shuffling from the ORIGINAL sampler
    # (reference: train_loop_utils.py:408-410): a sequential eval loader
    # must stay in-order after sharding; a shuffle=True loader keeps
    # shuffling. Regression for the silent shuffle=True default.
    from torch.utils.data import DataLoader, TensorDataset
    from ray_tpu.train.torch_trainer import prepare_data_loader
    seq_ds = TensorDataset(torch.arange(16, dtype=torch.float32))
    seq = prepare_data_loader(DataLoader(seq_ds, batch_size=2))
    assert seq.sampler.shuffle is False
    order = torch.cat([b[0] for b in seq])
    assert torch.equal(order, order.sort().values), \
        "sequential loader was silently shuffled by prepare_data_loader"
    rnd = prepare_data_loader(
        DataLoader(seq_ds, batch_size=2, shuffle=True))
    assert rnd.sampler.shuffle is True
    session.report({
        "rank": rank,
        "world": dist.get_world_size(),
        "param_sum": float(params.sum()),
        "loss": float(loss),
    }, checkpoint=Checkpoint.from_dict(
        {"weights": params.numpy().copy()}))


@pytest.mark.slow
def test_torch_trainer_ddp_gloo(ray_session, tmp_path):
    from ray_tpu.train import TorchTrainer

    trainer = TorchTrainer(
        _torch_ddp_loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="torch_ddp",
                             storage_path=str(tmp_path)))
    result = trainer.fit()
    # the in-loop all_gather allclose assertion (gradient sync across
    # rank-different data) would surface here as an error
    assert result.error is None, result.error
    head = result.metrics
    assert head["world"] == 2
    assert np.isfinite(head["loss"])
    # checkpointed weights correspond to the reported summary
    ck = result.checkpoint.to_dict()
    assert np.isfinite(ck["weights"]).all()
    assert float(ck["weights"].sum()) == pytest.approx(
        head["param_sum"], abs=1e-5)


def test_sklearn_trainer(ray_session):
    from sklearn.linear_model import LogisticRegression

    from ray_tpu import data as rtd
    from ray_tpu.train import SklearnTrainer

    rng = np.random.default_rng(0)
    rows = [{"a": float(x), "b": float(2 * x + rng.normal(0, .1)),
             "label": int(x > 0)} for x in rng.normal(0, 1, 200)]
    ds = rtd.from_items(rows)
    trainer = SklearnTrainer(
        LogisticRegression(), label_column="label",
        datasets={"train": ds})
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["train_score"] > 0.9
    est = result.checkpoint.to_dict()["estimator"]
    assert est.predict([[3.0, 6.0]])[0] == 1


def _tf_mwms_loop(config):
    """MultiWorkerMirroredStrategy over the TF_CONFIG rendezvous:
    rank-DIFFERENT data, identical post-sync variables prove the
    cross-replica gradient reduction ran (the TF analogue of the torch
    DDP assertion above)."""
    import json
    import os

    import numpy as np
    import tensorflow as tf

    from ray_tpu.train import Checkpoint, session

    tf_config = json.loads(os.environ["TF_CONFIG"])
    assert tf_config["task"]["index"] == session.get_world_rank()
    strategy = tf.distribute.MultiWorkerMirroredStrategy()
    rank = session.get_world_rank()
    with strategy.scope():
        v = tf.Variable(tf.zeros((4,)))
        opt = tf.keras.optimizers.SGD(0.1)

    x = tf.fill((4,), float(rank + 1))     # rank-different data

    @tf.function
    def step():
        def fn():
            with tf.GradientTape() as tape:
                loss = tf.reduce_sum((v - x) ** 2)
            grads = tape.gradient(loss, [v])
            opt.apply_gradients(zip(grads, [v]))
            return loss
        return strategy.run(fn)

    for _ in range(3):
        loss = step()
    out = v.numpy()
    # grads were averaged across ranks: every rank converges toward the
    # MEAN of the rank-specific targets, with identical variables
    session.report({
        "rank": rank,
        "world": session.get_world_size(),
        "v_sum": float(out.sum()),
    }, checkpoint=Checkpoint.from_dict({"v": out.copy()}))


@pytest.mark.slow
def test_tensorflow_trainer_mwms(ray_session, tmp_path):
    from ray_tpu.train import TensorflowTrainer

    trainer = TensorflowTrainer(
        _tf_mwms_loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="tf_mwms",
                             storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["world"] == 2
    ck = result.checkpoint.to_dict()
    # both ranks pulled toward mean(1, 2) = 1.5 per element; identical
    # variables across ranks would differ without the all-reduce
    assert abs(result.metrics["v_sum"] / 4 - ck["v"].mean()) < 1e-5
    assert 0.5 < ck["v"].mean() <= 1.5
