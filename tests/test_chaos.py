"""Chaos suite: kill real worker processes mid-workload, assert recovery.

Counterpart of the reference's chaos strategy (SURVEY.md §4: 'chaos =
kill the real process, not a mock' — `NodeKillerActor`
`_private/test_utils.py:1400`, `test_failure*.py`, release chaos tests):
a killer thread SIGKILLs random busy workers while a workload runs and
the assertions are about end-to-end results, not internal state.
"""

import json
import os
import random
import shutil
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def cluster(ray_session):
    return ray_session


def _node():
    return ray_tpu._worker.get_client().node


class WorkerKiller(threading.Thread):
    """Kills up to `max_kills` busy (non-actor) workers at `period`."""

    def __init__(self, period=0.4, max_kills=3, kind="generic"):
        super().__init__(daemon=True)
        self.period = period
        self.max_kills = max_kills
        self.kind = kind
        self.kills = 0
        self._halt = threading.Event()

    def run(self):
        node = _node()
        while not self._halt.is_set() and self.kills < self.max_kills:
            time.sleep(self.period)
            with node.lock:
                victims = [w for w in node.workers.values()
                           if w.alive and w.kind == self.kind
                           and w.current is not None
                           and getattr(w.proc, "pid", None)]
            if not victims:
                continue
            w = random.choice(victims)
            try:
                os.kill(w.proc.pid, signal.SIGKILL)
                self.kills += 1
            except OSError:
                pass

    def stop(self):
        self._halt.set()


def test_tasks_survive_worker_kills(cluster):
    """Retryable tasks complete correctly despite SIGKILLed workers."""
    @ray_tpu.remote(max_retries=4)
    def chunk_sum(i):
        time.sleep(0.3)
        return float(np.full(50_000, i, np.float64).sum())

    killer = WorkerKiller(period=0.35, max_kills=3)
    killer.start()
    try:
        refs = [chunk_sum.remote(i) for i in range(24)]
        out = ray_tpu.get(refs, timeout=300)
    finally:
        killer.stop()
        killer.join(5)
    assert out == [float(i * 50_000) for i in range(24)]
    assert killer.kills > 0, "chaos never fired; test proved nothing"


def test_no_retry_task_fails_cleanly_on_kill(cluster):
    """max_retries=0: a killed worker surfaces WorkerCrashedError, and the
    cluster stays usable afterwards."""
    @ray_tpu.remote(max_retries=0)
    def sitting_duck():
        time.sleep(30)
        return 1

    ref = sitting_duck.remote()
    node = _node()
    deadline = time.time() + 60
    pid = None
    while time.time() < deadline and pid is None:
        with node.lock:
            for w in node.workers.values():
                if (w.alive and w.current is not None
                        and w.current.spec.task_id is not None
                        and "sitting_duck" in w.current.spec.function_desc
                        and getattr(w.proc, "pid", None)):
                    pid = w.proc.pid
        time.sleep(0.05)
    assert pid is not None
    os.kill(pid, signal.SIGKILL)
    with pytest.raises(ray_tpu.exceptions.WorkerCrashedError):
        ray_tpu.get(ref, timeout=60)

    @ray_tpu.remote
    def ok():
        return 42
    assert ray_tpu.get(ok.remote(), timeout=60) == 42


def test_actor_restart_under_kill(cluster):
    """max_restarts actors come back; max_task_retries replays the call."""
    @ray_tpu.remote(max_restarts=2, max_task_retries=2)
    class Survivor:
        def __init__(self):
            self.calls = 0

        def work(self, x):
            self.calls += 1
            time.sleep(0.2)
            return x * 2

        def pid(self):
            return os.getpid()

    a = Survivor.remote()
    assert ray_tpu.get(a.work.remote(1), timeout=60) == 2
    pid1 = ray_tpu.get(a.pid.remote(), timeout=60)
    os.kill(pid1, signal.SIGKILL)
    # next call may replay through the restart
    assert ray_tpu.get(a.work.remote(21), timeout=120) == 42
    pid2 = ray_tpu.get(a.pid.remote(), timeout=60)
    assert pid2 != pid1
    ray_tpu.kill(a)


def test_pipeline_with_dependencies_survives_kills(cluster):
    """A dependency chain (each stage consumes the previous stage's object)
    completes under chaos — exercises retry + object re-registration."""
    @ray_tpu.remote(max_retries=4)
    def start():
        time.sleep(0.2)
        return np.ones(80_000, np.float32)

    @ray_tpu.remote(max_retries=4)
    def bump(arr):
        time.sleep(0.2)
        return arr + 1.0

    killer = WorkerKiller(period=0.3, max_kills=3)
    killer.start()
    try:
        ref = start.remote()
        for _ in range(6):
            ref = bump.remote(ref)
        out = ray_tpu.get(ref, timeout=300)
    finally:
        killer.stop()
        killer.join(5)
    assert float(out[0]) == 7.0


def test_serve_replicas_recover_from_kill(cluster):
    """Killing a serve replica's process: the controller restarts it and
    the handle keeps serving."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, x):
            return ("pid", os.getpid(), x)

    h = serve.run(Echo.bind(), name="chaos_app")
    try:
        _, pid, _ = h.call(0)
        os.kill(pid, signal.SIGKILL)
        deadline = time.time() + 120
        served_new_pid = False
        while time.time() < deadline:
            try:
                _, p, v = h.call(7, timeout=30)
            except Exception:
                time.sleep(0.2)
                continue
            if v == 7 and p != pid:
                served_new_pid = True
                break
            time.sleep(0.1)
        assert served_new_pid, "no healthy replica took over"
    finally:
        serve.shutdown()


def test_dead_worker_arena_pins_reclaimed(cluster):
    """A SIGKILLed actor's shared-arena pins (put-time owner pins) are
    force-released; objects still referenced by the driver survive via
    pin adoption, and dropping the last ref frees the space."""
    node = _node()
    store = node.store
    if store._arena is None:
        pytest.skip("native arena unavailable")

    @ray_tpu.remote
    class Producer:
        def make(self, n):
            return np.zeros(n, dtype=np.uint8)

        def pid(self):
            return os.getpid()

    a = Producer.remote()
    used0 = store._arena.stats()["used"]
    ref = a.make.remote(2_000_000)          # arena-backed (beyond inline)
    arr = ray_tpu.get(ref, timeout=60)
    pid = ray_tpu.get(a.pid.remote(), timeout=60)
    os.kill(pid, signal.SIGKILL)

    # wait until the node notices the death and reclaims the dead pid's pins
    deadline = time.time() + 60
    while time.time() < deadline:
        with node.lock:
            dead = not any(
                w.alive and getattr(w.proc, "pid", None) == pid
                for w in node.workers.values())
        if dead:
            break
        time.sleep(0.1)
    assert dead

    # the object survives the producer's death (driver adopted the pin)
    arr2 = ray_tpu.get(ref, timeout=60)
    assert arr2.shape == (2_000_000,)

    # dropping every reference frees the arena space even though the
    # origin worker can never deliver its FreeObject release
    del arr, arr2, ref
    import gc
    deadline = time.time() + 60
    while time.time() < deadline:
        gc.collect()
        ray_tpu._worker._drain_decs()
        if store._arena.stats()["used"] <= used0:
            break
        time.sleep(0.2)
    assert store._arena.stats()["used"] <= used0

# ---------------------------------------------------------------------------
# Elastic fault-tolerant training (ROADMAP item 4): SIGKILL a REAL trainer
# process mid-run, resume from the last committed checkpoint — at the same
# device count (bitwise trajectory match) or a smaller one (elastic).
# Trainers run as subprocesses (tests/ft_train_child.py) so the kill takes
# out the whole process, writer thread included, and so the resumed run can
# pick its own device count.
# ---------------------------------------------------------------------------

_CHILD = os.path.join(os.path.dirname(__file__), "ft_train_child.py")


def _run_child(env_over, timeout=420):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)          # the child pins its own devices
    env.update({k: str(v) for k, v in env_over.items()})
    return subprocess.run([sys.executable, _CHILD], env=env,
                          capture_output=True, text=True, timeout=timeout)


@pytest.fixture(scope="module")
def killed_run(tmp_path_factory):
    """(checkpoint_root, control_record, restored_step): a full control
    trajectory plus a trainer hard-killed mid-run with >= 1 committed
    checkpoint left behind."""
    from ray_tpu.train import ft

    base = tmp_path_factory.mktemp("ft_chaos")
    root = str(base / "ckpts")
    ctl_out = str(base / "control.json")

    # Control run: NO checkpointer. The bitwise comparison below then also
    # proves async snapshotting never perturbs the trajectory.
    r = _run_child({"FT_ROOT": str(base / "unused"), "FT_OUT": ctl_out,
                    "FT_STEPS": 12, "FT_EVERY": 0})
    assert r.returncode == 0, r.stderr[-2000:]
    with open(ctl_out) as f:
        control = json.load(f)
    assert control["steps"] == list(range(1, 13))

    # Victim run: checkpoints every 3 steps, SIGKILLs itself once the host
    # feed reaches batch 8 and at least one commit exists.
    r = _run_child({"FT_ROOT": root, "FT_STEPS": 12, "FT_EVERY": 3,
                    "FT_CRASH_AT": 8})
    assert r.returncode == -signal.SIGKILL, \
        f"rc={r.returncode}\n{r.stderr[-2000:]}"

    # Partial/temp dirs never shadow the committed checkpoint.
    os.makedirs(os.path.join(root, "step_00000099"))       # no manifest
    os.makedirs(os.path.join(root, ".step_00000098.tmp-1-abcdef"))
    latest = ft.latest_checkpoint(root)
    assert latest is not None, "kill left no committed checkpoint"
    step = ft.validate_checkpoint(latest)["step"]
    assert 0 < step < 12, step
    return root, control, step


def _resume(killed_run, tmp_path, **env):
    """Resume from a private copy of the crashed root (so each test sees
    the original post-kill state) and return the result record."""
    root, control, step = killed_run
    my_root = str(tmp_path / "ckpts")
    shutil.copytree(root, my_root)
    out = str(tmp_path / "resume.json")
    r = _run_child({"FT_ROOT": my_root, "FT_OUT": out, "FT_MODE": "resume",
                    "FT_STEPS": 12, "FT_EVERY": 3, **env})
    assert r.returncode == 0, r.stderr[-2000:]
    with open(out) as f:
        res = json.load(f)
    assert res["start"] == step
    assert res["steps"] == list(range(step + 1, 13))
    return control, step, res


def test_trainer_kill_resume_bitwise(killed_run, tmp_path):
    """Same device count: the resumed loss trajectory is BIT-IDENTICAL to
    the unkilled control from the restored step onward (JSON float
    round-trips are exact, so list equality is bitwise equality)."""
    control, step, res = _resume(killed_run, tmp_path)
    assert res["losses"] == control["losses"][step:]


def test_trainer_kill_elastic_resume_fewer_devices(killed_run, tmp_path):
    """Elastic resume: the checkpoint written on 8 devices restores onto a
    4-device mesh via the recorded PartitionSpecs and trains on. Reduction
    orders differ across device counts, so the trajectory matches tightly
    but not bitwise."""
    control, step, res = _resume(killed_run, tmp_path, FT_DEVICES=4)
    np.testing.assert_allclose(res["losses"], control["losses"][step:],
                               rtol=0, atol=1e-4)


@pytest.mark.slow
def test_multihost_trainer_kill_and_driver_resume(cluster, tmp_path):
    """Multi-host shape of the same proof: a trainer ACTOR (real worker
    process) checkpoints asynchronously; the driver SIGKILLs it mid-run,
    observes the crash, then resumes the job on its own mesh from the
    last committed checkpoint."""
    import jax

    from ray_tpu.parallel import MeshSpec
    from ray_tpu.train import ft, loop, spmd
    from tests import ft_train_child as tc

    root = str(tmp_path / "ckpts")
    total = 16

    @ray_tpu.remote(max_restarts=0)
    class TrainerHost:
        def pid(self):
            return os.getpid()

        def train(self):
            import jax as j
            from ray_tpu.parallel import MeshSpec as MS
            from ray_tpu.train import ft as f, loop as lp, spmd as sp
            from tests import ft_train_child as c
            mesh = MS(data=-1).build(j.devices())
            state, step_fn, _ = sp.make_gpt_trainer(c.make_cfg(), mesh)
            ckpt = f.AsyncCheckpointer(root, every=2, max_in_flight=2,
                                       keep=2)
            place = lp.make_placer(mesh, stacked=True)
            batches = lp.DevicePrefetcher(c.host_batches(), place,
                                          depth=2, group=2)
            train = lp.TrainLoop(step_fn, unroll=2, checkpointer=ckpt)
            # Far more steps than the driver lets us live for.
            train.run(state, batches, num_steps=10_000)
            return "finished"

    host = TrainerHost.remote()
    # actor calls execute serially: grab the pid BEFORE the long train()
    pid = ray_tpu.get(host.pid.remote(), timeout=120)
    ref = host.train.remote()

    deadline = time.time() + 300
    while ft.latest_checkpoint(root) is None and time.time() < deadline:
        time.sleep(0.2)
    assert ft.latest_checkpoint(root) is not None, "no commit before kill"
    os.kill(pid, signal.SIGKILL)
    with pytest.raises((ray_tpu.exceptions.WorkerCrashedError,
                        ray_tpu.exceptions.ActorDiedError,
                        ray_tpu.exceptions.ActorUnavailableError)):
        ray_tpu.get(ref, timeout=300)

    # Driver-side resume on ITS mesh from whatever the victim committed.
    mesh = MeshSpec(data=-1).build(jax.devices())
    _, step_fn, _ = spmd.make_gpt_trainer(tc.make_cfg(), mesh,
                                          init_state=False)
    state, start = ft.restore_resharded(root, mesh)
    assert start >= 2
    ckpt = ft.AsyncCheckpointer(root, every=2, max_in_flight=2, keep=2)
    place = loop.make_placer(mesh, stacked=True)
    batches = loop.DevicePrefetcher(
        ft.fast_forward(tc.host_batches(), start), place, depth=2, group=2)
    train = loop.TrainLoop(step_fn, unroll=2, checkpointer=ckpt)
    steps = max(total, start + 4)
    state, metrics = train.run(state, batches, num_steps=steps,
                               start_step=start)
    assert [int(m["step"]) for m in metrics] == \
        list(range(start + 1, steps + 1))
    assert all(np.isfinite(m["loss"]) for m in metrics)
    ckpt.check_invariants()
    ckpt.close()
