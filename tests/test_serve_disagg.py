"""Disaggregated prefill/decode serving (ISSUE 20 tentpole): the
KV-block handoff between role-specialized engines is greedy
token-identical to a colocated engine across every decode backend
(plain / shared-prefix / n-gram spec / draft spec / int8 KV+weights),
cancellation frees paged blocks on BOTH sides, the netaddr-streamed
serve path (`run_disagg`) matches local decode, an unreachable or
killed prefill replica fails over (decode-side re-prefill fallback and
the handle retry path respectively), the proxy sheds/queues on
per-request SLO targets, and the decode pool autoscales on stream
occupancy."""

import concurrent.futures
import dataclasses
import json
import time
import urllib.error
import urllib.request

import jax
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.models import gpt
from ray_tpu.serve.engine import InferenceEngine, InferenceReplica
from ray_tpu.serve.handle import HANDLE_STATS
from ray_tpu.util.faults import FaultPlan

CFG = dict(vocab_size=128, d_model=32, n_layers=2, n_heads=2,
           d_ff=64, max_seq_len=128)

PROMPTS = [[5, 9, 3, 17, 2, 88, 41, 7, 19, 23, 55, 1, 4, 9],
           [5, 9, 3, 17, 2, 88, 41, 7, 100, 101],
           [7] * 37,
           [1, 2, 3]]


@pytest.fixture
def serve_session(ray_session):
    yield serve
    serve.shutdown()


def _controller():
    from ray_tpu.serve.controller import get_controller
    return get_controller()


def _replicas(dep, app):
    c = _controller()
    _, reps = ray_tpu.get(c.get_replicas.remote(dep, app, -1), timeout=30)
    return reps


def _cfg(**kw):
    return gpt.small(**CFG, **kw)


def _params(cfg, seed=0):
    return gpt.init_params(jax.random.PRNGKey(seed), cfg)


def _engine(cfg, params, role=None, **ek):
    kw = dict(slots=2, max_len=128, block_size=8)
    if role:
        kw["role"] = role
    return InferenceEngine(params, cfg, **kw, **ek)


def _disagg_generate(pre, dec, prompt, n):
    blob = pre.handoff_for(pre.submit(list(prompt), max_new_tokens=n))
    return [int(t) for t in dec.tokens_for(dec.import_handoff(blob))]


# ---------------------------------------------------------------------------
# tentpole proof: token identity across the decode-backend matrix
# ---------------------------------------------------------------------------

MATRIX = [
    ("plain", {}, {}),
    ("ngram", {"spec": "ngram"}, {}),
    ("draft", "draft", {}),
    ("int8", {}, {"kv_dtype": "int8", "weight_dtype": "int8"}),
]


@pytest.mark.parametrize("label,ek,cfg_kw",
                         MATRIX, ids=[m[0] for m in MATRIX])
def test_disagg_token_identity_matrix(label, ek, cfg_kw):
    """prefill-role export -> decode-role import must reproduce the
    colocated greedy stream exactly, for every decode backend — the
    handoff carries the parked first token (and its logprob/version),
    so the decode engine continues rather than re-samples."""
    cfg = _cfg()
    if cfg_kw:
        cfg = dataclasses.replace(cfg, **cfg_kw)
    params = _params(cfg)
    if ek == "draft":
        dcfg = dataclasses.replace(cfg, n_layers=1)
        ek = {"spec": "draft", "draft_cfg": dcfg,
              "draft_params": _params(dcfg, seed=1)}
    col = _engine(cfg, params, **ek)
    expected = [[int(t) for t in col.generate(list(p), max_new_tokens=12)]
                for p in PROMPTS]
    col.check_invariants()

    pre = _engine(cfg, params, role="prefill", **ek)
    dec = _engine(cfg, params, role="decode", **ek)
    got = [_disagg_generate(pre, dec, p, 12) for p in PROMPTS]
    assert got == expected
    pre.check_invariants()
    dec.check_invariants()
    ps, ds = pre.stats(), dec.stats()
    assert ps["role"] == "prefill" and ds["role"] == "decode"
    assert ps["handoffs"] == len(PROMPTS)
    assert ds["imports"] == len(PROMPTS)
    assert ps["decode_steps"] == 0, "a prefill-role engine decoded"
    assert ps["kv_blocks_exported"] > 0
    assert ps["kv_export_bytes"] > 0 and ds["kv_import_bytes"] > 0


def test_disagg_shared_prefix_token_identity():
    """Prompts sharing a long prefix: the decode pool recognizes the
    radix-cached full blocks at import (matching params_version) and
    shares them by reference instead of re-scattering — fewer blocks
    imported than exported, same tokens."""
    cfg = _cfg()
    params = _params(cfg)
    shared = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3]  # 2 blocks
    prompts = [shared + [40 + i, 41 + i] for i in range(3)]
    col = _engine(cfg, params)
    expected = [[int(t) for t in col.generate(list(p), max_new_tokens=10)]
                for p in prompts]
    pre = _engine(cfg, params, role="prefill")
    dec = _engine(cfg, params, role="decode")
    got = [_disagg_generate(pre, dec, p, 10) for p in prompts]
    assert got == expected
    pre.check_invariants()
    dec.check_invariants()
    assert dec.stats()["kv_blocks_imported"] < \
        pre.stats()["kv_blocks_exported"], \
        "shared full prefix blocks should be ref'd, not re-scattered"


# ---------------------------------------------------------------------------
# satellites: cancellation frees both pools, imports validate blobs
# ---------------------------------------------------------------------------

def test_disagg_cancel_frees_blocks_both_sides():
    cfg = _cfg()
    params = _params(cfg)
    pre = _engine(cfg, params, role="prefill")
    dec = _engine(cfg, params, role="decode")
    free_pre, free_dec = pre._alloc.free, dec._alloc.free

    def drained(eng, baseline):
        # a cancel may legitimately park full prefix blocks in the
        # radix cache (evictable, refcounted — cache, not leak); what
        # "freed" means is that evicting the cache restores the pool
        if eng._tree is not None:
            eng._tree.evict(10 ** 6)
        return eng._alloc.free == baseline

    # (a) cancelled while still queued (a prefill-role tick runs ALL
    # pending prefill work — nothing decodes — so "mid-prefill" on this
    # role means before its tick): nothing allocated, nothing leaked
    rid = pre.submit([9] * 30, max_new_tokens=8)
    assert pre.cancel(rid)
    assert drained(pre, free_pre)
    with pytest.raises(KeyError):
        pre.handoff_for(rid)

    # (b) exported but never collected: device blocks were freed at
    # export; cancel drops the parked host blob and counts the abandon
    blob = pre.handoff_for(pre.submit([8] * 20, max_new_tokens=8))
    rid3 = pre.submit([4] * 20, max_new_tokens=8)
    while rid3 not in pre._handoffs:    # pump until parked, don't pop
        pre.step()
    assert pre.cancel(rid3)
    assert pre.take_handoff(rid3) is None
    assert pre.stats()["handoffs_abandoned"] == 1
    assert drained(pre, free_pre)

    # (c) imported and cancelled mid-stream: decode pool restored
    drid = dec.import_handoff(blob)
    it = dec.tokens_for(drid)
    assert next(it) is not None
    assert dec.cancel(drid)
    it.close()
    assert drained(dec, free_dec)
    pre.check_invariants()
    dec.check_invariants()


def test_import_rejects_mismatched_blob():
    cfg = _cfg()
    params = _params(cfg)
    pre = _engine(cfg, params, role="prefill")
    dec = _engine(cfg, params, role="decode")
    blob = pre.handoff_for(pre.submit([1, 2, 3, 4], max_new_tokens=4))
    with pytest.raises(ValueError, match="block_size"):
        dec.import_handoff(dict(blob, block_size=blob["block_size"] * 2))
    with pytest.raises(ValueError, match="max_len"):
        dec.import_handoff(dict(blob, max_new_tokens=10_000))
    with pytest.raises(ValueError, match="priority"):
        dec.import_handoff(dict(blob, priority=99))
    with pytest.raises(RuntimeError):
        pre.import_handoff(blob)
    with pytest.raises(RuntimeError):
        dec.handoff_for(0)
    # the untouched blob still imports cleanly after the rejections
    assert len(list(dec.tokens_for(dec.import_handoff(blob)))) == 4
    dec.check_invariants()


# ---------------------------------------------------------------------------
# serve layer: netaddr-streamed handoff parity, fallback, failover
# ---------------------------------------------------------------------------

def test_run_disagg_parity_and_transfer_stats(serve_session):
    """`run_disagg` 1+1: prompts prefill on one replica, the KV blob
    streams over netaddr to the decode replica, and the stream is
    token-identical to a local colocated replica of the same seed."""
    h = serve.run_disagg(name="t_dz", slots=4, max_len=64, seed=0)
    local = InferenceReplica(slots=4, max_len=64, seed=0)
    for p in ([1, 2, 3, 4], [7, 5, 3], [1, 2, 3, 9, 9]):
        got = [int(t) for t in h.generate(list(p), max_new_tokens=8)]
        want = [int(t) for t in local(list(p), max_new_tokens=8)]
        assert got == want, p

    # an abandoned stream releases the decode replica's registered
    # stream (and with it the engine request) — no leak across the wire
    s = h.stream([4, 4, 4], max_new_tokens=8)
    assert next(s) is not None
    s.close()
    deadline = time.time() + 10
    while time.time() < deadline:
        if sum(ray_tpu.get(r.stats.remote(), timeout=30)
               .get("streams", 0)
               for r in _replicas("decode", "t_dz")) == 0:
            break
        time.sleep(0.2)
    else:
        pytest.fail("decode replica still holds the abandoned stream")

    dh = serve.get_deployment_handle("decode", "t_dz")
    ds = ray_tpu.get(dh.stats.remote(), timeout=30)
    assert ds["imports"] >= 3
    assert ds["kv_pulled_bytes"] > 0
    assert ds["kv_transfer_gbps"] > 0
    assert ds["handoff_pull_ms_p99"] >= ds["handoff_pull_ms_p50"] > 0
    assert ds["handoff_fallbacks"] == 0
    ph = serve.get_deployment_handle("prefill", "t_dz")
    ps = ray_tpu.get(ph.stats.remote(), timeout=30)
    assert ps["handoffs"] >= 3 and ps["decode_steps"] == 0


def test_decode_fallback_when_prefill_unreachable(ray_session):
    """A descriptor whose source replica died before the KV pull: the
    decode replica falls back to a full local re-prefill — slower, but
    token-identical and counted."""
    from ray_tpu.serve.disagg import DecodeReplica
    dec = DecodeReplica(slots=2, max_len=64, seed=0)
    local = InferenceReplica(slots=2, max_len=64, seed=0)
    desc = {"handoff_addr": "127.0.0.1:9", "handoff_key": "00" * 16,
            "handoff_id": 1, "prompt": [5, 9, 3], "max_new_tokens": 8,
            "temperature": 0.0, "priority": 0, "kv_bytes": 0}
    got = [int(t) for t in dec(desc)]
    want = [int(t) for t in local([5, 9, 3], max_new_tokens=8)]
    assert got == want
    assert dec.stats()["handoff_fallbacks"] == 1
    dec.engine.check_invariants()


def test_prefill_kill_mid_handoff_fails_over(serve_session):
    """Seeded chaos: one of two prefill replicas dies at its next
    engine tick (mid-handoff, inside `handoff_for`'s pump). The
    deployment handle must retry the call on the survivor — every
    stream completes token-identical, none error out."""
    h = serve.run_disagg(name="t_dzkill", prefill_replicas=2,
                         decode_replicas=1, slots=2, max_len=64, seed=0)
    expected = [int(t) for t in h.generate([5, 9, 3], max_new_tokens=8)]
    reps = _replicas("prefill", "t_dzkill")
    assert len(reps) == 2
    ray_tpu.get(reps[0].install_faults.remote(
        FaultPlan(seed=20).kill("engine.tick", at=0)), timeout=30)
    before = HANDLE_STATS.stats()["retries"]
    # power-of-two routing picks per call: keep issuing until the
    # faulted replica is hit (its death must be invisible to callers)
    for _ in range(20):
        assert [int(t) for t in
                h.generate([5, 9, 3], max_new_tokens=8)] == expected
        if HANDLE_STATS.stats()["retries"] > before:
            break
    else:
        pytest.fail("the faulted prefill replica never took a call")


# ---------------------------------------------------------------------------
# SLO-aware admission at the proxy
# ---------------------------------------------------------------------------

def test_proxy_slo_admission_sheds_and_queues(serve_session):
    """A deployment reporting fixed latency histograms: requests whose
    SLO targets the live p99s already violate are 429-shed at the
    lowest priority class and queued-then-admitted at higher classes,
    with both counters on the proxy's stats source."""
    @serve.deployment(num_replicas=1)
    class FixedLatency:
        def __call__(self, req):
            return "ok"

        def stats(self):
            return {"ttft_ms_p99": 50.0, "p99_token_latency_ms": 5.0}

    serve.run(FixedLatency.bind(), name="t_slo")
    proxy = serve.start(http_options={"port": 0})
    info = ray_tpu.get(proxy.ready.remote(), timeout=30)
    serve.set_route("/slo", "FixedLatency", "t_slo")
    base = f"http://127.0.0.1:{info['port']}/slo"

    # wait for the controller scrape to publish the latency snapshot
    c = _controller()
    deadline = time.time() + 30
    while time.time() < deadline:
        snap = ray_tpu.get(c.get_slo_snapshot.remote(), timeout=30)
        if snap.get("t_slo:FixedLatency", {}).get("ttft_ms_p99") == 50.0:
            break
        time.sleep(0.25)
    else:
        pytest.fail(f"controller never published an SLO snapshot: "
                    f"{ray_tpu.get(c.get_slo_snapshot.remote(), timeout=30)}")

    def get(url, headers=None):
        req = urllib.request.Request(url, headers=headers or {})
        return urllib.request.urlopen(req, timeout=30)

    # satisfiable targets admit
    assert get(base, {"X-SLO-TTFT-MS": "1000",
                      "X-SLO-TPOT-MS": "100"}).status == 200
    # unsatisfiable target, lowest class: immediate shed
    try:
        get(base, {"X-SLO-TTFT-MS": "1"})
        pytest.fail("expected HTTP 429")
    except urllib.error.HTTPError as e:
        assert e.code == 429
        assert e.headers.get("Retry-After") == "1"
        assert json.loads(e.read())["error"] == "slo_shed"
    # unsatisfiable TPOT target via query params: same shed
    try:
        get(base + "?slo_tpot_ms=0.001")
        pytest.fail("expected HTTP 429")
    except urllib.error.HTTPError as e:
        assert e.code == 429
    # malformed target: 400, not a shed
    try:
        get(base, {"X-SLO-TTFT-MS": "fast"})
        pytest.fail("expected HTTP 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
    # unsatisfiable but priority class 1: queued briefly, then admitted
    t0 = time.time()
    assert get(base, {"X-SLO-TTFT-MS": "1",
                      "X-Serve-Priority": "1"}).status == 200
    assert time.time() - t0 >= 0.2, "high class should queue, not sail"

    st = ray_tpu.get(proxy.stats.remote(), timeout=30)
    assert st["slo_sheds"] >= 2
    assert st["slo_queued"] >= 1
    assert st["routes"] >= 1


# ---------------------------------------------------------------------------
# per-role autoscaling: the decode pool scales on stream occupancy
# ---------------------------------------------------------------------------

def test_decode_pool_autoscales_on_streams(serve_session):
    """Decode replicas carry long-lived token streams, not short calls —
    `demand_signal: "streams"` scales the pool on live stream count.
    Four concurrent streams against a throttled 1-per-replica target
    must grow the decode pool; the prefill pool (no backlog) stays
    put."""
    from ray_tpu.serve.disagg import DecodeReplica, PrefillReplica

    class SlowDecode(DecodeReplica):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            orig = self.engine.step

            def slow_step():
                time.sleep(0.04)
                return orig()

            self.engine.step = slow_step

    pre_app = serve.deployment(
        PrefillReplica, num_replicas=1).bind(None, slots=2, max_len=64,
                                             seed=0)
    dec_app = serve.deployment(
        SlowDecode,
        autoscaling_config={
            "min_replicas": 1, "max_replicas": 2,
            "target_num_ongoing_requests_per_replica": 1,
            "downscale_delay_s": 30.0,
            "demand_signal": "streams",
        },
    ).bind(None, slots=2, max_len=64, seed=0)
    serve.run(pre_app, name="t_dzpre")
    serve.run(dec_app, name="t_dzdec")
    from ray_tpu.serve.disagg import DisaggHandle
    h = DisaggHandle(serve.get_deployment_handle("PrefillReplica",
                                                 "t_dzpre"),
                     serve.get_deployment_handle("SlowDecode",
                                                 "t_dzdec"))
    warm = [int(t) for t in h.generate([5, 9, 3], max_new_tokens=4)]
    assert len(warm) == 4

    def one(_):
        return [int(t) for t in h.generate([5, 9, 3],
                                           max_new_tokens=48)]

    grew = False
    with concurrent.futures.ThreadPoolExecutor(4) as pool:
        futs = [pool.submit(one, i) for i in range(4)]
        deadline = time.time() + 60
        while time.time() < deadline:
            st = serve.status().get("t_dzdec:SlowDecode", {})
            if st.get("target_replicas", 1) >= 2 and \
                    st.get("replicas", 1) >= 2:
                grew = True
                break
            time.sleep(0.2)
        outs = [f.result(timeout=120) for f in futs]
    assert grew, f"decode pool never scaled on streams: " \
                 f"{serve.status().get('t_dzdec:SlowDecode')}"
    # no stream was truncated by the scaling event, and all replicas
    # decode greedily from the same seed
    assert all(len(o) == 48 for o in outs)
    assert all(o == outs[0] for o in outs)
    # the prefill pool (fixed size, no autoscaling config) is untouched
    assert serve.status()["t_dzpre:PrefillReplica"]["replicas"] == 1
