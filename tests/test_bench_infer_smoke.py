"""Tier-1 inference-bench smoke: `bench_infer.main()` end-to-end in CPU
mode through the continuous-batching engine, asserting the one-line JSON
contract (headline fields plus the inference extras) the driver
scrapes."""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))


def test_bench_infer_cpu_smoke(capsys, monkeypatch):
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_REQUESTS", "3")  # CI fast
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_NEW", "3")
    import bench_infer

    bench_infer.main()
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    assert rec["metric"] == "gpt_decode_tokens_per_sec"
    assert rec["unit"] == "tokens/s"
    assert rec["vs_baseline"] == 0.0     # CPU mode: no roofline ratio
    for key in ("value", "prefill_tokens_per_sec",
                "decode_tokens_per_sec", "p50_token_latency_ms",
                "p99_token_latency_ms"):
        assert np.isfinite(rec[key]) and rec[key] > 0, (key, rec)
    assert rec["value"] == rec["decode_tokens_per_sec"]
    assert 0 < rec["slot_occupancy"] <= 1.0
    assert rec["p50_token_latency_ms"] <= rec["p99_token_latency_ms"]
