"""Tier-1 inference-bench smoke: `bench_infer.main()` end-to-end in CPU
mode through the continuous-batching engine, asserting the one-line JSON
contract (headline fields plus the inference extras) the driver
scrapes."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))


def test_bench_infer_cpu_smoke(capsys, monkeypatch):
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_REQUESTS", "3")  # CI fast
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_NEW", "3")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_TRACE_OVERHEAD", "1")
    import bench_infer

    bench_infer.main()
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    assert rec["metric"] == "gpt_decode_tokens_per_sec"
    assert rec["unit"] == "tokens/s"
    assert rec["vs_baseline"] == 0.0     # CPU mode: no roofline ratio
    for key in ("value", "prefill_tokens_per_sec",
                "decode_tokens_per_sec", "p50_token_latency_ms",
                "p99_token_latency_ms"):
        assert np.isfinite(rec[key]) and rec[key] > 0, (key, rec)
    assert rec["value"] == rec["decode_tokens_per_sec"]
    assert 0 < rec["slot_occupancy"] <= 1.0
    assert rec["p50_token_latency_ms"] <= rec["p99_token_latency_ms"]
    # paged-cache fields of the JSON contract
    assert 0.0 <= rec["prefix_hit_rate"] <= 1.0
    assert 0.0 < rec["cache_block_utilization"] <= 1.0
    assert rec["max_admission_stall_ms"] >= 0.0
    assert rec["block_size"] > 0 and rec["cache_blocks"] > 0
    assert rec["shared_prefix"] == 0
    # spec off: speculative fields present but neutral
    assert rec["spec"] == "" and rec["spec_k"] == 0
    assert rec["acceptance_rate"] == 0.0
    assert rec["tokens_per_step"] == 1.0
    assert rec["spec_decode_tok_s"] == 0.0
    # RL-flywheel fields: the warm in-place weight swap (bench_infer
    # itself asserts the swap didn't retrace) and engine rollout rate
    assert np.isfinite(rec["weight_swap_ms"]) and rec["weight_swap_ms"] > 0
    # The absolute-wall-time bounds below distinguish "warm path" from
    # "accidental recompile" — but only when this process actually gets
    # the CPU. Under a loaded tier-1 runner (parallel suites, CI
    # neighbors) a warm swap can be descheduled past any fixed bound, so
    # the strict thresholds apply only when the 1-minute load average
    # leaves headroom; the structural guarantees (finiteness, the
    # retrace sentinel, trace-counter pins inside bench_infer.main)
    # hold unconditionally either way.
    calm = os.getloadavg()[0] < (os.cpu_count() or 1)
    if calm:
        assert rec["weight_swap_ms"] < 1000.0  # warm swap, not a compile
    assert rec["rollout_tok_s"] > 0.0
    # telemetry fields: TTFT percentiles over the timed region, a clean
    # retrace sentinel, and the flight-recorder overhead probe. The
    # target is <1% sampled-on vs sampled-off; XLA:CPU smoke wall times
    # are dominated by scheduler noise, so only a loose bound is
    # assertable here — the headline overhead number belongs on silicon.
    assert np.isfinite(rec["ttft_ms_p50"]) and rec["ttft_ms_p50"] > 0
    assert rec["ttft_ms_p50"] <= rec["ttft_ms_p99"]
    assert rec["retraces_unexpected"] == 0
    assert np.isfinite(rec["trace_overhead_pct"])
    if calm:    # wall-time delta of two tiny runs — pure noise under load
        assert abs(rec["trace_overhead_pct"]) < 50.0
    # quantization fields: everything full-precision by default. The
    # default pool is bf16 (TPU) / model dtype, so capacity_vs_f32 — a
    # ratio against an f32 pool of the same geometry — pins at exactly
    # 2.0, and the quality proxy is identically 0 (nothing to compare).
    assert rec["kv_dtype"] == "f32" and rec["weight_dtype"] == "f32"
    assert rec["pool_bytes"] > 0
    assert rec["capacity_streams_per_gb"] > 0
    assert rec["capacity_vs_f32"] == 2.0
    assert rec["quality_logprob_delta"] == 0.0
    # priority-mix off: fields present but neutral
    assert rec["priority_mix"] == ""
    assert rec["preemptions"] == 0
    assert rec["reprefill_blocks"] == 0
    assert rec["queue_wait_ms_p99_by_class"] == {}
    # disagg A/B (on by default): contract presence + types only — the
    # colocated-vs-disagg ordering is real on silicon and in the
    # recorded bench (BENCH_INFER_r02.json) but too noisy to pin on a
    # loaded CPU smoke runner.
    assert rec["disagg"] == 1
    assert rec["disagg_prefill_replicas"] == 1
    assert rec["disagg_decode_replicas"] == 1
    for key in ("disagg_decode_tpot_ms_p99", "colocated_decode_tpot_ms_p99",
                "disagg_ttft_ms_p99", "colocated_ttft_ms_p99"):
        assert np.isfinite(rec[key]) and rec[key] > 0, (key, rec)
    assert np.isfinite(rec["kv_transfer_gbps"]) and rec["kv_transfer_gbps"] > 0
    assert rec["kv_blocks_streamed"] > 0


def test_bench_infer_quantized_smoke(capsys, monkeypatch):
    """KV_DTYPE=int8 + WEIGHT_DTYPE=int8: the capacity headline (the
    tentpole's >=1.9x concurrent-stream criterion at equal pool budget)
    plus the pinned quality bound, with the retrace sentinel still
    silent — quantization must not add a single unexpected trace."""
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_REQUESTS", "3")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_NEW", "3")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_DISAGG", "0")  # timed in cpu_smoke
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_KV_DTYPE", "int8")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_WEIGHT_DTYPE", "int8")
    import bench_infer

    bench_infer.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["kv_dtype"] == "int8" and rec["weight_dtype"] == "int8"
    # int8 rows cost H*(D+4) bytes vs the f32 pool's H*D*4: >= 1.9x
    # more tokens (streams) per byte — 3.556x at this head_dim.
    assert rec["capacity_vs_f32"] > 1.9
    assert rec["capacity_streams_per_gb"] > 0
    assert rec["pool_bytes"] > 0
    # quality proxy: mean |greedy logprob delta| vs an f32 engine on
    # the same prompts — the "tight-allclose" bound, pinned loose
    # enough to absorb prompt-mix noise but far below real drift.
    assert 0.0 <= rec["quality_logprob_delta"] < 0.02
    assert rec["retraces_unexpected"] == 0
    assert rec["value"] == rec["decode_tokens_per_sec"] > 0
    # DISAGG=0: the A/B fields are present but neutral
    assert rec["disagg"] == 0 and rec["kv_blocks_streamed"] == 0
    assert rec["disagg_decode_tpot_ms_p99"] == 0.0
    assert rec["kv_transfer_gbps"] == 0.0


def test_bench_infer_spec_ngram_smoke(capsys, monkeypatch):
    """SPEC=ngram on the repeated-motif workload: the JSON must carry
    the speculative fields, with tokens_per_step > 1.0 (speculation is
    actually landing multi-token steps) and the compile guarantees
    asserted inside bench_infer.main() itself."""
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_DISAGG", "0")  # timed in cpu_smoke
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_SPEC", "ngram")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_NEW", "16")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_MAX_LEN", "32")
    import bench_infer

    bench_infer.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["spec"] == "ngram" and rec["spec_k"] == 4
    assert 0.0 < rec["acceptance_rate"] <= 1.0
    assert rec["tokens_per_step"] > 1.0, rec
    assert rec["spec_decode_tok_s"] > 0.0
    # the baseline headline is untouched by the spec engine's run
    assert rec["value"] == rec["decode_tokens_per_sec"] > 0


def test_bench_infer_spec_draft_smoke(capsys, monkeypatch):
    """SPEC=draft exercises the draft-model proposal path end to end.
    A randomly-initialized 1-layer draft rarely agrees with the target,
    so only the contract is pinned — acceptance is workload truth, not
    a constant."""
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_DISAGG", "0")  # timed in cpu_smoke
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_SPEC", "draft")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_SPEC_K", "2")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_NEW", "8")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_MAX_LEN", "32")
    import bench_infer

    bench_infer.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["spec"] == "draft" and rec["spec_k"] == 2
    assert 0.0 <= rec["acceptance_rate"] <= 1.0
    assert rec["tokens_per_step"] >= 1.0
    assert rec["spec_decode_tok_s"] > 0.0


@pytest.mark.slow
def test_bench_infer_spec_big(capsys, monkeypatch):
    """Larger spec run (more requests, longer generations) — the shape
    that actually measures speedup; headline comparisons belong on
    silicon."""
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_DISAGG", "0")  # timed in cpu_smoke
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_SPEC", "ngram")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_REQUESTS", "16")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_NEW", "24")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_PROMPT", "16")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_MAX_LEN", "64")
    import bench_infer

    bench_infer.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["tokens_per_step"] > 1.0
    assert rec["spec_decode_tok_s"] > 0.0


def test_bench_infer_priority_mix_smoke(capsys, monkeypatch):
    """PRIORITY_MIX with a pool sized below the mix's footprint: the
    high-class wave must preempt at least one low-class stream (real
    block pressure, deterministically provoked), and the JSON carries
    the per-class p99 queue-wait contract. Geometry: block 4, prompt 8,
    new 6 => 4 blocks per request; CACHE_BLOCKS=7 leaves 6 usable
    (block 0 is the trash block), so two streams can't coexist."""
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_DISAGG", "0")  # timed in cpu_smoke
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_PRIORITY_MIX", "2,0,1")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_CACHE_BLOCKS", "7")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_BLOCK", "4")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_PROMPT", "8")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_NEW", "6")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_MAX_LEN", "32")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_REQUESTS", "3")
    import bench_infer

    bench_infer.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["priority_mix"] == "2,0,1"
    assert rec["preemptions"] >= 1, rec
    assert rec["reprefill_blocks"] >= 1, rec
    waits = rec["queue_wait_ms_p99_by_class"]
    assert set(waits) == {"0", "2"} and all(
        np.isfinite(v) and v >= 0 for v in waits.values()), rec
    # the baseline headline is untouched by the priority engine's run
    assert rec["value"] == rec["decode_tokens_per_sec"] > 0


def test_bench_infer_shared_prefix_knobs(capsys, monkeypatch):
    """Shared-prefix + ragged workload: the radix cache must register
    hits and the JSON must echo the knob."""
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_REQUESTS", "4")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_NEW", "3")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_PROMPT", "24")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_DISAGG", "0")  # timed in cpu_smoke
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_SHARED_PREFIX", "16")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_RAGGED", "1")
    monkeypatch.setenv("RAY_TPU_INFER_BENCH_BLOCK", "8")
    import bench_infer

    bench_infer.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["shared_prefix"] == 16
    assert rec["block_size"] == 8
    assert rec["prefix_hit_rate"] > 0.0, rec
