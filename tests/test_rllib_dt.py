"""Decision Transformer — offline return-conditioned control
(reference: rllib/algorithms/dt/).

The decisive property: trained on a MIXED-quality dataset, conditioning
on the expert return must recover near-expert behavior — i.e. DT beats
the dataset average, which plain behavior cloning of the same data
cannot (BC regresses to the mixture)."""

import numpy as np

JAX_ENV_CFG = {"max_steps": 200}


def _collect_episodes(policy, n_eps, seed):
    """Roll CartPole eagerly with a python policy; returns SampleBatch
    columns."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.env.jax_env import make_env
    env = make_env("CartPole-v1", JAX_ENV_CFG)
    key = jax.random.PRNGKey(seed)
    cols = {"obs": [], "actions": [], "rewards": [], "dones": []}
    for _ in range(n_eps):
        key, k = jax.random.split(key)
        state, obs = env.reset(k)
        done = False
        while not done:
            a = policy(np.asarray(obs))
            key, k = jax.random.split(key)
            state, nxt, r, d, _ = env.step(state, jnp.asarray(a), k)
            cols["obs"].append(np.asarray(obs, np.float32))
            cols["actions"].append(np.int32(a))
            cols["rewards"].append(np.float32(r))
            cols["dones"].append(bool(d))
            obs, done = nxt, bool(d)
    return {k: np.asarray(v) for k, v in cols.items()}


def _expert(obs):
    # classic angle + angular-velocity controller: ~max return
    return 1 if obs[2] + 0.5 * obs[3] > 0 else 0


def test_dt_return_conditioning_beats_dataset():
    rng = np.random.default_rng(0)
    expert = _collect_episodes(_expert, 12, seed=1)
    random_ = _collect_episodes(
        lambda o: int(rng.integers(0, 2)), 12, seed=2)

    from ray_tpu.rllib.algorithms.dt import DTConfig
    cfg = DTConfig().environment("CartPole-v1", env_config=JAX_ENV_CFG)
    cfg.offline_data(input_=[expert, random_])
    cfg.train_batch_size = 64
    cfg.context_len = 20
    cfg.n_updates_per_iter = 60
    cfg.eval_episodes = 3
    cfg.seed = 0
    algo = cfg.build()
    best = -np.inf
    res = {}
    for _ in range(10):
        res = algo.train()
        best = max(best, res["episode_reward_mean"])
        if best >= 150:
            break
    ds_mean = res["dataset_return_mean"]
    assert res["dataset_return_max"] > 150       # expert data present
    assert ds_mean < 130                          # genuinely mixed
    # conditioning on the expert return recovers near-expert control
    assert best >= 150, (best, ds_mean)
    assert best >= ds_mean + 20, (best, ds_mean)
