"""multiprocessing.Pool drop-in and joblib backend.

Counterpart of the reference's `python/ray/tests/test_multiprocessing.py`
and `test_joblib.py`.
"""

import math

import pytest

import ray_tpu
from ray_tpu.util.multiprocessing import AsyncResult, Pool, TimeoutError


@pytest.fixture
def pool(ray_session):
    p = Pool(processes=3)
    yield p
    p.terminate()


def _sq(x):
    return x * x


def _add(a, b):
    return a + b


def test_map(pool):
    assert pool.map(_sq, range(10)) == [x * x for x in range(10)]


def test_map_chunked(pool):
    assert pool.map(_sq, range(23), chunksize=5) == \
        [x * x for x in range(23)]


def test_apply_and_async(pool):
    assert pool.apply(_add, (2, 3)) == 5
    res = pool.apply_async(_add, (10, 20))
    assert isinstance(res, AsyncResult)
    assert res.get(timeout=60) == 30
    assert res.ready() and res.successful()


def test_starmap(pool):
    assert pool.starmap(_add, [(1, 2), (3, 4), (5, 6)]) == [3, 7, 11]


def test_imap_ordered(pool):
    out = list(pool.imap(_sq, range(8), chunksize=3))
    assert out == [x * x for x in range(8)]


def test_imap_unordered(pool):
    out = sorted(pool.imap_unordered(_sq, range(8), chunksize=2))
    assert out == sorted(x * x for x in range(8))


def test_error_propagates(pool):
    def boom(x):
        raise RuntimeError("pool boom")
    with pytest.raises(RuntimeError, match="pool boom"):
        pool.map(boom, range(3))


def test_async_callbacks(pool):
    import threading
    done = threading.Event()
    got = []
    pool.map_async(_sq, range(4), callback=lambda r: (got.append(r),
                                                      done.set()))
    assert done.wait(60)
    assert got[0] == [0, 1, 4, 9]


def test_closed_pool_rejects(pool):
    pool.close()
    with pytest.raises(ValueError):
        pool.map(_sq, [1])


def test_context_manager(ray_session):
    with Pool(2) as p:
        assert p.map(_sq, [2, 4]) == [4, 16]
    with pytest.raises(ValueError):
        p.map(_sq, [1])


def test_joblib_backend(ray_session):
    joblib = pytest.importorskip("joblib")
    from ray_tpu.util.joblib import register_ray_tpu
    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu", n_jobs=3):
        out = joblib.Parallel()(
            joblib.delayed(math.factorial)(i) for i in range(8))
    assert out == [math.factorial(i) for i in range(8)]
