"""Units for train/ft.py: async sharded checkpointing + elastic restore.

The end-to-end kill/resume proof lives in tests/test_chaos.py; these
tests pin the mechanisms it relies on — atomic commit, checksummed
restore, elastic resharding, the in-flight bound, and the
no-per-step-host-sync property of snapshotting.
"""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel import MeshSpec
from ray_tpu.train import ft, loop, spmd
from ray_tpu.train.checkpoint import CheckpointError


@pytest.fixture(scope="module")
def mesh8():
    return MeshSpec(data=-1).build(jax.devices())


def sharded_tree(mesh):
    """Small mixed pytree with data-sharded, replicated and scalar
    leaves — the shapes of a real TrainState without the compile cost."""
    w = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    return {
        "params": {
            "w": jax.device_put(w, NamedSharding(mesh, P("data", None))),
            "b": jax.device_put(jnp.ones(8), NamedSharding(mesh, P())),
        },
        "step": jax.device_put(jnp.asarray(7, jnp.int32),
                               NamedSharding(mesh, P())),
    }


def snapshot_to(root, tree, step, **kw):
    ckpt = ft.AsyncCheckpointer(str(root), every=1, **kw)
    ckpt.maybe_snapshot(tree, step, force=True)
    ckpt.flush()
    return ckpt


def assert_trees_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert x.dtype == y.dtype and x.shape == y.shape
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_snapshot_restore_roundtrip_same_mesh(mesh8, tmp_path):
    tree = sharded_tree(mesh8)
    ckpt = snapshot_to(tmp_path, tree, 5)
    ckpt.check_invariants()
    ckpt.close()
    restored, step = ft.restore_resharded(str(tmp_path), mesh8)
    assert step == 5
    assert_trees_equal(restored, tree)
    # recorded PartitionSpecs re-applied, not degraded to replication
    assert restored["params"]["w"].sharding.spec == P("data", None)


@pytest.mark.parametrize("ndev", [4, 2, 1])
def test_elastic_restore_different_device_count(mesh8, tmp_path, ndev):
    tree = sharded_tree(mesh8)
    snapshot_to(tmp_path, tree, 3).close()
    small = MeshSpec(data=-1).build(jax.devices()[:ndev])
    restored, step = ft.restore_resharded(str(tmp_path), small)
    assert step == 3
    assert_trees_equal(restored, tree)
    w = restored["params"]["w"]
    assert w.sharding.mesh.devices.size == ndev
    assert w.sharding.spec == P("data", None)


def test_bfloat16_leaves_roundtrip(mesh8, tmp_path):
    tree = {"p": jax.device_put(
        jnp.linspace(-2, 2, 16, dtype=jnp.bfloat16),
        NamedSharding(mesh8, P()))}
    snapshot_to(tmp_path, tree, 1).close()
    restored, _ = ft.restore_resharded(str(tmp_path), mesh8)
    assert restored["p"].dtype == jnp.bfloat16
    assert_trees_equal(restored, tree)


def test_writer_crash_leaves_no_partial_checkpoint(mesh8, tmp_path,
                                                   monkeypatch):
    tree = sharded_tree(mesh8)
    snapshot_to(tmp_path, tree, 2).close()     # a good previous commit
    before = ft.committed_steps(str(tmp_path))

    real = ft._write_file
    calls = {"n": 0}

    def dying(path, data):
        calls["n"] += 1
        if calls["n"] >= 2:                    # die mid-checkpoint
            raise OSError("disk full")
        real(path, data)

    monkeypatch.setattr(ft, "_write_file", dying)
    ckpt = ft.AsyncCheckpointer(str(tmp_path), every=1)
    ckpt.maybe_snapshot(tree, 4, force=True)
    with pytest.raises(CheckpointError, match="disk full"):
        ckpt.flush()
    monkeypatch.setattr(ft, "_write_file", real)
    # the failed step never became visible; the old commit is intact
    assert ft.committed_steps(str(tmp_path)) == before
    assert not any(d.startswith(".step_") for d in os.listdir(tmp_path)), \
        "crashed writer leaked a temp dir"
    ft.validate_checkpoint(before[-1][1])
    ckpt.close()


def test_partial_dir_ignored_and_empty_root_raises(mesh8, tmp_path):
    os.makedirs(tmp_path / "step_00000042")    # no manifest: uncommitted
    assert ft.committed_steps(str(tmp_path)) == []
    assert ft.latest_checkpoint(str(tmp_path)) is None
    with pytest.raises(CheckpointError, match="no committed checkpoint"):
        ft.restore_resharded(str(tmp_path), mesh8)


def test_corrupted_shard_detected(mesh8, tmp_path):
    tree = sharded_tree(mesh8)
    snapshot_to(tmp_path, tree, 1).close()
    path = ft.latest_checkpoint(str(tmp_path))
    shard = os.path.join(path, "shard_00000.bin")
    blob = bytearray(open(shard, "rb").read())
    blob[0] ^= 0xFF
    with open(shard, "wb") as f:
        f.write(blob)
    with pytest.raises(CheckpointError, match="checksum mismatch"):
        ft.validate_checkpoint(path)
    with pytest.raises(CheckpointError, match="checksum mismatch"):
        ft.restore_resharded(str(tmp_path), mesh8)


def test_in_flight_bound_backpressures(mesh8, tmp_path, monkeypatch):
    """A slow filesystem stalls maybe_snapshot, never memory: at most
    max_in_flight snapshots sit between device and disk."""
    release = threading.Event()
    real_get = ft._device_get
    max_seen = {"q": 0}

    def slow_get(tree):
        release.wait(30)
        return real_get(tree)

    monkeypatch.setattr(ft, "_device_get", slow_get)
    ckpt = ft.AsyncCheckpointer(str(tmp_path), every=1, max_in_flight=1,
                                keep=5)
    tree = sharded_tree(mesh8)
    ckpt.maybe_snapshot(tree, 1, force=True)   # writer dequeues, blocks
    time.sleep(0.2)                            # let the writer pick it up
    ckpt.maybe_snapshot(tree, 2, force=True)   # fills the bounded queue

    def late_release():
        time.sleep(0.3)
        max_seen["q"] = ckpt._queue.qsize()
        release.set()

    t = threading.Thread(target=late_release)
    t.start()
    ckpt.maybe_snapshot(tree, 3, force=True)   # must block until release
    t.join()
    assert max_seen["q"] <= 1                  # bound held while stalled
    assert ckpt.stalls >= 1
    ckpt.flush()
    ckpt.check_invariants()
    assert ckpt.commits == 3
    ckpt.close()


def test_keep_prunes_oldest(mesh8, tmp_path):
    ckpt = ft.AsyncCheckpointer(str(tmp_path), every=1, keep=2)
    tree = sharded_tree(mesh8)
    for step in range(1, 6):
        ckpt.maybe_snapshot(tree, step, force=True)
        ckpt.flush()
    assert [s for s, _ in ft.committed_steps(str(tmp_path))] == [4, 5]
    ckpt.check_invariants()
    ckpt.close()


def test_snapshot_cadence(mesh8, tmp_path):
    ckpt = ft.AsyncCheckpointer(str(tmp_path), every=4, keep=10)
    tree = sharded_tree(mesh8)
    for step in range(1, 13):
        ckpt.maybe_snapshot(tree, step)
    ckpt.flush()
    assert ckpt.snapshots == 3
    assert [s for s, _ in ft.committed_steps(str(tmp_path))] == [4, 8, 12]
    ckpt.close()


def test_fast_forward():
    it = ft.fast_forward(iter(range(10)), 4)
    assert list(it) == [4, 5, 6, 7, 8, 9]


def test_uri_root_mirrors_and_restores(mesh8, tmp_path):
    """root='mem://...' stages locally and mirrors every commit through
    the commit-marker upload; restore works straight from the URI."""
    from ray_tpu.util import storage
    uri = "mem://ftckpt/run1"
    tree = sharded_tree(mesh8)
    ckpt = ft.AsyncCheckpointer(uri, every=1, keep=1)
    ckpt.maybe_snapshot(tree, 9, force=True)
    ckpt.flush()
    assert storage.is_committed(storage.uri_join(uri, "step_00000009"))
    restored, step = ft.restore_resharded(uri, mesh8)
    assert step == 9
    assert_trees_equal(restored, tree)
    ckpt.close()


def test_training_thread_never_syncs(mesh8, tmp_path, monkeypatch):
    """The acceptance criterion: with checkpointing ON, every device→host
    fetch ft performs happens OFF the training thread, and the loop's own
    fetch count stays at its ring cadence bound."""
    cfg_devices = jax.devices()
    mesh = MeshSpec(data=-1).build(cfg_devices)
    from ray_tpu.models import gpt
    cfg = gpt.small(vocab_size=64, d_model=16, n_layers=1, n_heads=2,
                    d_ff=32, max_seq_len=8)
    state, step_fn, _ = spmd.make_gpt_trainer(cfg, mesh)

    main_thread = threading.get_ident()
    ft_fetch_threads = []
    loop_fetches = {"n": 0}
    real_ft_get, real_loop_get = ft._device_get, loop._device_get

    def spy_ft(tree):
        ft_fetch_threads.append(threading.get_ident())
        return real_ft_get(tree)

    def spy_loop(tree):
        loop_fetches["n"] += 1
        return real_loop_get(tree)

    monkeypatch.setattr(ft, "_device_get", spy_ft)
    monkeypatch.setattr(loop, "_device_get", spy_loop)

    def host_batches():
        rng = np.random.default_rng(0)
        while True:
            toks = rng.integers(0, cfg.vocab_size, (8, cfg.max_seq_len + 1),
                                np.int32)
            yield {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    steps, every = 20, 5
    ckpt = ft.AsyncCheckpointer(str(tmp_path), every=every, keep=2)
    place = loop.make_placer(mesh)
    batches = loop.DevicePrefetcher(host_batches(), place, depth=2)
    train = loop.TrainLoop(step_fn, metrics_interval=10,
                           checkpointer=ckpt)
    state, metrics = train.run(state, batches, num_steps=steps)
    ckpt.check_invariants()
    ckpt.close()

    assert len(metrics) == steps
    # ft fetched exactly one tree per snapshot, never on the main thread
    assert len(ft_fetch_threads) == ckpt.snapshots == steps // every
    assert all(t != main_thread for t in ft_fetch_threads)
    # the loop's fetch budget is unchanged by checkpointing: one lagged
    # fetch per interval plus the end-of-run drain
    assert loop_fetches["n"] <= steps // 10 + 1
