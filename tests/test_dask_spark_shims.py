"""dask and spark integration shims.

References: `python/ray/util/dask/` (ray_dask_get scheduler over the
dask graph spec) and `python/ray/util/spark/` (setup_ray_cluster: head
on the driver, worker nodes held by a background Spark job). The dask
scheduler is exercised on hand-built graphs (the documented dask spec —
no dask needed); the spark seam is driven by a fake SparkSession whose
executors are local threads, the same RDD protocol a real session
provides.
"""

import threading

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util.dask import ray_dask_get, ray_dask_get_sync


def _inc(x):
    return x + 1


def _add(a, b):
    return a + b


def _sum(xs):
    return sum(xs)


GRAPH = {
    "a": 1,
    "b": (_inc, "a"),            # 2
    "c": (_inc, "b"),            # 3
    "d": (_add, "b", "c"),       # 5
    "e": (_sum, ["b", "c", "d"]),  # 10
    "alias": "d",
}


def test_dask_get_executes_graph(ray_session):
    assert ray_dask_get(GRAPH, "e") == 10
    assert ray_dask_get(GRAPH, ["b", "d"]) == [2, 5]
    # nested key structure comes back with matching shape
    assert ray_dask_get(GRAPH, [["b", "c"], "alias"]) == [[2, 3], 5]


def test_dask_get_shares_subgraphs(ray_session):
    """A diamond's shared node computes once (its ObjectRef is reused)."""
    calls = []

    def probe(x):
        import os
        return (x, os.getpid())

    dsk = {
        "base": (probe, 1),
        "l": (lambda t: t[1], "base"),
        "r": (lambda t: t[1], "base"),
        "pair": (lambda a, b: (a, b), "l", "r"),
    }
    left, right = ray_dask_get(dsk, "pair")
    assert left == right       # same execution, not two probe() calls


def test_dask_get_nested_tasks_and_literals(ray_session):
    dsk = {
        "x": (_add, (_inc, 1), 10),        # nested task -> inline
        "y": (_sum, [1, 2, (_inc, 0)]),
    }
    assert ray_dask_get(dsk, "x") == 12
    assert ray_dask_get(dsk, "y") == 4


def test_dask_get_sync_matches(ray_session):
    for keys in ("e", ["b", "d"], [["b"], "c"]):
        assert ray_dask_get_sync(GRAPH, keys) == ray_dask_get(GRAPH, keys)


def test_dask_get_cycle_detection(ray_session):
    with pytest.raises(ValueError, match="cycle"):
        ray_dask_get({"a": (_inc, "b"), "b": (_inc, "a")}, "a")


def test_dask_numpy_partitions(ray_session):
    """Array-chunk style graph: partition tasks -> tree reduction."""
    dsk = {
        ("x", i): (np.arange, 5) for i in range(4)
    }
    dsk["total"] = (lambda parts: float(np.sum(parts)),
                    [("x", i) for i in range(4)])
    assert ray_dask_get(dsk, "total") == 40.0


# ---------------------------------------------------------------------------
# spark
# ---------------------------------------------------------------------------


class _FakeRDD:
    def __init__(self, seq, n):
        self._parts = [[x] for x in seq]

    def foreachPartition(self, fn):
        threads = [threading.Thread(target=fn, args=(iter(p),),
                                    daemon=True) for p in self._parts]
        for t in threads:
            t.start()
        for t in threads:
            t.join()


class _FakeSparkContext:
    def parallelize(self, seq, n):
        return _FakeRDD(seq, n)


class _FakeSparkSession:
    sparkContext = _FakeSparkContext()


_SPARK_DRIVER = r"""
import threading
import ray_tpu
from ray_tpu.util import spark as ray_spark

class _FakeRDD:
    def __init__(self, seq, n):
        self._parts = [[x] for x in seq]
    def foreachPartition(self, fn):
        ts = [threading.Thread(target=fn, args=(iter(p),), daemon=True)
              for p in self._parts]
        [t.start() for t in ts]
        [t.join() for t in ts]

class _FakeSparkContext:
    def parallelize(self, seq, n):
        return _FakeRDD(seq, n)

class _FakeSparkSession:
    sparkContext = _FakeSparkContext()

import sys
shared = sys.argv[1]
address = ray_spark.setup_ray_cluster(
    _FakeSparkSession(), num_worker_nodes=2, num_cpus_per_node=1,
    shared_dir=shared)
assert address
from ray_tpu._private.worker import get_client
nodes = get_client().control("list_nodes")
spark_nodes = [n for n in nodes
               if str(n.get("node_id", "")).startswith("spark_")]
assert len(spark_nodes) == 2, nodes

@ray_tpu.remote
def where():
    import os
    return os.getpid()

# the head has 0 CPUs: work MUST run on the spark worker nodes
pids = set(ray_tpu.get([where.remote() for _ in range(4)], timeout=120))
assert pids
ray_spark.shutdown_ray_cluster()
print("SPARK_OK")
"""


def test_setup_ray_cluster_on_spark(tmp_path):
    """Head on the driver + one worker node per 'executor' (local
    threads standing in for Spark tasks); tasks run on the worker
    nodes; shutdown releases the executors. Runs in a subprocess so the
    shim's own ray_tpu.init doesn't collide with the shared session."""
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "-c", _SPARK_DRIVER, str(tmp_path)],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    assert "SPARK_OK" in out.stdout
