"""URI-keyed storage seam (reference: air/_internal/remote_storage.py
upload_to_uri/download_from_uri, tune/syncer.py experiment sync,
external_storage.py S3 spill): Train checkpoints, Tune experiment
state, and object spilling all run against the mem:// FAKE remote
backend — same code path a registered gs:// backend would take, with
no shared-filesystem shortcuts (bytes only move through backend verbs).
"""

import os
import shutil
import subprocess
import sys
import uuid

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import (
    Checkpoint,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.config import CheckpointConfig
from ray_tpu.util import storage

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bucket() -> str:
    return f"mem://bucket-{uuid.uuid4().hex[:8]}"


# -- backend verbs -----------------------------------------------------------

def test_backend_roundtrip():
    root = _bucket()
    storage.write_bytes(storage.uri_join(root, "a/b.bin"), b"payload")
    assert storage.exists(storage.uri_join(root, "a/b.bin"))
    assert storage.read_bytes(storage.uri_join(root, "a/b.bin")) == \
        b"payload"
    assert storage.list_prefix(root) == ["a/b.bin"]
    storage.delete(storage.uri_join(root, "a"))
    assert not storage.exists(storage.uri_join(root, "a/b.bin"))


def test_dir_transfer_and_syncer(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "x.txt").write_text("one")
    (src / "sub" / "y.txt").write_text("two")
    root = _bucket()
    syncer = storage.DirSyncer(str(src), root)
    assert syncer.sync_up() == 2
    assert syncer.sync_up() == 0          # incremental: nothing changed
    (src / "x.txt").write_text("one-changed")
    assert syncer.sync_up() == 1
    dest = tmp_path / "dest"
    storage.download_dir(root, str(dest))
    assert (dest / "x.txt").read_text() == "one-changed"
    assert (dest / "sub" / "y.txt").read_text() == "two"


def test_unknown_scheme_errors():
    with pytest.raises(ValueError, match="no storage backend"):
        storage.get_backend("gs://nope/x")


def test_checkpoint_to_from_uri(tmp_path):
    ck = Checkpoint.from_dict({"step": 7, "w": np.arange(5.0)})
    uri = storage.uri_join(_bucket(), "ckpt")
    ck.to_uri(uri)
    # staging dir from a previous life must not mask fresh downloads
    shutil.rmtree(storage.staging_dir(uri), ignore_errors=True)
    back = Checkpoint.from_uri(uri).to_dict()
    assert back["step"] == 7
    assert np.array_equal(back["w"], np.arange(5.0))


# -- Train checkpoints against the fake remote -------------------------------

def _train_loop(config):
    from ray_tpu.train import Checkpoint as Ck, session
    for i in range(3):
        session.report(
            {"step": i},
            checkpoint=Ck.from_dict({"step": i, "w": np.ones(3) * i}))


def test_train_checkpoints_to_uri(ray_session):
    root = _bucket()
    trainer = JaxTrainer(
        _train_loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="run", storage_path=root,
            checkpoint_config=CheckpointConfig(num_to_keep=2)))
    result = trainer.fit()
    assert result.error is None
    run_uri = storage.uri_join(root, "run")
    files = storage.list_prefix(run_uri)
    names = {f.split("/")[0] for f in files}
    # 3 checkpoints, keep-top-2: the first was deleted REMOTELY too
    assert names == {"checkpoint_000002", "checkpoint_000003"}, files
    last = Checkpoint.from_uri(
        storage.uri_join(run_uri, "checkpoint_000003"))
    assert last.to_dict()["step"] == 2


# -- Tune experiment state + restore against the fake remote -----------------

def _trial_fn(config):
    from ray_tpu.tune.trainable import report
    from ray_tpu.train import Checkpoint as Ck
    report({"score": config["x"] * 2},
           checkpoint=Ck.from_dict({"x": config["x"]}))


def test_tune_experiment_uri_and_restore(ray_session):
    from ray_tpu import tune
    from ray_tpu.tune.tuner import Tuner, TuneConfig

    root = _bucket()
    tuner = Tuner(
        _trial_fn,
        param_space={"x": tune.grid_search([1, 2, 3])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(
            name="exp", storage_path=root,
            checkpoint_config=CheckpointConfig(num_to_keep=1)))
    grid = tuner.fit()
    assert len(grid) == 3
    assert grid.get_best_result("score").metrics["score"] == 6

    exp_uri = storage.uri_join(root, "exp")
    files = storage.list_prefix(exp_uri)
    assert "experiment_state.json" in files, files
    assert any(f.startswith("trial_") and "checkpoint_" in f
               for f in files), files

    # restore from the URI into a WIPED staging dir: everything must come
    # back through the backend
    shutil.rmtree(storage.staging_dir(exp_uri), ignore_errors=True)
    restored = Tuner.restore(exp_uri, _trial_fn).fit()
    assert len(restored) == 3
    best = restored.get_best_result("score")
    assert best.metrics["score"] == 6
    assert best.checkpoint is not None
    assert best.checkpoint.to_dict()["x"] == 3


# -- spill to URI ------------------------------------------------------------

_SPILL_SCRIPT = r"""
import numpy as np
import ray_tpu
from ray_tpu.util import storage

ray_tpu.init(num_cpus=2)
# tiny arena (set via env) forces puts to overflow into spill storage
refs = [ray_tpu.put(np.ones(300_000, np.float32) * i) for i in range(8)]
for i, r in enumerate(refs):
    arr = ray_tpu.get(r)
    assert arr[0] == i and arr.shape == (300_000,)
import os
root = os.environ["RAY_TPU_OBJECT_SPILL_ROOT"]
assert storage.list_prefix(root), "nothing landed in spill storage"
ray_tpu.shutdown()
print("SPILL-URI-OK")
"""


def test_spill_to_uri():
    env = dict(os.environ)
    env["RAY_TPU_OBJECT_SPILL_ROOT"] = _bucket() + "/spill"
    env["RAY_TPU_OBJECT_STORE_BYTES"] = str(512 * 1024)   # 0.5 MiB arena
    r = subprocess.run([sys.executable, "-c", _SPILL_SCRIPT], cwd=REPO,
                       env=env, capture_output=True, text=True,
                       timeout=180)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "SPILL-URI-OK" in r.stdout


# -- commit-marker uploads (crash-safe URI checkpoints) ----------------------

def _src_dir(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.bin").write_bytes(b"alpha" * 100)
    (src / "sub" / "b.bin").write_bytes(b"beta" * 100)
    return src


def test_committed_upload_roundtrip(tmp_path):
    root = _bucket()
    storage.upload_dir_committed(str(_src_dir(tmp_path)), root)
    assert storage.is_committed(root)
    dest = tmp_path / "dest"
    storage.download_dir_committed(root, str(dest))
    assert (dest / "a.bin").read_bytes() == b"alpha" * 100
    assert (dest / "sub" / "b.bin").read_bytes() == b"beta" * 100


def test_from_uri_on_missing_prefix_raises():
    from ray_tpu.train.checkpoint import CheckpointError
    with pytest.raises(CheckpointError, match="no restorable checkpoint"):
        Checkpoint.from_uri(storage.uri_join(_bucket(), "ckpt"))


def test_markerless_upload_refused(tmp_path):
    """Objects without a commit marker (a writer that died before the
    marker write) must not restore as if they were a checkpoint."""
    from ray_tpu.train.checkpoint import CheckpointError
    root = _bucket()
    storage.upload_dir(str(_src_dir(tmp_path)), root)   # no marker
    assert not storage.is_committed(root)
    with pytest.raises(storage.UncommittedError, match="no commit marker"):
        storage.download_dir_committed(root, str(tmp_path / "dest"))
    with pytest.raises(CheckpointError, match="no restorable checkpoint"):
        Checkpoint.from_uri(root)


def test_interrupted_committed_upload_refused(tmp_path, monkeypatch):
    """Kill the uploader mid-stream: some objects land, the marker never
    does, and restore refuses the partial prefix."""
    root = _bucket()
    backend, _ = storage.get_backend(root)
    real = backend.write_bytes
    calls = {"n": 0}

    def dying(path, data):
        calls["n"] += 1
        if calls["n"] >= 2:
            raise OSError("connection reset by peer")
        real(path, data)

    monkeypatch.setattr(backend, "write_bytes", dying)
    with pytest.raises(OSError):
        storage.upload_dir_committed(str(_src_dir(tmp_path)), root)
    monkeypatch.undo()
    assert storage.list_prefix(root)            # partial bytes DID land
    assert not storage.is_committed(root)
    with pytest.raises(storage.UncommittedError,
                       match="no commit marker"):
        storage.download_dir_committed(root, str(tmp_path / "dest"))


def test_committed_download_checksum_mismatch(tmp_path):
    root = _bucket()
    storage.upload_dir_committed(str(_src_dir(tmp_path)), root)
    storage.write_bytes(storage.uri_join(root, "a.bin"), b"tampered")
    with pytest.raises(storage.UncommittedError,
                       match="checksum mismatch"):
        storage.download_dir_committed(root, str(tmp_path / "dest"))


def test_checkpoint_to_directory_crash_safe(tmp_path):
    """A to_directory that dies mid-write leaves NO destination dir (and
    no temp litter); a later successful write fully replaces any previous
    content."""
    dest = tmp_path / "ck"
    bad = Checkpoint.from_dict({"f": lambda: None})     # unpicklable
    with pytest.raises(Exception):
        bad.to_directory(str(dest))
    assert not dest.exists()
    assert not any(p.name.startswith(".ck.tmp") for p in tmp_path.iterdir())

    Checkpoint.from_dict({"v": 1}).to_directory(str(dest))
    assert Checkpoint.from_directory(str(dest)).to_dict()["v"] == 1
    Checkpoint.from_dict({"w": 2}).to_directory(str(dest))
    back = Checkpoint.from_directory(str(dest)).to_dict()
    assert back["w"] == 2 and "v" not in back   # old content fully gone
