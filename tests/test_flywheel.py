"""RL flywheel tests: TokenEvent metadata on every emitted token,
in-place donated weight hot-swap (post-swap greedy outputs bitwise-match
a fresh engine built on the new params — incl. shared-prefix/COW and
spec-decode on — with the trace counters pinned unchanged), logprob
parity between the engine's KV-cache paths and a full-forward recompute
(`gpt.completion_logprobs`, f32 1e-4), staleness tagging
(`params_version` on every trajectory token), the pluggable generation
backend (default PythonEnvRunner path byte-identical to before),
TrainLoop's publisher hook, and the end-to-end flywheel: a tiny GPT
trained on engine-generated rollouts with mid-stream hot-swaps, zero
recompiles, and a measurably rising reward. Runs under
JAX_PLATFORMS=cpu (conftest forces it)."""

import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt
from ray_tpu.rl.flywheel import FlywheelLoop, motif_reward
from ray_tpu.rl.sampler import (MASK, PARAMS_VERSION, START, TOKENS,
                                EngineSampler, TokenEnvRunner)
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.rollout import PythonEnvRunner, make_env_runner
from ray_tpu.serve.engine import InferenceEngine, TokenEvent
from ray_tpu.train.loop import TrainLoop


def tiny_cfg(**kw):
    return gpt.GPTConfig(**{**dict(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype="float32"), **kw})


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    params2 = gpt.init_params(jax.random.PRNGKey(1), cfg)
    return cfg, params, params2


def make_engine(cfg, params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("block_size", 8)
    # The engine donates its param buffers on update_params, so it gets
    # its own copy — the module-scoped fixture params stay valid.
    return InferenceEngine(jax.tree.map(jnp.copy, params), cfg, **kw)


def ints(events):
    return [int(t) for t in events]


# ---------------------------------------------------------------------------
# TokenEvent
# ---------------------------------------------------------------------------

class TestTokenEvent:
    def test_is_an_int(self):
        ev = TokenEvent(7, -1.5, 3)
        assert ev == 7 and ev + 1 == 8 and isinstance(ev, int)
        assert ev.logprob == -1.5 and ev.params_version == 3
        assert [ev] == [7]            # list equality, as old tests use

    def test_pickle_keeps_metadata(self):
        ev = pickle.loads(pickle.dumps(TokenEvent(9, -0.25, 2)))
        assert ev == 9 and ev.logprob == -0.25 and ev.params_version == 2

    def test_defaults(self):
        ev = TokenEvent(4)
        assert ev.logprob == 0.0 and ev.params_version == 0


# ---------------------------------------------------------------------------
# weight hot-swap
# ---------------------------------------------------------------------------

class TestHotSwap:
    def test_swap_matches_fresh_engine_greedy(self, setup):
        cfg, params, params2 = setup
        eng = make_engine(cfg, params)
        prompts = [[1, 2, 3, 4], [5, 6, 7, 8, 9]]
        for p in prompts:
            eng.generate(p, max_new_tokens=6)
        assert eng.decode_traces == 1
        traces = (eng.decode_traces, eng.prefill_traces)
        v = eng.update_params(jax.tree.map(jnp.copy, params2))
        assert v == 1
        swapped = [eng.generate(p, max_new_tokens=6) for p in prompts]
        # no recompile: same executables, same trace counters
        assert (eng.decode_traces, eng.prefill_traces) == traces
        fresh = make_engine(cfg, params2)
        for got, p in zip(swapped, prompts):
            want = fresh.generate(p, max_new_tokens=6)
            assert ints(got) == ints(want)
            np.testing.assert_allclose(
                [t.logprob for t in got], [t.logprob for t in want],
                atol=1e-5)
        st = eng.stats()
        assert st["params_version"] == 1 and st["swaps"] == 1
        assert st["weight_swap_ms"] > 0.0
        assert all(t.params_version == 1 for t in swapped[0])

    def test_swap_with_shared_prefix_cow(self, setup):
        """Prefix-cache state must not leak across a swap: requests
        sharing a radix-cached prefix (with a mid-block COW split)
        re-prefill after the flush and still match a fresh engine."""
        cfg, params, params2 = setup
        eng = make_engine(cfg, params, slots=2)
        shared = list(range(1, 13))       # 12 tokens: 1.5 blocks -> COW
        a, b = shared + [20, 21], shared + [30, 31]
        eng.generate(a, max_new_tokens=4)
        eng.generate(b, max_new_tokens=4)   # COW hit on the shared part
        assert eng.stats()["prefix_hit_tokens"] > 0
        eng.update_params(jax.tree.map(jnp.copy, params2))
        assert eng.stats()["cached_prefix_blocks"] == 0  # flushed
        fresh = make_engine(cfg, params2, slots=2)
        for p in (a, b, a):   # third run re-shares post-swap prefixes
            assert ints(eng.generate(p, max_new_tokens=4)) == \
                ints(fresh.generate(p, max_new_tokens=4))
        eng.check_invariants()

    def test_swap_with_spec_decode_on(self, setup):
        cfg, params, params2 = setup
        rng = np.random.default_rng(0)
        motif = rng.integers(1, cfg.vocab_size, 4)
        prompt = np.tile(motif, 4).astype(np.int32)
        eng = make_engine(cfg, params, spec="ngram", spec_k=3)
        eng.generate(prompt, max_new_tokens=8)
        assert eng.verify_traces == 1
        traces = (eng.decode_traces, eng.verify_traces,
                  eng.prefill_traces)
        eng.update_params(jax.tree.map(jnp.copy, params2))
        got = eng.generate(prompt, max_new_tokens=8)
        assert (eng.decode_traces, eng.verify_traces,
                eng.prefill_traces) == traces
        fresh = make_engine(cfg, params2, spec="ngram", spec_k=3)
        assert ints(got) == ints(fresh.generate(prompt,
                                                max_new_tokens=8))

    def test_swap_draft_params(self, setup):
        cfg, params, params2 = setup
        dcfg = tiny_cfg(n_layers=1)
        d1 = gpt.init_params(jax.random.PRNGKey(7), dcfg)
        d2 = gpt.init_params(jax.random.PRNGKey(8), dcfg)
        eng = make_engine(cfg, params, spec="draft", spec_k=2,
                          draft_cfg=dcfg,
                          draft_params=jax.tree.map(jnp.copy, d1))
        prompt = [1, 2, 3, 4, 5, 6]
        eng.generate(prompt, max_new_tokens=6)
        traces = (eng.decode_traces, eng.verify_traces,
                  eng.draft_traces)
        eng.update_params(jax.tree.map(jnp.copy, params2),
                          draft_params=jax.tree.map(jnp.copy, d2))
        got = eng.generate(prompt, max_new_tokens=6)
        assert (eng.decode_traces, eng.verify_traces,
                eng.draft_traces) == traces
        fresh = make_engine(cfg, params2, spec="draft", spec_k=2,
                            draft_cfg=dcfg, draft_params=d2)
        assert ints(got) == ints(fresh.generate(prompt,
                                                max_new_tokens=6))

    def test_swap_validation(self, setup):
        cfg, params, _ = setup
        eng = make_engine(cfg, params)
        bad_shape = jax.tree.map(lambda a: a, params)
        bad_shape = dict(bad_shape)
        bad_shape["embed"] = jnp.zeros((3, 3), jnp.float32)
        with pytest.raises(ValueError, match="leaf mismatch"):
            eng.update_params(bad_shape)
        with pytest.raises(ValueError, match="structure"):
            eng.update_params({"nope": jnp.zeros(())})
        with pytest.raises(ValueError, match="no draft model"):
            eng.update_params(
                jax.tree.map(jnp.copy, params),
                draft_params=jax.tree.map(jnp.copy, params))
        assert eng.stats()["swaps"] == 0     # failed swaps don't count

    def test_mid_prefill_swap_keeps_mixed_kv_out_of_tree(self, setup):
        """A prompt whose chunked prefill spans a swap computed K/V
        under BOTH weight versions — it must finish (tagged with the
        new version) but never publish its blocks to the prefix cache."""
        cfg, params, params2 = setup
        eng = make_engine(cfg, params, prefill_chunk=8)
        # park a decoding sequence so the scheduler runs ONE prefill
        # chunk per tick for the next admission
        eng.submit([1, 2, 3], max_new_tokens=8)
        eng.step()
        assert any(s.phase == "decode" for s in eng._slots)
        rid = eng.submit(np.arange(1, 25, dtype=np.int32),
                         max_new_tokens=3)
        eng.step()                      # admit + first chunk only
        eng.update_params(jax.tree.map(jnp.copy, params2))
        eng.run_until_idle()
        out = list(eng._out[rid])
        assert len(out) == 3
        # final prefill chunk + decodes all ran post-swap -> tagged 1
        assert all(t.params_version == 1 for t in out)
        assert eng._tree.n_blocks() == 0    # mixed-KV prefix not cached
        eng.check_invariants()

    def test_params_version_survives_reset_stats(self, setup):
        cfg, params, params2 = setup
        eng = make_engine(cfg, params)
        eng.generate([1, 2, 3], max_new_tokens=2)
        eng.update_params(jax.tree.map(jnp.copy, params2))
        eng.generate([1, 2, 3], max_new_tokens=2)
        assert eng.stats()["swaps"] == 1
        eng.reset_stats()
        st = eng.stats()
        assert st["params_version"] == 1      # identity: never rewinds
        assert st["swaps"] == 0 and st["weight_swap_ms"] == 0.0


# ---------------------------------------------------------------------------
# logprob parity: engine KV-cache paths vs full-forward recompute
# ---------------------------------------------------------------------------

def recompute_logprobs(params, cfg, prompt, completion):
    full = np.concatenate([np.asarray(prompt, np.int32),
                           np.asarray(completion, np.int32)])[None]
    lp = gpt.completion_logprobs(params, jnp.asarray(full),
                                 jnp.asarray([len(prompt)], jnp.int32),
                                 len(completion), cfg)
    return np.asarray(lp)[0]


class TestLogprobParity:
    @pytest.mark.parametrize("temperature", [0.0, 0.7])
    def test_decode_path(self, setup, temperature):
        """Emitted logprobs are the NATURAL log pi regardless of the
        sampling temperature, matching a full forward to f32 1e-4."""
        cfg, params, _ = setup
        eng = make_engine(cfg, params)
        prompt = [3, 1, 4, 1, 5]
        out = eng.generate(prompt, max_new_tokens=6,
                           temperature=temperature)
        want = recompute_logprobs(params, cfg, prompt, ints(out))
        np.testing.assert_allclose([t.logprob for t in out], want,
                                   atol=1e-4)

    def test_spec_verify_path(self, setup):
        cfg, params, _ = setup
        rng = np.random.default_rng(3)
        motif = rng.integers(1, cfg.vocab_size, 3)
        prompt = np.tile(motif, 4).astype(np.int32)
        eng = make_engine(cfg, params, spec="ngram", spec_k=3)
        out = eng.generate(prompt, max_new_tokens=8)
        assert eng.stats()["spec_steps"] > 0   # speculation really ran
        want = recompute_logprobs(params, cfg, prompt, ints(out))
        np.testing.assert_allclose([t.logprob for t in out], want,
                                   atol=1e-4)

    def test_chunked_prefill_first_token(self, setup):
        """The first generated token's logprob comes off the prefill
        path (parked through chunking) — same contract."""
        cfg, params, _ = setup
        eng = make_engine(cfg, params, prefill_chunk=8)
        prompt = list(range(1, 20))
        out = eng.generate(prompt, max_new_tokens=4)
        want = recompute_logprobs(params, cfg, prompt, ints(out))
        np.testing.assert_allclose([t.logprob for t in out], want,
                                   atol=1e-4)


# ---------------------------------------------------------------------------
# EngineSampler / trajectory batches
# ---------------------------------------------------------------------------

class _TokenEnv:
    """Token-level env for the runner contract: fixed prompt family +
    motif-fraction reward."""
    eos_id = None

    def __init__(self, motif=7):
        self._reward = motif_reward(motif)

    def make_prompt(self, rng):
        return [1, 2, int(rng.integers(3, 9))]

    def reward(self, prompt, completion):
        return self._reward(prompt, completion)


class TestEngineSampler:
    def test_batch_contract(self, setup):
        cfg, params, _ = setup
        eng = make_engine(cfg, params, slots=4)
        sampler = EngineSampler(eng, max_new_tokens=5, temperature=1.0,
                                pad_to=16)
        prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]
        batch = sampler.rollout(prompts, motif_reward(7))
        B, W = len(prompts), 5
        assert batch[TOKENS].shape == (B, 16)
        assert batch[sb.ACTIONS].shape == (B, W)
        assert batch[sb.ACTION_LOGP].shape == (B, W)
        assert batch[MASK].sum() == B * W          # no eos: full width
        for b, p in enumerate(prompts):
            assert batch[START][b] == len(p)
            assert list(batch[TOKENS][b, :len(p)]) == p
            np.testing.assert_array_equal(
                batch[TOKENS][b, len(p):len(p) + W],
                batch[sb.ACTIONS][b])
        assert batch[sb.DONES].all()
        assert (batch[sb.ACTION_LOGP][batch[MASK] > 0] < 0).all()
        assert sampler.last_rollout_tok_s > 0

    def test_staleness_tags_on_every_trajectory(self, setup):
        cfg, params, params2 = setup
        eng = make_engine(cfg, params, slots=2)
        sampler = EngineSampler(eng, max_new_tokens=3, pad_to=16)
        b0 = sampler.rollout([[1, 2, 3], [4, 5, 6]])
        assert (b0[PARAMS_VERSION][b0[MASK] > 0] == 0).all()
        eng.update_params(jax.tree.map(jnp.copy, params2))
        b1 = sampler.rollout([[1, 2, 3], [4, 5, 6]])
        assert (b1[PARAMS_VERSION][b1[MASK] > 0] == 1).all()

    def test_engine_backend_runner(self, setup):
        cfg, params, _ = setup
        eng = make_engine(cfg, params, slots=2)
        runner = make_env_runner(
            _TokenEnv(), module=None, rollout_length=3, seed=0,
            backend="engine",
            backend_kwargs=dict(engine=eng, max_new_tokens=4,
                                pad_to=16, publish=False))
        assert isinstance(runner, TokenEnvRunner)
        batch, last_v = runner.sample(None)
        assert len(batch) == 3 and last_v.shape == (3,)
        stats = runner.pop_episode_stats()
        assert stats["episodes_this_iter"] == 3
        assert np.isfinite(stats["episode_reward_mean"])

    def test_unknown_backend_raises(self):
        with pytest.raises(ValueError, match="unknown generation"):
            make_env_runner(object(), None, 1, backend="nope")


# ---------------------------------------------------------------------------
# default rollout path regression (pluggable backend satellite)
# ---------------------------------------------------------------------------

class _CountEnv:
    """Deterministic 4-step-episode gym-style env."""

    def __init__(self):
        self._t = 0

    def reset(self):
        self._t = 0
        return np.zeros(2, np.float32)

    def step(self, action):
        self._t += 1
        obs = np.full(2, self._t, np.float32)
        return obs, float(self._t), self._t % 4 == 0, {}


class _TinyModule:
    def compute_actions(self, params, obs, key):
        a = jnp.sum(obs, axis=-1).astype(jnp.int32) % 3
        logp = -jnp.ones(obs.shape[0])
        v = jnp.sum(obs, axis=-1)
        return a, logp, v


def test_default_path_byte_identical():
    """make_env_runner(backend=None) IS the historical PythonEnvRunner
    construction — same class, same seeds, byte-identical batches."""
    mod = _TinyModule()
    direct = PythonEnvRunner(_CountEnv(), mod, 6, seed=3)
    via = make_env_runner(_CountEnv(), mod, 6, seed=3)
    assert type(via) is PythonEnvRunner
    b_direct, v_direct = direct.sample({})
    b_via, v_via = via.sample({})
    assert set(b_direct.keys()) == set(b_via.keys())
    for k in b_direct:
        np.testing.assert_array_equal(b_direct[k], b_via[k])
    assert v_direct == v_via
    assert direct.pop_episode_stats() == via.pop_episode_stats()


# ---------------------------------------------------------------------------
# TrainLoop publisher hook
# ---------------------------------------------------------------------------

def test_trainloop_publisher_hook():
    calls = []

    def step(state, batch):
        return state + 1, {"step": state}

    loop = TrainLoop(jax.jit(step),
                     publisher=lambda st, n: calls.append((int(st), n)))
    state, _ = loop.run(jnp.int32(0), iter([jnp.int32(0)] * 4),
                        num_steps=4)
    # called after every dispatch with the POST-step state + step count
    assert calls == [(1, 1), (2, 2), (3, 3), (4, 4)]
    assert int(state) == 4
    loop.publisher = None                     # mutable, like checkpointer
    state, _ = loop.run(state, iter([jnp.int32(0)] * 2), num_steps=99)
    assert calls[-1] == (4, 4)


# ---------------------------------------------------------------------------
# end-to-end flywheel
# ---------------------------------------------------------------------------

def _flywheel(iterations, **kw):
    cfg = tiny_cfg(vocab_size=32)
    kw.setdefault("engine_kwargs", dict(
        slots=4, max_len=32, prefill_buckets=(8,), block_size=8))
    fly = FlywheelLoop(
        cfg, lambda rng: [1, 2, int(rng.integers(3, 9))],
        motif_reward(7), lr=5e-2, prompts_per_iter=8, max_new_tokens=5,
        temperature=1.0, pad_to=16, seed=0, **kw)
    state, metrics = fly.run(iterations)
    return fly, state, metrics


def test_flywheel_e2e_smoke():
    """Tier-1 acceptance: engine-generated rollouts train the policy,
    weights hot-swap mid-stream with ZERO recompiles, post-swap greedy
    tokens bitwise-match a fresh engine on the final params, and the
    reward measurably rises."""
    replica_like = make_engine(
        tiny_cfg(vocab_size=32),
        gpt.init_params(jax.random.PRNGKey(0), tiny_cfg(vocab_size=32)))
    fly, state, metrics = _flywheel(12, publish_to=[replica_like])
    # zero recompiles across 12 hot-swaps
    assert fly.engine.decode_traces == 1
    assert fly.engine.stats()["swaps"] == 12
    assert fly.engine.params_version == 12
    assert replica_like.params_version == 12      # publish fan-out
    # the objective measurably improves
    rw = [h["reward_mean"] for h in fly.history]
    assert np.mean(rw[-4:]) > np.mean(rw[:4]) + 0.15, rw
    # staleness is tagged and bounded (colocated loop: fully on-policy)
    assert all(h["staleness"] >= 0 for h in fly.history)
    assert len(metrics) == 12 and np.isfinite(metrics[-1]["loss"])
    # post-swap greedy bitwise-matches a fresh engine on the new params
    fresh = InferenceEngine(
        jax.tree.map(jnp.copy, state.params), fly.cfg,
        slots=4, max_len=32, prefill_buckets=(8,), block_size=8)
    for prompt in ([1, 2, 3], [1, 2, 8]):
        got = fly.engine.generate(prompt, max_new_tokens=5)
        want = fresh.generate(prompt, max_new_tokens=5)
        assert ints(got) == ints(want)
        np.testing.assert_allclose([t.logprob for t in got],
                                   [t.logprob for t in want], atol=1e-5)
    assert fly.engine.decode_traces == 1          # still exactly once


@pytest.mark.slow
def test_flywheel_e2e_full():
    """Longer run drives the motif reward to (near-)saturation, and the
    REINFORCE (clip=None) objective also learns."""
    fly, _, _ = _flywheel(40)
    rw = [h["reward_mean"] for h in fly.history]
    assert np.mean(rw[-5:]) > 0.8, rw
    fly2, _, _ = _flywheel(30, clip=None)
    rw2 = [h["reward_mean"] for h in fly2.history]
    assert np.mean(rw2[-5:]) > np.mean(rw2[:5]) + 0.2, rw2
