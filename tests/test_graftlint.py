"""graftlint: the AST invariant checker (ray_tpu/tools/graftlint/).

Pins the tentpole contracts: the repo lints clean against the
checked-in baseline (tier-1 — the baseline can never silently regress),
every rule is proven live on a known-bad corpus file and silent on its
clean twin, waivers require reasons, the CLI honors its exit-code and
JSON schema contract, the RetraceSentinel's registered watches agree
with the R003 compile-once registry, and the two R004 bug fixes (engine
weight placement, controller shutdown kills) actually release their
locks during the blocking work.
"""

import ast
import json
import os
import subprocess
import sys
import threading

import pytest

from ray_tpu.tools.graftlint import astutil, core, scopes
from ray_tpu.tools.graftlint.rules import ALL_RULES

REPO = core.REPO_ROOT
CORPUS = os.path.join(REPO, "tests", "graftlint_corpus")
BASELINE = os.path.join(REPO, "ray_tpu", "tools", "graftlint",
                        "baseline.json")


def _lint(path, **kw):
    return core.lint_file(path, **kw)


# ---------------------------------------------------------------------------
# tier-1: the repo is clean and the waiver set matches the baseline
# ---------------------------------------------------------------------------

def test_repo_is_clean():
    findings, nfiles = core.lint_paths([os.path.join(REPO, "ray_tpu")])
    assert nfiles > 100
    active = [f for f in findings if not f.waived]
    assert not active, "graftlint found active findings:\n" + \
        "\n".join(str(f) for f in active)
    waived = sorted({(f.file, f.rule, f.waiver_reason)
                     for f in findings if f.waived})
    with open(BASELINE) as fh:
        baseline = sorted((w["file"], w["rule"], w["reason"])
                          for w in json.load(fh)["waived"])
    assert waived == baseline, (
        "waiver set drifted from baseline.json — if the new waiver is "
        "deliberate, regenerate the baseline and justify it in review")


def test_every_baseline_waiver_has_reason():
    with open(BASELINE) as fh:
        for w in json.load(fh)["waived"]:
            assert w["reason"].strip(), w


# ---------------------------------------------------------------------------
# corpus: every rule fires on bad, stays silent on clean, and dies
# when disabled (proven live, not vacuously clean)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rule", sorted(ALL_RULES))
def test_rule_live_on_corpus(rule):
    n = rule[1:].lstrip("0")
    bad = os.path.join(CORPUS, f"r{int(n):03d}_bad.py")
    clean = os.path.join(CORPUS, f"r{int(n):03d}_clean.py")
    hits = [f for f in _lint(bad) if f.rule == rule]
    assert hits, f"{rule} found nothing in its known-bad corpus file"
    assert all(not f.waived for f in hits)
    disabled = [f for f in _lint(bad, disable={rule}) if f.rule == rule]
    assert not disabled, f"{rule} fired while disabled"
    assert not [f for f in _lint(clean) if f.rule == rule], \
        f"{rule} false-positived on its known-clean corpus file"


def test_r004_detects_lock_order_cycle():
    bad = os.path.join(CORPUS, "r004_bad.py")
    msgs = [f.message for f in _lint(bad) if f.rule == "R004"]
    assert any("cycle" in m for m in msgs)


def test_r005_reports_both_directions():
    bad = os.path.join(CORPUS, "r005_bad.py")
    msgs = "\n".join(f.message for f in _lint(bad) if f.rule == "R005")
    assert "emitted" in msgs      # returned but undocumented
    assert "retired" in msgs      # documented but not returned


# ---------------------------------------------------------------------------
# waiver parsing
# ---------------------------------------------------------------------------

def _write(tmp_path, body):
    p = tmp_path / "snippet.py"
    p.write_text(body)
    return str(p)


WAIVABLE = """import jax

@jax.jit
def f(x):
    print(x){waiver}
    return x
"""


def test_waiver_same_line(tmp_path):
    path = _write(tmp_path, WAIVABLE.format(
        waiver="  # graftlint: disable=R001 trace-time debug aid"))
    (f,) = _lint(path)
    assert f.rule == "R001" and f.waived
    assert f.waiver_reason == "trace-time debug aid"


def test_waiver_next_line(tmp_path):
    body = WAIVABLE.format(waiver="").replace(
        "    print(x)",
        "    # graftlint: disable-next-line=R001 warmup print only\n"
        "    print(x)")
    (f,) = _lint(_write(tmp_path, body))
    assert f.waived and f.waiver_reason == "warmup print only"


def test_waiver_without_reason_is_rejected(tmp_path):
    path = _write(tmp_path, WAIVABLE.format(
        waiver="  # graftlint: disable=R001"))
    findings = _lint(path)
    rules = sorted(f.rule for f in findings)
    assert rules == ["R001", "W001"]      # finding stays active...
    assert all(not f.waived for f in findings)


def test_waiver_wrong_rule_does_not_apply(tmp_path):
    path = _write(tmp_path, WAIVABLE.format(
        waiver="  # graftlint: disable=R005 mismatched rule id"))
    (f,) = _lint(path)
    assert f.rule == "R001" and not f.waived


def test_multi_rule_waiver(tmp_path):
    path = _write(tmp_path, WAIVABLE.format(
        waiver="  # graftlint: disable=R001,R003 shared justification"))
    (f,) = _lint(path)
    assert f.waived and f.waiver_reason == "shared justification"


# ---------------------------------------------------------------------------
# CLI: exit codes + JSON schema
# ---------------------------------------------------------------------------

def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.tools.graftlint", *args],
        capture_output=True, text=True, cwd=REPO)


def test_cli_exit_1_on_findings_and_json_schema():
    out = _cli(os.path.join(CORPUS, "r001_bad.py"), "--json")
    assert out.returncode == 1, out.stderr
    data = json.loads(out.stdout)
    assert data["version"] == 1
    assert data["files_scanned"] == 1
    assert set(data["counts"]) == {"total", "waived", "active"}
    assert data["counts"]["active"] > 0
    for f in data["findings"]:
        assert set(f) == {"rule", "file", "line", "col", "message",
                          "waived", "waiver_reason"}
        assert f["rule"] in set(ALL_RULES) | {"W001", "E999"}


def test_cli_exit_0_on_clean():
    out = _cli(os.path.join(CORPUS, "r001_clean.py"))
    assert out.returncode == 0, out.stdout + out.stderr


def test_cli_exit_2_on_bad_path_and_unknown_rule():
    assert _cli("definitely/not/a/path.py").returncode == 2
    assert _cli(os.path.join(CORPUS, "r001_clean.py"),
                "--select", "R999").returncode == 2


def test_cli_select_limits_rules():
    out = _cli(os.path.join(CORPUS, "r001_bad.py"), "--json",
               "--select", "R002")
    assert out.returncode == 0    # only R001 findings live in that file
    assert json.loads(out.stdout)["counts"]["total"] == 0


# ---------------------------------------------------------------------------
# sentinel <-> registry agreement (the ISSUE's bugfix satellite)
# ---------------------------------------------------------------------------

def _registered_watch_names():
    """Watch names armed with registered=True, read from the source of
    every file in the compile-once registry."""
    names = set()
    for rel in scopes.COMPILE_ONCE_JITS:
        with open(os.path.join(REPO, rel)) as fh:
            tree = ast.parse(fh.read())
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "watch"):
                continue
            if not any(k.arg == "registered"
                       and isinstance(k.value, ast.Constant)
                       and k.value.value is True
                       for k in node.keywords):
                continue
            assert node.args and isinstance(node.args[0], ast.Constant)
            names.add(node.args[0].value)
    return names


def test_sentinel_watches_match_registry():
    armed = _registered_watch_names()
    assert armed == set(scopes.RETRACE_WATCHES), (
        "RetraceSentinel registered watches and graftlint's "
        "COMPILE_ONCE_JITS inventory drifted apart: "
        f"armed-only={armed - scopes.RETRACE_WATCHES}, "
        f"registry-only={set(scopes.RETRACE_WATCHES) - armed}")


def test_registered_watch_rejects_unknown_path():
    from ray_tpu.util.telemetry import RetraceSentinel
    s = RetraceSentinel("t-registry")
    with pytest.raises(ValueError, match="not a registered"):
        s.watch("definitely_not_a_jit_path", lambda: 0, cap=1,
                registered=True)
    # registered names pass; ad-hoc names stay fine unregistered
    s.watch("decode", lambda: 0, cap=1, registered=True)
    s.watch("my_test_path", lambda: 0, cap=1)


def test_registry_watch_names_only_from_inventory():
    # every non-None watch name in the inventory is exported
    from_inventory = {n for per in scopes.COMPILE_ONCE_JITS.values()
                      for n in per.values() if n is not None}
    assert from_inventory == set(scopes.RETRACE_WATCHES)


# ---------------------------------------------------------------------------
# R004 fixes: the blocking work really happens outside the locks
# ---------------------------------------------------------------------------

def test_engine_swap_releases_scheduler_lock_during_placement():
    """update_params must hold the scheduler lock only for snapshot and
    commit: while the (slow) host->device placement runs, ticks keep
    going. Regression for the R004 finding this PR fixed."""
    jax = pytest.importorskip("jax")
    from ray_tpu.models import gpt
    from ray_tpu.serve.engine import InferenceEngine

    cfg = gpt.GPTConfig(vocab_size=128, d_model=32, n_layers=1,
                        n_heads=2, d_ff=64, max_seq_len=64,
                        dtype="float32")
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, slots=2, max_len=32,
                          prefill_buckets=(8, 16))
    fresh = jax.tree.map(lambda a: a + 1, gpt.init_params(
        jax.random.PRNGKey(1), cfg))

    placing = threading.Event()
    release = threading.Event()
    orig_place = eng._place_tree

    def slow_place(old, new, what):
        placing.set()
        assert release.wait(10), "test deadlock"
        return orig_place(old, new, what)

    eng._place_tree = slow_place
    errs = []

    def do_swap():
        try:
            eng.update_params(fresh)
        except Exception as exc:      # surface in the main thread
            errs.append(exc)

    t = threading.Thread(target=do_swap)
    t.start()
    assert placing.wait(10)
    # mid-placement the scheduler lock must be FREE: a tick (or this
    # acquire) must not wait behind the weight upload
    acquired = eng._lock.acquire(timeout=2)
    assert acquired, "scheduler lock held during weight placement"
    eng._lock.release()
    assert eng.params_version == 0    # commit hasn't happened yet
    release.set()
    t.join(10)
    assert not t.is_alive() and not errs, errs
    assert eng.params_version == 1
    assert eng.stats()["swaps"] == 1


def test_controller_shutdown_kills_outside_lock():
    """graceful_shutdown snapshots-and-clears under the lock and kills
    outside it: status()-style RPCs must not stall behind teardown."""
    from ray_tpu.serve import controller as controller_mod

    class _QuietController(controller_mod.ServeController):
        def _reconcile_loop(self):
            return                     # no reconcile thread activity

    ctl = _QuietController()
    st = controller_mod._DeploymentState("d", "app",
                                         {"num_replicas": 2})
    st.replicas = ["fake-r1", "fake-r2"]
    ctl._deployments[("app", "d")] = st
    ctl._graveyard.append(["fake-r3"])

    killing = threading.Event()
    release = threading.Event()
    killed = []

    def fake_kill(replicas):
        killed.append(list(replicas))
        killing.set()
        assert release.wait(10), "test deadlock"

    ctl._kill_replicas = fake_kill
    t = threading.Thread(target=ctl.graceful_shutdown)
    t.start()
    assert killing.wait(10)
    acquired = ctl._lock.acquire(timeout=2)
    assert acquired, "controller lock held during replica kill"
    # state was already cleared under the lock before any kill ran
    assert ctl._deployments == {} and ctl._graveyard == []
    ctl._lock.release()
    release.set()
    t.join(10)
    assert not t.is_alive()
    assert killed == [["fake-r1", "fake-r2"], ["fake-r3"]]


# ---------------------------------------------------------------------------
# engine jit index sanity (guards the registry against silent decay)
# ---------------------------------------------------------------------------

def test_engine_jit_anchors_match_inventory():
    rel = "ray_tpu/serve/engine.py"
    with open(os.path.join(REPO, rel)) as fh:
        tree = ast.parse(fh.read())
    astutil.add_parents(tree)
    anchors = set(astutil.build_jit_index(tree).by_anchor)
    assert anchors == set(scopes.COMPILE_ONCE_JITS[rel])
