"""Host-side collective group tests (reference:
`python/ray/util/collective/tests/`)."""

import numpy as np

import ray_tpu


def _rank_fn(rank, world):
    from ray_tpu.util import collective as col
    col.init_collective_group(world, rank, group_name="g1")
    out = col.allreduce(np.full(4, rank + 1.0), group_name="g1")
    gathered = col.allgather(np.array([rank]), group_name="g1")
    bcast = col.broadcast(np.array([rank * 10.0]), src_rank=2,
                          group_name="g1")
    return out, [int(g[0]) for g in gathered], float(bcast[0])


def test_collective_allreduce_allgather_broadcast(ray_session):
    world = 3
    fn = ray_tpu.remote(_rank_fn)
    refs = [fn.remote(r, world) for r in range(world)]
    results = ray_tpu.get(refs, timeout=180)
    expect_sum = sum(r + 1.0 for r in range(world))
    for out, gathered, bcast in results:
        np.testing.assert_allclose(out, np.full(4, expect_sum))
        assert gathered == [0, 1, 2]
        assert bcast == 20.0


def test_collective_send_recv(ray_session):
    def sender():
        from ray_tpu.util import collective as col
        g = col.init_collective_group(2, 0, group_name="p2p")
        g.send(np.array([7.0]), dst=1)
        return True

    def receiver():
        from ray_tpu.util import collective as col
        g = col.init_collective_group(2, 1, group_name="p2p")
        return float(g.recv(src=0)[0])

    s = ray_tpu.remote(sender).remote()
    r = ray_tpu.remote(receiver).remote()
    assert ray_tpu.get(r, timeout=120) == 7.0
    assert ray_tpu.get(s, timeout=120)


def test_named_group_create_race_converges(ray_session):
    """All ranks racing to create the group's rendezvous actor must bind
    to the SAME actor. Under pipelined submission the losing create no
    longer raises at `.remote()` (the name collision surfaces as an
    error object), so the client must re-resolve through the head's name
    table instead of trusting its own handle."""
    def join(rank, world):
        from ray_tpu.util import collective as col
        g = col.init_collective_group(world, rank, group_name="race")
        return g._actor._actor_id

    world = 4
    fn = ray_tpu.remote(join)
    refs = [fn.remote(r, world) for r in range(world)]
    ids = ray_tpu.get(refs, timeout=120)
    assert len(set(ids)) == 1, ids


def test_collective_refuses_big_tensors(ray_session):
    """The host-side group is a control-plane funnel (one rendezvous
    actor); model-state-sized payloads must be refused with a pointer at
    the in-graph path, not silently bottlenecked."""
    import numpy as np
    import pytest

    from ray_tpu.exceptions import RayTpuError
    from ray_tpu.util.collective import CollectiveGroup

    g = CollectiveGroup("cap_test", world_size=1, rank=0)
    assert g.allreduce(np.ones(8)).sum() == 8.0          # small: fine
    with pytest.raises(RayTpuError, match="in-graph"):
        g.allreduce(np.zeros(80 << 20, np.uint8))        # 80MB: refused
