"""Native arena store concurrency stress harness.

Counterpart of the reference's plasma concurrency tests
(`src/ray/object_manager/test/` + TSAN/ASAN CI configs under `ci/`):
N worker PROCESSES hammer one shared arena with create/seal/pin/
acquire/read/delete while the arena stays over-subscribed (forcing the
LRU eviction and boundary-tag coalescing paths), one process gets
SIGKILLed mid-traffic and its pins force-reclaimed (robust-mutex +
release_all crash path), and every surviving read must be consistent
(each object is filled with a one-byte pattern; a torn or reused block
fails the checksum).

Run under sanitizers (separate instrumented .so, never the cached
release build):

    RAY_TPU_SANITIZE=thread  python -m pytest tests/test_native_store_stress.py
    RAY_TPU_SANITIZE=address python -m pytest tests/test_native_store_stress.py
"""

import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, random, sys
sys.path.insert(0, %(repo)r)
from ray_tpu._private.native.arena import Arena

session_dir, wid, seconds = sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
arena = Arena.open(session_dir, capacity=%(capacity)d)
assert arena is not None, "native arena unavailable"
rng = random.Random(1000 + wid)

import time
deadline = time.monotonic() + seconds
mine = []           # (oid, pattern, size) sealed by this worker
ops = sealed = read = evicted_reads = 0
while time.monotonic() < deadline:
    ops += 1
    roll = rng.random()
    if roll < 0.45 or not mine:
        # create -> fill with a pattern -> pin -> seal
        oid = f"obj_{wid}_{ops}"
        size = rng.choice((1 << 10, 16 << 10, 64 << 10, 200 << 10))
        buf = arena.create(oid, size)
        if buf is None:
            # arena full: evict unpinned sealed objects and retry once
            arena.evict(size * 2)
            buf = arena.create(oid, size)
            if buf is None:
                continue
        pattern = (wid * 31 + ops) %% 251 + 1
        buf[:] = bytes([pattern]) * size
        arena.pin(oid, 1)
        arena.seal(oid)
        mine.append((oid, pattern, size))
        sealed += 1
    elif roll < 0.75:
        # read-validate one of ours (we hold the owner pin, so the
        # bytes must NEVER be torn or reused underneath us)
        oid, pattern, size = rng.choice(mine)
        view = arena.acquire(oid)
        if view is None:
            raise AssertionError(f"pinned object {oid} vanished")
        b = view[rng.randrange(size)]
        if b != pattern:
            raise AssertionError(
                f"torn read on {oid}: {b} != {pattern}")
        view.release()
        arena.pin(oid, -1)
        read += 1
    elif roll < 0.9 and mine:
        # release + delete one of ours (frees or condemns)
        oid, pattern, size = mine.pop(rng.randrange(len(mine)))
        arena.pin(oid, -1)
        arena.delete(oid)
    else:
        # cross-worker probe: acquire someone else's object if present;
        # evicted/deleted is fine, torn bytes are not
        other = rng.randrange(%(workers)d)
        oid = f"obj_{other}_{rng.randrange(1, ops + 1)}"
        view = arena.acquire(oid)
        if view is None:
            evicted_reads += 1
        else:
            b0 = view[0]
            ok = all(view[i] == b0 for i in
                     rng.sample(range(len(view)), min(8, len(view))))
            view.release()
            arena.pin(oid, -1)
            if not ok:
                raise AssertionError(f"inconsistent fill in {oid}")
assert not arena.poisoned(), "arena poisoned (lock holder died badly)"
print(f"worker {wid}: ops={ops} sealed={sealed} read={read} "
      f"missing_probes={evicted_reads}", flush=True)
"""


@pytest.mark.parametrize("n_workers,seconds", [(4, 6.0)])
def test_multiprocess_stress_with_crash(tmp_path, n_workers, seconds):
    capacity = 8 << 20      # 8 MiB arena, deliberately over-subscribed
    session = str(tmp_path)
    script = WORKER % {"repo": REPO, "capacity": capacity,
                       "workers": n_workers}
    procs = [
        subprocess.Popen([sys.executable, "-c", script, session, str(i),
                          str(seconds)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True)
        for i in range(n_workers)
    ]
    # SIGKILL one worker mid-traffic: the crash-reclaim path must free
    # its pins so the arena doesn't leak to a halt
    time.sleep(seconds / 3)
    victim = procs[0]
    victim.send_signal(signal.SIGKILL)

    outs = []
    for i, p in enumerate(procs[1:], start=1):
        out, _ = p.communicate(timeout=seconds * 10 + 60)
        outs.append(out)
        assert p.returncode == 0, f"worker {i} failed:\n{out}"

    # reclaim every dead process's pins (what a daemon does on each
    # worker death — the SIGKILLed victim is the crash path, the clean
    # exits still hold their owner pins), then the arena must be fully
    # usable
    from ray_tpu._private.native.arena import Arena
    arena = Arena.open(session, capacity=capacity)
    assert arena is not None
    for p in procs:
        arena.release_all(p.pid)
    assert not arena.poisoned()
    # after reclaim + eviction, a fresh create of half the arena works
    arena.evict(capacity)
    buf = arena.create("post_crash_probe", capacity // 2)
    assert buf is not None, "arena leaked to death after crash reclaim"
    buf[:] = b"\x42" * (capacity // 2)
    arena.seal("post_crash_probe")
    view = arena.lookup("post_crash_probe")
    assert view is not None and view[0] == 0x42
    arena.close()
    assert any("sealed=" in o for o in outs)


def test_stress_under_sanitizer_smoke(tmp_path):
    """Build + run a short burst against the TSAN-instrumented library
    when a sanitizer build is requested (or as a plain smoke otherwise).
    Sanitizer findings abort the worker -> nonzero exit -> failure."""
    sanitize = os.environ.get("RAY_TPU_SANITIZE", "")
    env = dict(os.environ)
    if sanitize in ("thread", "address"):
        # a sanitized .so can only dlopen into a process with the
        # sanitizer runtime already mapped (static TLS); preload it
        lib = {"thread": "libtsan.so", "address": "libasan.so"}[sanitize]
        path = subprocess.run(["gcc", f"-print-file-name={lib}"],
                              capture_output=True, text=True,
                              check=True).stdout.strip()
        env["LD_PRELOAD"] = path
        # TSAN flags: fail loudly, but don't die on the expected
        # inter-process shared mapping (it only sees one process)
        env.setdefault("TSAN_OPTIONS", "halt_on_error=1")
        # leak detection off: LSan reports CPython's own interpreter
        # allocations; heap-overflow/UAF detection (the part that can
        # implicate store.cc) stays on
        env.setdefault("ASAN_OPTIONS",
                       "detect_leaks=0:halt_on_error=1")
    script = WORKER % {"repo": REPO, "capacity": 4 << 20, "workers": 2}
    procs = [
        subprocess.Popen([sys.executable, "-c", script, str(tmp_path),
                          str(i), "2.0"],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env)
        for i in range(2)
    ]
    for i, p in enumerate(procs):
        out, _ = p.communicate(timeout=180)
        assert p.returncode == 0, \
            f"worker {i} failed under {sanitize or 'release'}:\n{out}"
