"""DreamerV3 — world-model RL (reference: rllib/algorithms/dreamerv3/).

Two claims, tested separately: the WORLD MODEL learns (reconstruction
loss collapses — the RSSM actually models CartPole dynamics), and the
IMAGINATION-trained policy improves the real-environment return well
beyond the random baseline. Time-bounded thresholds: from ~22 (random)
the measured curve passes 60 around iteration 30-40 on this box."""

import numpy as np


def test_dreamerv3_world_model_and_policy_learn():
    from ray_tpu.rllib.algorithms.dreamerv3 import DreamerV3Config

    cfg = DreamerV3Config().environment(
        "CartPole-v1", env_config={"max_steps": 200})
    cfg.seed = 0
    cfg.num_envs_per_worker = 8
    cfg.n_updates_per_iter = 10
    cfg.learning_starts = 16
    cfg.entropy_coeff = 1e-2
    algo = cfg.build()

    first_recon, best = None, 0.0
    for i in range(40):
        r = algo.train()
        if first_recon is None and np.isfinite(r["recon_loss"]):
            first_recon = r["recon_loss"]
        best = max(best, r["episode_reward_mean"])
        if best >= 60:
            break
    # the RSSM models the dynamics...
    assert np.isfinite(r["world_model_loss"])
    assert r["recon_loss"] < first_recon * 0.5, (
        first_recon, r["recon_loss"])
    # ...and acting from imagination beats the random baseline (~22) by
    # a wide margin
    assert best >= 60, best
    # checkpoint roundtrip
    st = algo.get_state()
    algo.set_state(st)
