"""DreamerV3 — world-model RL (reference: rllib/algorithms/dreamerv3/).

Two claims, tested separately: the WORLD MODEL learns (reconstruction
loss collapses — the RSSM actually models CartPole dynamics), and the
IMAGINATION-trained policy improves the real-environment return well
beyond the random baseline. Time-bounded thresholds: from ~22 (random)
the measured curve passes 60 around iteration 30-40 on this box. The
full learning regression is `slow` (tier-1 budget); the tier-1 smoke
pins the train-step contract and a checkpoint roundtrip in a few
iterations.
"""

import numpy as np
import pytest


def _build(**overrides):
    from ray_tpu.rllib.algorithms.dreamerv3 import DreamerV3Config

    cfg = DreamerV3Config().environment(
        "CartPole-v1", env_config={"max_steps": 200})
    cfg.seed = 0
    cfg.num_envs_per_worker = 8
    cfg.n_updates_per_iter = 10
    cfg.learning_starts = 16
    cfg.entropy_coeff = 1e-2
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg.build()


def test_dreamerv3_smoke():
    """Tier-1: the world-model + actor-critic step runs end to end with
    finite losses, and get_state/set_state roundtrips — no learning
    threshold (that's the slow regression)."""
    algo = _build(n_updates_per_iter=2)
    r = None
    for _ in range(3):
        r = algo.train()
    assert np.isfinite(r["world_model_loss"])
    assert np.isfinite(r["recon_loss"])
    assert np.isfinite(r["episode_reward_mean"])
    st = algo.get_state()
    algo.set_state(st)


@pytest.mark.slow
def test_dreamerv3_world_model_and_policy_learn():
    algo = _build()

    first_recon, best = None, 0.0
    for i in range(40):
        r = algo.train()
        if first_recon is None and np.isfinite(r["recon_loss"]):
            first_recon = r["recon_loss"]
        best = max(best, r["episode_reward_mean"])
        if best >= 60:
            break
    # the RSSM models the dynamics...
    assert np.isfinite(r["world_model_loss"])
    assert r["recon_loss"] < first_recon * 0.5, (
        first_recon, r["recon_loss"])
    # ...and acting from imagination beats the random baseline (~22) by
    # a wide margin
    assert best >= 60, best
    # checkpoint roundtrip
    st = algo.get_state()
    algo.set_state(st)
