"""Serve platform seams: model multiplexing, declarative config apply,
and per-node HTTP proxies.

References: `serve/multiplex.py` (@serve.multiplexed +
get_multiplexed_model_id), `serve/schema.py` + `dashboard/modules/serve/`
(declarative YAML/REST deploy), `_private/http_proxy.py:858` (one proxy
actor per node).
"""

import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_session(ray_session):
    yield ray_session
    serve.delete()
    serve.shutdown()
    time.sleep(0.3)


# ---------------------------------------------------------------------------
# multiplexing
# ---------------------------------------------------------------------------

LOADS: list = []      # records (replica_pid, model_id) loads


@serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 0.1})
class MuxServer:
    @serve.multiplexed(max_num_models_per_replica=2)
    def get_model(self, model_id: str):
        import os
        return {"id": model_id, "pid": os.getpid(),
                "stamp": time.time()}

    def __call__(self, x):
        model = self.get_model(serve.get_multiplexed_model_id())
        return {"model": model["id"], "pid": model["pid"],
                "stamp": model["stamp"], "x": x}


def test_multiplexed_routing_and_lru(serve_session):
    handle = serve.run(MuxServer.bind(), name="mux")

    # same model id -> same replica (rendezvous hash) and a cache HIT
    # (the load stamp must not change between calls)
    r1 = handle.options(multiplexed_model_id="m1").call(1)
    r2 = handle.options(multiplexed_model_id="m1").call(2)
    assert r1["model"] == r2["model"] == "m1"
    assert r1["pid"] == r2["pid"], "m1 moved replicas between calls"
    assert r1["stamp"] == r2["stamp"], "m1 was reloaded (cache miss)"

    # LRU cap 2: load 3 models pinned to ONE replica id, the first gets
    # evicted and reloads with a new stamp
    ids = ["a", "b", "c"]
    first = {m: handle.options(multiplexed_model_id=m).call(0)
             for m in ids}
    # drive them all to the same replica? HRW may spread them; only
    # assert eviction when a, b, c landed together with a
    pids = {m: first[m]["pid"] for m in ids}
    same = [m for m in ids if pids[m] == pids["a"]]
    if len(same) == 3:
        again = handle.options(multiplexed_model_id="a").call(0)
        assert again["stamp"] != first["a"]["stamp"], \
            "LRU cap did not evict the oldest model"
    # no-model-id calls still work
    plain = handle.call(42)
    assert plain["model"] == "" and plain["x"] == 42


# ---------------------------------------------------------------------------
# declarative config apply (module-level app so import_path resolves)
# ---------------------------------------------------------------------------

@serve.deployment(ray_actor_options={"num_cpus": 0.1})
class Echo:
    def __init__(self, prefix: str = "echo"):
        self.prefix = prefix

    def __call__(self, x):
        return f"{self.prefix}:{x}"


config_app = Echo.bind("fromcfg")

CONFIG = {
    "applications": [{
        "name": "cfg_app",
        "route_prefix": "/cfg",
        "import_path": "tests.test_serve_platform:config_app",
        "deployments": [{"name": "Echo", "num_replicas": 2}],
    }],
}


def test_apply_config_dict_and_overrides(serve_session):
    out = serve.apply_config(CONFIG)
    assert out == {"cfg_app": "deployed"}
    handle = serve.get_deployment_handle("Echo", "cfg_app")
    assert handle.call("hi") == "fromcfg:hi"
    st = serve.status()
    assert st["cfg_app:Echo"]["target_replicas"] == 2
    serve.delete("cfg_app")


def test_apply_config_yaml_and_cli_roundtrip(serve_session, tmp_path):
    import yaml
    path = tmp_path / "serve.yaml"
    cfg = {"applications": [{
        "name": "yaml_app", "route_prefix": "/y",
        "import_path": "tests.test_serve_platform:config_app",
    }]}
    path.write_text(yaml.safe_dump(cfg))
    out = serve.apply_config(str(path))
    assert out == {"yaml_app": "deployed"}
    assert serve.get_deployment_handle("Echo", "yaml_app").call("x") \
        == "fromcfg:x"
    serve.delete("yaml_app")


def test_apply_config_rejects_unknown_deployment(serve_session):
    bad = {"applications": [{
        "name": "bad", "import_path":
            "tests.test_serve_platform:config_app",
        "deployments": [{"name": "Nope", "num_replicas": 2}],
    }]}
    with pytest.raises(Exception, match="unknown deployments"):
        serve.apply_config(bad)


# ---------------------------------------------------------------------------
# per-node HTTP proxies
# ---------------------------------------------------------------------------

def test_proxy_on_every_node(serve_session):
    import json
    import urllib.request

    from ray_tpu.cluster_utils import Cluster
    c = Cluster.attach()
    nid = c.add_node({"CPU": 1, "proxyhost": 1})
    try:
        serve.run(Echo.bind("edge"), name="edge_app")
        serve.start(http_options={"port": 0, "worker_port": 0,
                                  "location": "EveryNode"})
        serve.set_route("/edge", "Echo", "edge_app")
        from ray_tpu.serve.api import proxy_endpoints
        eps = proxy_endpoints()
        assert "head" in eps and nid in eps, eps
        # the WORKER node's proxy serves the route end to end
        port = eps[nid]["port"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/edge?x=1", timeout=30) as r:
            body = r.read().decode()
        assert "edge:" in body, body
        serve.delete("edge_app")
    finally:
        try:
            c.kill_node(nid)
        except Exception:
            pass
