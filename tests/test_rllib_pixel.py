"""Pixel-tier RL oracle: MinAtar-class env invariants, the conv-policy
learning regressions (PPO / IMPALA / Ape-X tuned examples), and the
same configs on the 8-device mesh.

This is the repo's counterpart of the reference's Atari oracle tier
(`rllib/tuned_examples/ppo/pong-ppo.yaml:1`,
`impala/pong-impala-fast.yaml:1-4`, `rllib/env/wrappers/
atari_wrappers.py`): reward thresholds + wall-clock budgets prove a conv
encoder learns spatio-temporal structure from pixels end-to-end through
each architecture (in-graph PPO, async actor-learner IMPALA,
distributed-replay Ape-X).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.rllib.env.pixel import (
    PixelAsterix, PixelBreakout, PixelInvaders)
from ray_tpu.rllib.train import list_tuned_examples, run_tuned_example


def _rollout(env, n_steps, seed=0, batch=8):
    keys = jax.random.split(jax.random.PRNGKey(seed), batch)
    state, obs = jax.vmap(env.reset)(keys)
    n_act = env.action_space.n

    def body(carry, key):
        state = carry
        ka, ks = jax.random.split(key)
        actions = jax.random.randint(ka, (batch,), 0, n_act)
        state, obs, r, d, _ = jax.vmap(env.step)(
            state, actions, jax.random.split(ks, batch))
        return state, (obs, r, d)

    scan = jax.jit(lambda s, ks: jax.lax.scan(body, s, ks))
    state, (obs, r, d) = scan(
        state, jax.random.split(jax.random.PRNGKey(seed + 1), n_steps))
    return state, obs, r, d


@pytest.mark.parametrize("cls", [PixelBreakout, PixelAsterix,
                                 PixelInvaders])
def test_env_vmap_scan_contract(cls):
    """Pure-function contract: vmap over envs + scan over time compiles;
    observations are [10, 10, 4] binary images; episodes terminate and
    auto-reset."""
    env = cls({})
    state, obs, r, d, = _rollout(env, 300)
    assert obs.shape == (300, 8, 10, 10, 4)
    assert float(obs.min()) >= 0.0 and float(obs.max()) <= 1.0
    assert set(np.unique(obs)).issubset({0.0, 1.0})
    assert int(d.sum()) > 0, "no episode ever terminated"
    assert np.isfinite(np.asarray(r)).all()


@pytest.mark.parametrize("cls", [PixelBreakout, PixelAsterix,
                                 PixelInvaders])
def test_env_deterministic(cls):
    env = cls({})
    _, obs1, r1, d1 = _rollout(env, 64, seed=3)
    _, obs2, r2, d2 = _rollout(env, 64, seed=3)
    np.testing.assert_array_equal(np.asarray(obs1), np.asarray(obs2))
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))


def test_breakout_mechanics():
    """Brick hits pay +1 and consume the brick; missing the ball ends
    the episode; a perfect (predictive) player sustains play to the step
    cap."""
    env = PixelBreakout({"max_steps": 200})
    key = jax.random.PRNGKey(0)
    state, obs = env.reset(key)
    step = jax.jit(env.step)

    def predict_landing(s):
        y, x = int(s["ball_y"]), int(s["ball_x"])
        dy, dx = int(s["dy"]), int(s["dx"])
        bricks = np.array(s["bricks"])
        for _ in range(200):
            nx = x + dx
            if nx < 0 or nx > 9:
                dx = -dx
                nx = max(0, min(9, -nx if nx < 0 else nx))
            ny = y + dy
            if ny < 0:
                dy, ny = 1, 1
            if 1 <= ny <= 3 and bricks[ny - 1, nx] == 1:
                bricks[ny - 1, nx] = 0
                dy, ny = -dy, y
            if ny >= 9:
                return nx
            y, x = ny, nx
        return x

    total_r, dones = 0.0, 0
    for i in range(400):
        key, k = jax.random.split(key)
        target = predict_landing(state)
        px = int(state["paddle"])
        a = 0 if target == px else (1 if target < px else 2)
        state, obs, r, d, _ = step(state, jnp.asarray(a), k)
        total_r += float(r)
        dones += int(bool(d))
    # perfect play: episodes end only at the 200-step cap, scoring
    # steadily (measured ~12 bricks/200 steps)
    assert dones == 2 and total_r >= 10, (dones, total_r)

    # a frozen paddle loses within one ball descent
    state, obs = env.reset(jax.random.PRNGKey(1))
    for i in range(12):
        key, k = jax.random.split(key)
        state, obs, r, d, _ = step(state, jnp.asarray(0), k)
        if bool(d):
            break
    assert i < 11, "episode should end quickly with a frozen paddle"


def test_asterix_gold_and_death():
    """Gold touches pay +1 and despawn; enemy touches terminate."""
    env = PixelAsterix({"gold_p": 1.0})
    _, _, r, d = _rollout(env, 400, seed=0, batch=16)
    assert float(np.asarray(r).sum()) > 5, "all-gold config must pay"
    env2 = PixelAsterix({"gold_p": 0.0, "max_steps": 250})
    _, _, r2, d2 = _rollout(env2, 250, seed=0, batch=16)
    # all-enemy config: deaths before the cap, and never a reward
    assert float(np.asarray(r2).sum()) == 0.0
    assert int(np.asarray(d2).sum()) >= 16


def test_invaders_kill_and_invasion():
    env = PixelInvaders({})
    _, obs, r, d = _rollout(env, 300, seed=0, batch=16)
    assert float(np.asarray(r).sum()) > 10, "random fire must hit aliens"
    # alien channel occupancy decreases as kills land within an episode
    alien_density = np.asarray(obs)[..., 1].sum(axis=(2, 3))
    assert alien_density.min() < 24, "no alien was ever destroyed"


# ---------------------------------------------------------------------------
# learning regressions (reward threshold + wall-clock budget per yaml)
# ---------------------------------------------------------------------------


def _run_yaml(substr: str) -> dict:
    path = [p for p in list_tuned_examples() if substr in p]
    assert path, f"tuned example {substr} missing"
    return run_tuned_example(path[0], verbose=False)


@pytest.mark.slow
def test_pixel_breakout_ppo_regression():
    out = _run_yaml("pixel-breakout-ppo")
    assert out["passed"], out


@pytest.mark.slow
def test_pixel_breakout_impala_regression(ray_session):
    out = _run_yaml("pixel-breakout-impala")
    assert out["passed"], out


@pytest.mark.slow
def test_pixel_invaders_apex_regression(ray_session):
    out = _run_yaml("pixel-invaders-apex")
    assert out["passed"], out


# ---------------------------------------------------------------------------
# the same pixel config on the 8-device mesh (conftest forces an
# 8-device CPU mesh; the driver's dryrun covers the train stack — this
# covers RL)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_pixel_ppo_on_8_device_mesh():
    """The pixel-breakout PPO config shard_maps its WHOLE fused
    iteration (rollout + GAE + minibatch SGD) over a data-axis mesh:
    env batch split across 8 devices, gradients pmean'd, advantages
    standardized with global moments."""
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    assert len(jax.devices()) >= 8
    algo = (PPOConfig().environment("PixelBreakout")
            .rollouts(num_envs_per_worker=32, rollout_fragment_length=32)
            .training(train_batch_size=1024, sgd_minibatch_size=512,
                      num_sgd_iter=2, lr=1e-3, entropy_coeff=0.01,
                      num_learner_devices=8,
                      model={"conv_filters": ((16, 3, 1), (32, 3, 2)),
                             "post_fcnet_hiddens": (128,)})
            .debugging(seed=0).build())
    r1 = algo.train()
    r2 = algo.train()
    assert np.isfinite(r2["policy_loss"])
    assert np.isfinite(r2["vf_loss"])
    # params stayed replicated across the mesh (pmean'd updates)
    leaf = jax.tree.leaves(algo.params)[0]
    assert len(set(d.device_kind for d in leaf.devices())) == 1
