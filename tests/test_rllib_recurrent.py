"""Recurrent-policy path + R2D2 (reference: rllib/algorithms/r2d2/).

The memory probe is the decisive test: in MemoryRecall the cue appears
ONLY at t=0 and the rewarded action depends on it for the rest of the
episode, so a feedforward policy is capped at chance after the first
step while an LSTM can hold the cue — R2D2 clearing the feedforward
ceiling proves recurrent state actually flows through rollout, replay
(stored state + burn-in), and the train unroll.
"""

import numpy as np
import pytest


def _train(algo_name, env, stop_reward, max_iters, **overrides):
    from ray_tpu.rllib.algorithms.algorithm import get_algorithm_class
    cls = get_algorithm_class(algo_name)
    cfg = cls.get_default_config()
    cfg.environment(env)
    for k, v in overrides.items():
        setattr(cfg, k, v)
    algo = cfg.build()
    best = -np.inf
    for _ in range(max_iters):
        res = algo.train()
        r = res.get("episode_reward_mean", float("nan"))
        if np.isfinite(r):
            best = max(best, r)
        if best >= stop_reward:
            break
    return best


def test_recurrent_module_state_flow():
    """Unit: LSTM state changes across steps, resets on done, and the
    step/unroll paths agree (the training unroll must reproduce what the
    sampler computed)."""
    import jax
    import jax.numpy as jnp
    from ray_tpu.rllib.core.recurrent import RecurrentQModule
    from ray_tpu.rllib.env.spaces import Box, Discrete

    mod = RecurrentQModule(Box(-1, 1, (3,)), Discrete(2),
                           {"fcnet_hiddens": (8,), "lstm_cell_size": 8})
    params = mod.init(jax.random.PRNGKey(0))
    s0 = mod.initial_state(2)
    obs_seq = jax.random.normal(jax.random.PRNGKey(1), (5, 2, 3))
    dones = jnp.zeros((5, 2))

    # unroll == repeated steps
    q_unrolled, sT = mod.q_unroll(params, obs_seq, dones, s0)
    s = s0
    qs = []
    for t in range(5):
        q, s = mod.q_step(params, obs_seq[t], s)
        qs.append(q)
    np.testing.assert_allclose(np.asarray(q_unrolled),
                               np.asarray(jnp.stack(qs)), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(sT[0]), np.asarray(s[0]),
                               rtol=1e-5)
    # state is live (changes between steps)...
    assert float(jnp.abs(s[1]).sum()) > 0
    # ...and a done in the middle resets it: the post-done state must
    # equal a fresh unroll of the suffix from zeros
    dones_mid = dones.at[2].set(1.0)
    q_r, s_r = mod.q_unroll(params, obs_seq, dones_mid, s0)
    q_fresh, s_fresh = mod.q_unroll(
        params, obs_seq[3:], dones[3:], mod.initial_state(2))
    np.testing.assert_allclose(np.asarray(q_r[3:]),
                               np.asarray(q_fresh), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(s_r[0]),
                               np.asarray(s_fresh[0]), rtol=1e-5)


def test_recurrent_sampler_carries_and_stores_state():
    """The in-graph sampler threads LSTM state through the scan and
    returns the fragment-START state (R2D2's stored-state strategy)."""
    import jax
    import jax.numpy as jnp
    from ray_tpu.rllib.core.recurrent import (
        RecurrentInGraphSampler, RecurrentQModule)
    from ray_tpu.rllib.env.jax_env import make_env

    env = make_env("MemoryRecall", {"episode_len": 6})
    mod = RecurrentQModule(env.observation_space, env.action_space,
                           {"fcnet_hiddens": (8,), "lstm_cell_size": 8})
    params = mod.init(jax.random.PRNGKey(0))
    sampler = RecurrentInGraphSampler(env, mod, num_envs=4,
                                      rollout_length=5)
    carry = sampler.init_state(jax.random.PRNGKey(1))
    c2, traj, state0 = sampler.sample(params, carry,
                                      jax.random.PRNGKey(2),
                                      jnp.asarray(0.1))
    # fragment-start state is the INITIAL zero state on the first call
    assert float(jnp.abs(state0[0]).sum()) == 0.0
    # after 5 steps (episode_len 6: nothing done yet) state is nonzero
    assert float(jnp.abs(c2["policy_state"][0]).sum()) > 0.0
    _, _, state1 = sampler.sample(params, c2, jax.random.PRNGKey(3),
                                  jnp.asarray(0.1))
    # second fragment's stored state == carry state at its start
    np.testing.assert_allclose(np.asarray(state1[0]),
                               np.asarray(c2["policy_state"][0]))
    assert traj["obs"].shape[:2] == (5, 4)


def test_r2d2_learns_memory_task():
    """R2D2 beats the feedforward ceiling on MemoryRecall.

    episode_len=10, cue at t=0 only: acting on the cue every step pays
    1/step. A memoryless policy earns at most ~1 + 9*0.5 = 5.5 in
    expectation (chance after t=0); threshold 8 requires genuinely
    remembered cue bits (reference parity:
    rllib/algorithms/r2d2 tuned on RepeatAfterMeEnv/stateless envs)."""
    best = _train(
        "R2D2", "MemoryRecall", stop_reward=8.0, max_iters=60,
        train_batch_size=16, buffer_size=2000, learning_starts=64,
        num_envs_per_worker=16, rollout_fragment_length=12, burn_in=2,
        n_updates_per_iter=16, target_network_update_freq=100,
        epsilon_timesteps=6000, lr=2e-3,
        model={"fcnet_hiddens": (32,), "lstm_cell_size": 32},
        env_config={"episode_len": 10})
    assert best >= 8.0, f"R2D2 failed the memory task: best={best:.2f}"


def test_dqn_feedforward_fails_memory_task():
    """Control: the same budget with feedforward DQN stays at the
    memoryless ceiling — proving the task actually requires memory (and
    the R2D2 pass isn't an artifact of the env being trivially
    solvable)."""
    best = _train(
        "DQN", "MemoryRecall", stop_reward=8.0, max_iters=25,
        train_batch_size=64, buffer_size=5000, learning_starts=200,
        num_envs_per_worker=16, rollout_fragment_length=12,
        n_updates_per_iter=16, epsilon_timesteps=4000,
        env_config={"episode_len": 10})
    assert best < 8.0, (
        f"feedforward DQN 'solved' the memory task ({best:.2f}) — the "
        "env no longer requires memory")


def test_stateless_cartpole_masks_only_observations():
    """StatelessCartPole exposes (x, theta) only, while the INTERNAL
    dynamics (and auto-reset) stay 4-dimensional — the masked trajectory
    must track the full env's exactly (regression: a masked reset once
    leaked into the parent's auto-reset and broke shapes)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.env.jax_env import make_env

    full = make_env("CartPole-v1", {"max_steps": 50})
    masked = make_env("StatelessCartPole", {"max_steps": 50})
    assert masked.observation_space.shape == (2,)

    key = jax.random.PRNGKey(0)
    sf, of = full.reset(key)
    sm, om = masked.reset(key)
    np.testing.assert_allclose(np.asarray(om),
                               np.asarray(of)[[0, 2]])
    for t in range(60):      # crosses at least one auto-reset boundary
        key, k = jax.random.split(key)
        a = jnp.asarray(t % 2)
        sf, of, rf, df, _ = full.step(sf, a, k)
        sm, om, rm, dm, _ = masked.step(sm, a, k)
        assert om.shape == (2,)
        np.testing.assert_allclose(np.asarray(om),
                                   np.asarray(of)[[0, 2]], rtol=1e-6)
        assert bool(df) == bool(dm) and float(rf) == float(rm)
