"""Dashboard HTTP API, job submission, and CLI session attach.

Counterpart of the reference's `dashboard/modules/job/tests/`,
`python/ray/tests/test_dashboard.py`, and the state-CLI tests: REST
endpoints serve live state; jobs run as managed subprocesses with status
and captured logs; an external process attaches to the session socket.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.job_submission import JobSubmissionClient


@pytest.fixture
def cluster(ray_session):
    return ray_session


@pytest.fixture(scope="module")
def dashboard_port(ray_session):
    from ray_tpu.dashboard import start_dashboard
    return start_dashboard(0)   # ephemeral port


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        body = r.read().decode()
        if r.headers.get_content_type() == "application/json":
            return json.loads(body)
        return body


def test_dashboard_healthz_and_state(cluster, dashboard_port):
    @ray_tpu.remote
    def dash_task():
        return 1

    ray_tpu.get(dash_task.remote())
    assert _get(dashboard_port, "/healthz") == {"status": "ok"}
    nodes = _get(dashboard_port, "/api/nodes")
    assert nodes and nodes[0]["resources_total"]["CPU"] > 0
    tasks = _get(dashboard_port, "/api/tasks")
    assert any("dash_task" in t["name"] for t in tasks)
    assert isinstance(_get(dashboard_port, "/api/workers"), list)
    assert isinstance(_get(dashboard_port, "/api/summary"), dict)
    from ray_tpu.util import metrics as m
    m.Counter("dash_probe", "d").inc(1.0)
    text = _get(dashboard_port, "/metrics")
    assert "ray_tpu_dash_probe 1.0" in text   # prometheus exposition
    # timeseries gauge sample feeding the UI's sparkline charts
    snap = _get(dashboard_port, "/api/metrics_snapshot")
    assert snap["nodes_alive"] >= 1 and snap["workers_alive"] >= 1
    assert snap["ts"] > 0 and "store_used_bytes" in snap
    # the SPA shell + assets serve, and the app covers the reference
    # client's page families (dashboard/client/src/pages/)
    page = _get(dashboard_port, "/")
    assert 'src="/static/app.js"' in page
    app = _get(dashboard_port, "/static/app.js")
    for family in ("overview", "cluster", "jobs", "actors", "tasks",
                   "serve", "logs", "metrics"):
        assert f"pages.{family}" in app, family
    assert "metrics_snapshot" in app
    css = _get(dashboard_port, "/static/style.css")
    assert "--accent" in css
    # path traversal is rejected
    import urllib.error
    with pytest.raises(urllib.error.HTTPError):
        _get(dashboard_port, "/static/../__init__.py")
    # every API the SPA polls responds
    for route in ("/api/nodes", "/api/actors", "/api/tasks",
                  "/api/summary", "/api/jobs", "/api/logs",
                  "/api/serve/applications", "/api/metrics_snapshot"):
        _get(dashboard_port, route)


def test_job_submit_success_and_logs(cluster):
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('job says hi')\"")
    status = client.wait_until_finished(job_id, timeout=60)
    assert status == "SUCCEEDED"
    assert "job says hi" in client.get_job_logs(job_id)
    info = client.get_job_info(job_id)
    assert info["returncode"] == 0
    assert any(j["job_id"] == job_id for j in client.list_jobs())


def test_job_failure_status(cluster):
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"import sys; sys.exit(3)\"")
    assert client.wait_until_finished(job_id, timeout=60) == "FAILED"
    assert client.get_job_info(job_id)["returncode"] == 3


def test_job_stop(cluster):
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"import time; time.sleep(60)\"")
    time.sleep(0.3)
    assert client.stop_job(job_id)
    assert client.wait_until_finished(job_id, timeout=30) == "STOPPED"


def test_job_env_vars(cluster):
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=(f"{sys.executable} -c "
                    "\"import os; print(os.environ['MYVAR'], "
                    "os.environ['RAY_TPU_JOB_ID'])\""),
        runtime_env={"env_vars": {"MYVAR": "tpu42"}})
    assert client.wait_until_finished(job_id, timeout=60) == "SUCCEEDED"
    logs = client.get_job_logs(job_id)
    assert "tpu42" in logs and job_id in logs


def test_dashboard_job_rest(cluster, dashboard_port):
    req = urllib.request.Request(
        f"http://127.0.0.1:{dashboard_port}/api/jobs",
        data=json.dumps({
            "entrypoint": f"{sys.executable} -c \"print('rest job')\""
        }).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        job_id = json.loads(r.read())["job_id"]
    deadline = time.time() + 60
    while time.time() < deadline:
        info = _get(dashboard_port, f"/api/jobs/{job_id}")
        if info["status"] in ("SUCCEEDED", "FAILED"):
            break
        time.sleep(0.25)
    assert info["status"] == "SUCCEEDED"
    assert "rest job" in _get(dashboard_port, f"/api/jobs/{job_id}/logs")


def test_cli_attach_from_subprocess(cluster):
    """A separate process attaches to this session and reads state —
    the `ray status` path."""
    session_dir = ray_tpu._worker.get_client().node.session_dir
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli",
         "--session", session_dir, "status"],
        capture_output=True, text=True, timeout=60,
        cwd="/root/repo")
    assert out.returncode == 0, out.stderr
    assert "CPU" in out.stdout and "workers:" in out.stdout

    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli",
         "--session", session_dir, "list", "nodes"],
        capture_output=True, text=True, timeout=60, cwd="/root/repo")
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout)[0]["alive"] is True


def test_attach_idle_longpoll_outlives_control_timeout(cluster):
    """An attach client whose default control deadline is SHORTER than a
    long-poll's server-side window must still get the empty batch back,
    not a spurious ConnectionError (ADVICE r3 #3: the transport deadline
    used to equal the server poll timeout exactly)."""
    session_dir = ray_tpu._worker.get_client().node.session_dir
    script = (
        "from ray_tpu._private.attach import AttachClient\n"
        f"c = AttachClient({session_dir!r})\n"
        "last, msgs = c.control('pubsub_poll',"
        " {'channel': 'idle_chan_never_published', 'after': 0,"
        "  'timeout': 4.0})\n"
        "assert msgs == [], msgs\n"
        "c.close()\n"
        "print('POLL_OK')\n")
    env = dict(os.environ)
    # client-side default deadline (2s) < server-side poll window (4s):
    # before the fix this raised ConnectionError at 2s
    env["RAY_TPU_ATTACH_CONTROL_TIMEOUT_S"] = "2.0"
    out = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=60, cwd="/root/repo", env=env)
    assert out.returncode == 0, out.stderr
    assert "POLL_OK" in out.stdout
