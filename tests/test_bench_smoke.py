"""Tier-1 bench smoke: `bench.main()` end-to-end in CPU mode through the
overlapped loop (prefetch + accum + fused dispatch + metrics ring), so
bench breakage is caught here instead of on silicon. Asserts the one-line
JSON contract the driver scrapes.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))


def test_bench_cpu_smoke(capsys, monkeypatch):
    monkeypatch.setenv("RAY_TPU_BENCH_STEPS", "4")   # keep CI fast
    import bench

    bench.main()
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    assert rec["metric"] == "gpt_train_tokens_per_sec"
    assert rec["unit"] == "tokens/s"
    assert np.isfinite(rec["value"]) and rec["value"] > 0
    assert rec["vs_baseline"] == 0.0        # CPU mode reports no MFU ratio
    # fault-tolerance cost is part of the published contract
    assert np.isfinite(rec["checkpoint_overhead_pct"])
    # telemetry fields: MFU (meaningless on CPU but present and finite),
    # the host step-time breakdown shares, and a clean retrace sentinel
    # on the fused dispatch's compile-once pin.
    assert np.isfinite(rec["mfu"]) and rec["mfu"] >= 0
    bd = rec["step_breakdown"]
    for key in ("prefetch", "dispatch", "metrics", "checkpoint",
                "publish"):
        assert 0.0 <= bd[key] <= 1.0, (key, bd)
    assert sum(bd.values()) <= 1.001, bd
    assert bd["dispatch"] > 0.0, bd
    assert rec["retraces_unexpected"] == 0
