"""Model + kernel tests on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt
from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.parallel import MeshSpec, reference_attention, tree_shardings


@pytest.fixture(scope="module")
def small_cfg():
    return gpt.small(dtype="float32", attn_impl="xla")


@pytest.fixture(scope="module")
def small_params(small_cfg):
    return gpt.init_params(jax.random.PRNGKey(0), small_cfg)


def test_gpt_forward_shape(small_cfg, small_params):
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = gpt.forward(small_params, tokens, small_cfg)
    assert logits.shape == (2, 16, small_cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_gpt_loss_decreases_with_training(small_cfg, small_params):
    import optax
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, small_cfg.vocab_size, (4, 32)),
                         jnp.int32)
    opt = optax.adam(1e-3)
    params = small_params
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(gpt.loss_fn)(
            params, {"tokens": tokens}, small_cfg)
        updates, state = opt.update(grads, state)
        return optax.apply_updates(params, updates), state, loss

    first = None
    for i in range(10):
        params, state, loss = step(params, state)
        if first is None:
            first = float(loss)
    assert float(loss) < first


def test_gpt_attention_impls_agree(small_cfg, small_params):
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, small_cfg.vocab_size, (2, 128)),
        jnp.int32)
    import dataclasses
    logits_xla = gpt.forward(small_params, tokens, small_cfg)
    cfg_flash = dataclasses.replace(small_cfg, attn_impl="flash")
    logits_flash = gpt.forward(small_params, tokens, cfg_flash)
    np.testing.assert_allclose(np.asarray(logits_xla),
                               np.asarray(logits_flash), atol=2e-4,
                               rtol=2e-4)


def test_gpt_sharded_matches_single(small_cfg, small_params):
    """The same params/tokens give the same loss on a dp x tensor mesh."""
    mesh = MeshSpec(data=2, tensor=4).build()
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, small_cfg.vocab_size, (4, 32)),
        jnp.int32)
    base = float(gpt.loss_fn(small_params, {"tokens": tokens}, small_cfg))

    shardings = tree_shardings(mesh, gpt.param_logical_axes(small_cfg))
    sharded_params = jax.device_put(small_params, shardings)
    sharded = float(jax.jit(
        lambda p, b: gpt.loss_fn(p, b, small_cfg))(
            sharded_params, {"tokens": tokens}))
    assert abs(base - sharded) < 1e-4


def test_flash_attention_matches_reference():
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 128, 2, 32)),
                           jnp.float32) for _ in range(3))
    for causal in (False, True):
        out = flash_attention(q, k, v, causal)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_flash_attention_grad():
    rng = np.random.default_rng(3)
    q, k, v = (jnp.asarray(rng.standard_normal((1, 128, 2, 16)),
                           jnp.float32) for _ in range(3))
    g = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, True) ** 2))(q)
    gr = jax.grad(lambda q: jnp.sum(
        reference_attention(q, k, v, causal=True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=1e-4,
                               rtol=1e-4)


def test_flash_attention_all_grads():
    """dq, dk, dv all flow through the Pallas backward kernels."""
    rng = np.random.default_rng(5)
    q, k, v = (jnp.asarray(rng.standard_normal((1, 128, 2, 16)),
                           jnp.float32) for _ in range(3))

    def tot(attn):
        return lambda q, k, v: jnp.sum(attn(q, k, v) ** 2)

    gf = jax.grad(tot(lambda q, k, v: flash_attention(q, k, v, True)),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(tot(lambda q, k, v: reference_attention(
        q, k, v, causal=True)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_attention_ragged_seq_stays_on_kernel():
    """T=384 is not a multiple of the 1024 default block; the planner
    shrinks blocks to a divisor instead of falling back to XLA."""
    from ray_tpu.ops.flash_attention import _plan_blocks

    assert _plan_blocks(384, 1024, 1024) == (384, 384)
    assert _plan_blocks(1536, 1024, 1024) == (768, 768)
    assert _plan_blocks(1280, 1024, 1024) == (640, 640)
    assert _plan_blocks(1152, 1024, 1024) == (384, 384)
    assert _plan_blocks(8191, 1024, 1024) is None   # prime: XLA fallback

    rng = np.random.default_rng(7)
    q, k, v = (jnp.asarray(rng.standard_normal((1, 384, 1, 16)),
                           jnp.float32) for _ in range(3))
    out = flash_attention(q, k, v, True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_resnet18_forward_and_grad():
    from ray_tpu.models.resnet import resnet18
    model = resnet18(num_classes=10, dtype="float32")
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)

    def loss(params):
        out, _ = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"])
        return jnp.mean(out ** 2)

    g = jax.grad(loss)(variables["params"])
    assert jax.tree.all(jax.tree.map(lambda a: bool(jnp.all(jnp.isfinite(a))), g))
