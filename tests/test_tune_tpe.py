"""TPE searcher + logger callbacks (reference: tune/search/ model-based
searchers via optuna et al., tune/logger/ csv/json/tensorboard)."""

import csv
import json
import math
import os
import random

import pytest

from ray_tpu import tune
from ray_tpu.train.config import RunConfig
from ray_tpu.tune.loggers import encode_event, read_records, write_record
from ray_tpu.tune.tpe import TPESearcher


def _rosen_ish(cfg):
    return (cfg["x"] - 0.3) ** 2 + (cfg["y"] + 0.1) ** 2


def _drive(searcher, objective, n):
    best = math.inf
    for i in range(n):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        val = objective(cfg)
        searcher.on_trial_complete(tid, {"loss": val})
        best = min(best, val)
    return best


def test_tpe_beats_random_on_quadratic():
    """Seeded head-to-head on a smooth response surface: 100 evaluations
    each across 3 seeds; TPE must beat random on every one AND land at
    least 5x closer at the median (across 12 seeds TPE wins 9 with a ~20x
    better median; the fixed seeds keep the assertion deterministic)."""
    space = {"x": tune.uniform(-1.0, 1.0), "y": tune.uniform(-1.0, 1.0)}
    tpe_bests, rand_bests = [], []
    for seed in (0, 7, 9):
        tpe_bests.append(_drive(
            TPESearcher(space, metric="loss", mode="min", seed=seed,
                        n_initial=15), _rosen_ish, 100))
        rng = random.Random(seed)
        rand_bests.append(min(
            _rosen_ish({k: d.sample(rng) for k, d in space.items()})
            for _ in range(100)))
    for t, r in zip(tpe_bests, rand_bests):
        assert t < r, (tpe_bests, rand_bests)
    assert sorted(tpe_bests)[1] * 5 < sorted(rand_bests)[1]


def test_tpe_categorical_and_log_scale():
    """Category quality + log-scale floats: TPE concentrates on the good
    category and the right order of magnitude."""
    space = {"opt": tune.choice(["bad1", "good", "bad2"]),
             "lr": tune.loguniform(1e-5, 1e-1)}

    def objective(cfg):
        penalty = 0.0 if cfg["opt"] == "good" else 1.0
        return penalty + abs(math.log10(cfg["lr"]) + 3.0)  # best at 1e-3

    s = TPESearcher(space, metric="loss", mode="min", seed=3,
                    n_initial=12)
    _drive(s, objective, 80)
    tail = []
    for i in range(10):
        cfg = s.suggest(f"probe{i}")
        tail.append(cfg)
        s.on_trial_complete(f"probe{i}", {"loss": objective(cfg)})
    good_frac = sum(1 for c in tail if c["opt"] == "good") / len(tail)
    assert good_frac >= 0.7, tail
    lrs = [c["lr"] for c in tail]
    assert sum(1 for lr in lrs if 1e-4 <= lr <= 1e-2) >= 6, lrs


def test_tpe_max_mode_and_int():
    space = {"n": tune.randint(1, 100)}
    s = TPESearcher(space, metric="acc", mode="max", seed=11, n_initial=8)

    def objective(cfg):
        return -abs(cfg["n"] - 42)       # maximized at n=42

    best = -math.inf
    for i in range(60):
        cfg = s.suggest(f"t{i}")
        val = objective(cfg)
        s.on_trial_complete(f"t{i}", {"acc": val})
        best = max(best, val)
    assert best >= -3, best


def test_tpe_in_tuner_lazy_suggest(ray_session, tmp_path):
    """End-to-end through the Tuner: configs must resolve lazily at trial
    launch so later suggestions see earlier results."""
    def trainable(config):
        tune.report({"loss": (config["x"] - 0.5) ** 2})

    searcher = TPESearcher({"x": tune.uniform(0.0, 1.0)},
                           metric="loss", mode="min", seed=5, n_initial=4)
    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.uniform(0.0, 1.0)},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    search_alg=searcher, num_samples=10,
                                    max_concurrent_trials=1),
        run_config=RunConfig(name="tpe", storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert len(grid) == 10
    assert not grid.errors
    # the searcher actually observed completions (lazy path engaged)
    assert len(searcher._history) == 10
    best = grid.get_best_result("loss", "min")
    assert best.metrics["loss"] < 0.05


def test_tpe_under_concurrency_limiter(ray_session, tmp_path):
    """ConcurrencyLimiter.suggest returning None means 'at capacity',
    not 'exhausted' — every trial must still run (regression: trials
    were silently TERMINATED)."""
    def trainable(config):
        tune.report({"loss": abs(config["x"])})

    searcher = tune.ConcurrencyLimiter(
        TPESearcher({"x": tune.uniform(-1.0, 1.0)},
                    metric="loss", mode="min", seed=2, n_initial=2),
        max_concurrent=2)
    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.uniform(-1.0, 1.0)},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    search_alg=searcher, num_samples=6),
        run_config=RunConfig(name="lim", storage_path=str(tmp_path))).fit()
    assert len(grid) == 6
    assert not grid.errors
    assert all("loss" in r.metrics for r in grid)


def test_tfevents_framing_roundtrip(tmp_path):
    path = str(tmp_path / "events.out.tfevents.test")
    with open(path, "wb") as f:
        write_record(f, encode_event(0, {}))
        write_record(f, encode_event(1, {"loss": 0.5, "acc": 0.9}))
        write_record(f, encode_event(2, {"loss": 0.25}))
    payloads = read_records(path)     # asserts both CRCs per record
    assert len(payloads) == 3
    assert b"loss" in payloads[1] and b"acc" in payloads[1]


def test_logger_callbacks_write_files(ray_session, tmp_path):
    def trainable(config):
        for i in range(3):
            tune.report({"score": config["x"] * (i + 1)})

    cbs = [tune.JsonLoggerCallback(), tune.CSVLoggerCallback(),
           tune.TensorBoardLoggerCallback()]
    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1.0, 2.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="loggers", storage_path=str(tmp_path),
                             callbacks=cbs)).fit()
    assert len(grid) == 2 and not grid.errors
    for result in grid:
        trial_dir = result.path
        with open(os.path.join(trial_dir, "result.json")) as f:
            lines = [json.loads(line) for line in f]
        # 3 reports + the function-trainable's final done marker
        assert len(lines) == 4 and lines[-1]["done"] is True
        assert lines[-1]["score"] in (3.0, 6.0)
        with open(os.path.join(trial_dir, "progress.csv")) as f:
            rows = list(csv.DictReader(f))
        assert len(rows) == 4 and "score" in rows[0]
        events = [p for name in os.listdir(trial_dir)
                  if name.startswith("events.out.tfevents")
                  for p in read_records(os.path.join(trial_dir, name))]
        # header + 4 results
        assert len(events) == 5
        assert sum(1 for p in events if b"ray_tpu/score" in p) == 4
