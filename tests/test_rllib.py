"""RLlib-equivalent tests.

Modeled on the reference's test strategy (SURVEY.md §4): pure-logic unit
tests for math components (V-trace, GAE, replay priorities — like
`rllib/algorithms/impala/tests/test_vtrace.py`), plus short
learning-regression runs with reward thresholds (the reference's
`tuned_examples/*.yaml` regression oracles, rllib/BUILD:152-162)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.rllib.core.distributions import Categorical, DiagGaussian
from ray_tpu.rllib.env.jax_env import CartPole, EagerJaxEnv, Pendulum
from ray_tpu.rllib.replay_buffers import (
    PrioritizedReplayBuffer, ReplayBuffer)
from ray_tpu.rllib.sample_batch import SampleBatch, compute_gae, concat_samples


# ---------------------------------------------------------------------------
# Math units
# ---------------------------------------------------------------------------


def test_sample_batch_ops():
    b1 = SampleBatch({"obs": np.ones((4, 3)), "rewards": np.arange(4.0)})
    b2 = SampleBatch({"obs": np.zeros((2, 3)), "rewards": np.arange(2.0)})
    cat = concat_samples([b1, b2])
    assert cat.count == 6
    mbs = list(cat.minibatches(2))
    assert len(mbs) == 3 and all(m.count == 2 for m in mbs)


def test_gae_matches_manual():
    r = np.array([1.0, 1.0, 1.0], np.float32)
    v = np.array([0.5, 0.4, 0.3], np.float32)
    d = np.array([False, False, True])
    out = compute_gae(r, v, d, last_value=9.9, gamma=0.9, lam=0.8)
    # terminal step: delta = 1 - 0.3
    a2 = 0.7
    a1 = (1 + 0.9 * 0.3 - 0.4) + 0.9 * 0.8 * a2
    a0 = (1 + 0.9 * 0.4 - 0.5) + 0.9 * 0.8 * a1
    np.testing.assert_allclose(out["advantages"], [a0, a1, a2], rtol=1e-5)


def test_vtrace_on_policy_reduces_to_returns():
    """With target==behaviour (rho=1) and lambda=1, vs is the n-step
    bootstrapped return (V-trace paper, remark 1)."""
    from ray_tpu.rllib.algorithms.impala import vtrace
    T = 5
    logp = jnp.zeros(T)
    rewards = jnp.ones(T)
    values = jnp.asarray(np.linspace(0.2, 1.0, T), jnp.float32)
    dones = jnp.zeros(T, bool)
    last_v = jnp.asarray(2.0)
    vs, pg = vtrace(logp, logp, rewards, values, dones, last_v,
                    gamma=0.9, lambda_=1.0, clip_rho=1.0, clip_pg_rho=1.0)
    # manual n-step return
    expect = []
    acc = float(last_v)
    for t in reversed(range(T)):
        acc = 1.0 + 0.9 * acc
        expect.append(acc)
    np.testing.assert_allclose(np.asarray(vs), expect[::-1], rtol=1e-5)


def test_categorical_dist():
    logits = jnp.asarray([[2.0, 0.0, -1.0]])
    dist = Categorical(logits)
    p = np.exp(np.asarray(jax.nn.log_softmax(logits)))[0]
    np.testing.assert_allclose(
        float(dist.entropy()[0]), -(p * np.log(p)).sum(), rtol=1e-5)
    np.testing.assert_allclose(
        float(dist.logp(jnp.asarray([0]))[0]), np.log(p[0]), rtol=1e-5)
    assert int(dist.deterministic()[0]) == 0


def test_gaussian_dist():
    dist = DiagGaussian(jnp.zeros((1, 2)), jnp.zeros((1, 2)))
    lp = float(dist.logp(jnp.zeros((1, 2)))[0])
    np.testing.assert_allclose(lp, -np.log(2 * np.pi), rtol=1e-5)
    kl = float(dist.kl(DiagGaussian(jnp.ones((1, 2)),
                                    jnp.zeros((1, 2))))[0])
    np.testing.assert_allclose(kl, 1.0, rtol=1e-5)   # 2 dims * 0.5


def test_replay_buffer_ring():
    buf = ReplayBuffer(capacity=10)
    buf.add_batch({"x": np.arange(8.0)})
    assert len(buf) == 8
    buf.add_batch({"x": np.arange(8.0, 16.0)})
    assert len(buf) == 10
    s = buf.sample(32)
    assert s["x"].shape == (32,)
    assert s["x"].max() >= 10      # new data present after wraparound


def test_prioritized_buffer_biases_sampling():
    buf = PrioritizedReplayBuffer(capacity=128, alpha=1.0, seed=0)
    buf.add_batch({"x": np.arange(100.0)})
    # give item 7 overwhelming priority
    buf.update_priorities(np.arange(100), np.full(100, 1e-3))
    buf.update_priorities(np.array([7]), np.array([100.0]))
    s = buf.sample(256)
    frac = (s["x"] == 7.0).mean()
    assert frac > 0.9
    assert "weights" in s and s["weights"].min() > 0


# ---------------------------------------------------------------------------
# Environments
# ---------------------------------------------------------------------------


def test_cartpole_pd_controller_survives():
    env = EagerJaxEnv(CartPole({}), seed=0)
    obs = env.reset()
    total = 0
    for _ in range(500):
        obs, r, done, _ = env.step(int(obs[2] + 0.5 * obs[3] > 0))
        total += r
        if done:
            break
    assert total > 400


def test_pendulum_shapes():
    env = Pendulum({})
    state, obs = env.reset(jax.random.PRNGKey(0))
    assert obs.shape == (3,)
    state, obs, r, done, _ = env.step(
        state, jnp.asarray([0.5]), jax.random.PRNGKey(1))
    assert float(r) <= 0          # pendulum cost is negative reward


# ---------------------------------------------------------------------------
# Learning regressions (reward thresholds, short budgets)
# ---------------------------------------------------------------------------


def test_ppo_cartpole_learns():
    from ray_tpu.rllib.algorithms.ppo import PPOConfig
    algo = (PPOConfig().environment("CartPole-v1")
            .training(num_sgd_iter=4, sgd_minibatch_size=256)
            .rollouts(num_envs_per_worker=8, rollout_fragment_length=64)
            .debugging(seed=0)
            .build())
    best = 0.0
    for _ in range(30):
        r = algo.train()
        rew = r.get("episode_reward_mean")
        if rew == rew:      # not NaN
            best = max(best, rew)
    assert best > 60, best


def test_dqn_cartpole_learns():
    from ray_tpu.rllib.algorithms.dqn import DQNConfig
    algo = (DQNConfig().environment("CartPole-v1")
            .training(epsilon_timesteps=15_000)
            .debugging(seed=0)
            .build())
    best = 0.0
    for _ in range(120):
        r = algo.train()
        rew = r.get("episode_reward_mean")
        if rew == rew:
            best = max(best, rew)
    assert best > 60, best


def test_dqn_prioritized_replay_runs():
    from ray_tpu.rllib.algorithms.dqn import DQNConfig
    algo = (DQNConfig().environment("CartPole-v1")
            .training(prioritized_replay=True, learning_starts=200,
                      n_updates_per_iter=4)
            .build())
    for _ in range(5):
        r = algo.train()
    assert r["buffer_size"] > 0


def test_ppo_pendulum_continuous_runs():
    from ray_tpu.rllib.algorithms.ppo import PPOConfig
    algo = (PPOConfig().environment("Pendulum-v1")
            .training(num_sgd_iter=2, sgd_minibatch_size=128)
            .rollouts(num_envs_per_worker=4, rollout_fragment_length=32)
            .build())
    r = algo.train()
    assert np.isfinite(r["policy_loss"])


def test_algorithm_checkpoint_roundtrip(tmp_path):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig
    algo = (PPOConfig().environment("CartPole-v1")
            .rollouts(num_envs_per_worker=2, rollout_fragment_length=16)
            .build())
    algo.train()
    ckpt = algo.save()
    algo2 = (PPOConfig().environment("CartPole-v1")
             .rollouts(num_envs_per_worker=2, rollout_fragment_length=16)
             .build())
    algo2.restore(ckpt)
    a = jax.tree.leaves(algo.params)
    b = jax.tree.leaves(algo2.params)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# Distributed paths (shared cluster fixture)
# ---------------------------------------------------------------------------


def test_ppo_workerset_path(ray_session):
    """PPO with remote rollout actors (the reference's default shape)."""
    from ray_tpu.rllib.algorithms.ppo import PPOConfig
    algo = (PPOConfig().environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=64)
            .training(num_sgd_iter=2, sgd_minibatch_size=64)
            .build())
    try:
        r1 = algo.train()
        r2 = algo.train()
        assert np.isfinite(r2["policy_loss"])
        assert r2["num_env_steps_sampled_this_iter"] == 128
    finally:
        algo.cleanup()


def test_impala_learns(ray_session):
    from ray_tpu.rllib.algorithms.impala import IMPALAConfig
    algo = (IMPALAConfig().environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=128)
            .build())
    best = 0.0
    try:
        for _ in range(40):
            r = algo.train()
            rew = r.get("episode_reward_mean")
            if rew == rew:
                best = max(best, rew)
    finally:
        algo.cleanup()
    assert best > 40, best


def test_tune_over_algorithm(ray_session, tmp_path):
    """tune.run(PPO, ...) — Algorithm as Trainable (reference:
    algorithm.py:191 Algorithm IS-A Trainable)."""
    from ray_tpu import tune
    from ray_tpu.rllib.algorithms.ppo import PPO

    grid = tune.run(
        PPO,
        config={"env": "CartPole-v1", "num_envs_per_worker": 4,
                "rollout_fragment_length": 32, "num_sgd_iter": 2,
                "sgd_minibatch_size": 64,
                "lr": tune.grid_search([3e-4, 1e-3])},
        stop={"training_iteration": 2},
        storage_path=str(tmp_path), name="rl_tune")
    assert len(grid) == 2
    assert not grid.errors
    for r in grid:
        assert r.metrics["training_iteration"] == 2
