"""Serve-equivalent tests (modeled on the reference's `serve/tests/`:
test_api, test_deploy, test_autoscaling_policy, test_batching)."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_session(ray_session):
    yield serve
    serve.shutdown()


@serve.deployment(num_replicas=2)
class Doubler:
    def __call__(self, x):
        return x * 2


def test_deploy_and_call(serve_session):
    handle = serve.run(Doubler.bind(), name="t_basic")
    assert handle.call(21) == 42
    refs = [handle.remote(i) for i in range(10)]
    assert ray_tpu.get(refs, timeout=60) == [i * 2 for i in range(10)]


def test_composition_handles(serve_session):
    @serve.deployment
    class Ingress:
        def __init__(self, doubler):
            self.doubler = doubler

        def __call__(self, x):
            return self.doubler.call(x) + 1

    h = serve.run(Ingress.bind(Doubler.bind()), name="t_comp")
    assert h.call(10) == 21
    st = serve.status()
    assert st["t_comp:Ingress"]["status"] == "RUNNING"
    assert st["t_comp:Doubler"]["replicas"] == 2


def test_method_calls_and_function_deployment(serve_session):
    @serve.deployment
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self, by):
            self.n += by
            return self.n

        def __call__(self, x):
            return x

    h = serve.run(Counter.bind(), name="t_method")
    assert h.incr.call(5) == 5
    assert h.incr.call(3) == 8

    @serve.deployment
    def square(x):
        return x * x

    hf = serve.run(square.bind(), name="t_fn")
    assert hf.call(7) == 49


def test_http_proxy(serve_session):
    @serve.deployment
    class Echo:
        def __call__(self, req):
            return {"path": req.path, "q": req.query,
                    "body": req.json()}

    serve.run(Echo.bind(), name="t_http")
    proxy = serve.start(http_options={"port": 0})
    info = ray_tpu.get(proxy.ready.remote(), timeout=30)
    serve.set_route("/echo", "Echo", "t_http")
    url = f"http://127.0.0.1:{info['port']}/echo?a=1"
    resp = urllib.request.urlopen(urllib.request.Request(
        url, data=json.dumps({"hi": 5}).encode()))
    out = json.loads(resp.read())
    assert out == {"path": "/echo", "q": {"a": "1"}, "body": {"hi": 5}}
    # 404 for unknown route
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{info['port']}/nope")
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_priority_rides_the_serve_path(serve_session):
    """A request's priority class travels handle -> replica contextvar
    (and proxy header -> handle.options), with the deployment's
    `default_priority` as the fallback — the serve-side plumbing of the
    engine's priority classes."""
    @serve.deployment(default_priority=1)
    class WhatClass:
        def __call__(self, req):
            return serve.get_request_priority()

    h = serve.run(WhatClass.bind(), name="t_prio")
    assert h.call(0) == 1                       # deployment default
    assert h.options(priority=3).call(0) == 3   # per-call override
    assert h.call(0) == 1                       # options() didn't stick

    proxy = serve.start(http_options={"port": 0})
    info = ray_tpu.get(proxy.ready.remote(), timeout=30)
    serve.set_route("/prio", "WhatClass", "t_prio")
    base = f"http://127.0.0.1:{info['port']}/prio"
    req = urllib.request.Request(base, data=b"{}")
    req.add_header("X-Serve-Priority", "2")
    assert json.loads(urllib.request.urlopen(req).read()) == 2
    assert json.loads(urllib.request.urlopen(
        urllib.request.Request(f"{base}?priority=4",
                               data=b"{}")).read()) == 4
    try:
        urllib.request.urlopen(urllib.request.Request(
            base, data=b"{}", headers={"X-Serve-Priority": "nope"}))
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_batching(serve_session):
    @serve.deployment(max_concurrent_queries=16)
    class Batched:
        def __init__(self):
            self.sizes = []

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
        def handle(self, xs):
            self.sizes.append(len(xs))
            return [x + 100 for x in xs]

        def __call__(self, x):
            return self.handle(x)

        def get_sizes(self):
            return self.sizes

    h = serve.run(Batched.bind(), name="t_batch")
    outs = ray_tpu.get([h.remote(i) for i in range(8)], timeout=60)
    assert sorted(outs) == [100 + i for i in range(8)]
    assert max(h.get_sizes.call()) > 1      # actually batched


def test_autoscaling_up(serve_session):
    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_num_ongoing_requests_per_replica": 1,
        "downscale_delay_s": 300})
    class Slow:
        def __call__(self, x):
            time.sleep(1.5)
            return x

    h = serve.run(Slow.bind(), name="t_auto")
    refs = [h.remote(i) for i in range(12)]
    grew = False
    for _ in range(8):
        time.sleep(0.5)
        st = serve.status()["t_auto:Slow"]
        if st["target_replicas"] >= 2:
            grew = True
            break
    ray_tpu.get(refs, timeout=120)
    assert grew, serve.status()


def test_replica_restart_on_death(serve_session):
    @serve.deployment(num_replicas=1)
    class Svc:
        def __call__(self, x):
            return x + 1

    h = serve.run(Svc.bind(), name="t_restart")
    assert h.call(1) == 2
    # kill the replica behind the controller's back
    from ray_tpu.serve.controller import get_controller
    c = get_controller()
    _, replicas = ray_tpu.get(
        c.get_replicas.remote("Svc", "t_restart", -1), timeout=30)
    ray_tpu.kill(replicas[0])
    # controller health check replaces it; handle retries through death
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            assert h.call(5, timeout=10) == 6
            break
        except Exception:
            time.sleep(0.5)
    else:
        pytest.fail("replica never recovered")


def test_redeploy_updates_code(serve_session):
    @serve.deployment
    class V:
        def __call__(self, x):
            return "v1"

    h = serve.run(V.bind(), name="t_upgrade")
    assert h.call(0) == "v1"

    @serve.deployment(name="V")
    class V2:
        def __call__(self, x):
            return "v2"

    h2 = serve.run(V2.bind(), name="t_upgrade")
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if h2.call(0) == "v2":
                break
        except Exception:
            pass
        time.sleep(0.3)
    else:
        pytest.fail("redeploy never took effect")

    serve.delete("t_upgrade")
    assert "t_upgrade:V" not in serve.status()


def test_streaming_response_http(serve_session):
    """A generator deployment streams chunked bytes through the proxy —
    the response arrives incrementally, not as one buffered body
    (reference: streaming replies, _private/replica.py:249)."""
    @serve.deployment
    class Streamer:
        def __call__(self, req):
            def gen():
                for i in range(40):
                    yield f"chunk-{i};"
            return serve.StreamingResponse(gen(), content_type="text/plain")

    serve.run(Streamer.bind(), name="streamapp")
    proxy = serve.start(http_options={"port": 0})
    info = ray_tpu.get(proxy.ready.remote(), timeout=30)
    serve.set_route("/stream", "Streamer", "streamapp")
    url = f"http://127.0.0.1:{info['port']}/stream"
    resp = urllib.request.urlopen(url, timeout=60)
    assert resp.headers.get("Transfer-Encoding") == "chunked"
    body = resp.read().decode()
    assert body == "".join(f"chunk-{i};" for i in range(40))


def test_streaming_via_handle(serve_session):
    """Python-side streaming consumption without HTTP."""
    @serve.deployment
    class Gen:
        def __call__(self, n):
            def producer():
                for i in range(n):
                    yield i * i
            return producer()

    serve.run(Gen.bind(), name="genapp")
    h = serve.get_deployment_handle("Gen", "genapp")
    got = list(h.stream(5))
    assert got == [0, 1, 4, 9, 16]


def test_proxy_concurrent_requests(serve_session):
    """Slow replicas must not serialize the proxy: 8 concurrent requests
    against 2 replicas of a 0.4s deployment finish in ~4 batch rounds,
    far under the 3.2s serial floor."""
    import concurrent.futures

    @serve.deployment(num_replicas=2, max_concurrent_queries=4)
    class Slow:
        def __call__(self, req):
            time.sleep(0.4)
            return "ok"

    serve.run(Slow.bind(), name="slowapp")
    proxy = serve.start(http_options={"port": 0})
    info = ray_tpu.get(proxy.ready.remote(), timeout=30)
    serve.set_route("/slow", "Slow", "slowapp")
    url = f"http://127.0.0.1:{info['port']}/slow"

    def one(_):
        return urllib.request.urlopen(url, timeout=60).read()

    t0 = time.time()
    with concurrent.futures.ThreadPoolExecutor(max_workers=8) as pool:
        results = list(pool.map(one, range(8)))
    elapsed = time.time() - t0
    assert all(r == b"ok" for r in results)
    assert elapsed < 2.4, f"proxy serialized requests: {elapsed:.2f}s"


def test_async_replica_soak_1k_concurrent(ray_session):
    """1000 concurrent slow requests overlap on ONE replica's event loop
    (reference: serve's async replica, `serve/_private/replica.py:429`).
    Thread-per-call would need 1000 threads; serialized execution would
    take ~1000s. The async replica holds them all on awaits."""
    @serve.deployment(max_concurrent_queries=1000)
    class Slow:
        async def __call__(self, i):
            import asyncio
            await asyncio.sleep(1.0)
            return i

    h = serve.run(Slow.bind(), name="t_soak")
    assert ray_tpu.get(h.remote(-1), timeout=60) == -1   # warm
    t0 = time.time()
    out = ray_tpu.get([h.remote(i) for i in range(1000)], timeout=240)
    dt = time.time() - t0
    assert out == list(range(1000))
    assert dt < 60, f"requests serialized: {dt:.1f}s for 1000x1s"
