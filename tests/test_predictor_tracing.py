"""Batch inference (Predictor/BatchPredictor) and tracing spans.

Counterpart of the reference's `train/tests/test_predictor.py`,
`test_batch_predictor.py`, and `tests/test_tracing.py`.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import BatchPredictor, Checkpoint, JaxPredictor
from ray_tpu.util import tracing


@pytest.fixture
def cluster(ray_session):
    return ray_session


def _linear_apply(params, x):
    return x @ params["w"] + params["b"]


def test_jax_predictor_roundtrip():
    params = {"w": np.ones((4, 2), np.float32),
              "b": np.zeros(2, np.float32)}
    ckpt = Checkpoint.from_dict({"params": params})
    pred = JaxPredictor.from_checkpoint(ckpt, apply_fn=_linear_apply,
                                        input_column="x")
    batch = {"x": np.ones((8, 4), np.float32)}
    out = pred._predict_numpy(batch)
    assert out["predictions"].shape == (8, 2)
    np.testing.assert_allclose(out["predictions"], 4.0)
    # plain-array input path
    out2 = pred.predict(np.ones((3, 4), np.float32))
    np.testing.assert_allclose(out2["predictions"], 4.0)


def test_batch_predictor_over_dataset(cluster):
    from ray_tpu import data as rdata
    params = {"w": np.full((4, 1), 2.0, np.float32),
              "b": np.zeros(1, np.float32)}
    ckpt = Checkpoint.from_dict({"params": params})
    bp = BatchPredictor.from_checkpoint(
        ckpt, JaxPredictor, apply_fn=_linear_apply, input_column="x")
    ds = rdata.from_items(
        [{"x": np.ones(4, np.float32) * i, "id": i} for i in range(32)])
    out = bp.predict(ds, batch_size=8).take_all()
    assert len(out) == 32
    by_id = {int(r["id"]): r for r in out}
    np.testing.assert_allclose(by_id[3]["predictions"], 24.0)
    np.testing.assert_allclose(by_id[0]["predictions"], 0.0)


def test_tracing_spans_nest_and_export(tmp_path):
    tracing.clear_spans()
    tracing.enable_tracing()
    with tracing.span("outer", {"k": "v"}):
        with tracing.span("inner"):
            pass
    spans = tracing.get_spans()
    inner = next(s for s in spans if s["name"] == "inner")
    outer = next(s for s in spans if s["name"] == "outer")
    assert inner["parent_span_id"] == outer["span_id"]
    assert inner["trace_id"] == outer["trace_id"]
    assert outer["end_ns"] > outer["start_ns"]

    path = tmp_path / "spans.json"
    assert tracing.export_json(str(path)) >= 2
    events = tracing.spans_to_chrome_trace()
    assert any(e["name"] == "outer" for e in events)


def test_tracing_error_status():
    tracing.clear_spans()
    tracing.enable_tracing()
    with pytest.raises(ValueError):
        with tracing.span("boom"):
            raise ValueError("x")
    s = next(s for s in tracing.get_spans() if s["name"] == "boom")
    assert s["status"] == "ERROR" and "ValueError" in \
        s["attributes"]["exception"]


def test_tracing_inside_tasks(cluster):
    tracing.enable_tracing()

    @ray_tpu.remote
    def traced_work(i):
        from ray_tpu.util import tracing as t
        with t.span("work", {"i": i}):
            return i * 2

    assert ray_tpu.get([traced_work.remote(i) for i in range(3)]) == \
        [0, 2, 4]
