"""Telemetry plane (util/telemetry.py): flight-recorder request tracing,
the stats()->metrics bridge behind the dashboard's /metrics, the train
step-time breakdown, and the runtime retrace sentinel.

Acceptance pins of the observability PR: /metrics serves engine + train
series in parseable Prometheus exposition; /api/timeline interleaves
per-request spans with task events; a forced recompile on a pinned path
after warmup trips `retraces_unexpected` with ONE WARN while armed
same-shape traffic reports zero.
"""

import gc
import json
import logging
import re
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from ray_tpu.models import gpt
from ray_tpu.util import metrics
from ray_tpu.util import telemetry
from ray_tpu.util import tracing


def tiny_cfg(**kw):
    return gpt.GPTConfig(**{**dict(
        vocab_size=128, d_model=32, n_layers=1, n_heads=2, d_ff=64,
        max_seq_len=64, dtype="float32"), **kw})


def assert_prometheus_parses(text):
    """Every non-comment line must match the exposition sample grammar
    with a float-parseable value — the property check_invariants pins."""
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = telemetry._PROM_SAMPLE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        float(m.group(1))


# ---------------------------------------------------------------------------
# prometheus rendering: sanitization + canonical le round-trip
# ---------------------------------------------------------------------------

class TestPrometheusRendering:
    def test_sanitize_name(self):
        assert metrics.sanitize_name("engine0/ttft ms") == \
            "engine0_ttft_ms"
        assert metrics.sanitize_name("0starts_bad") == "_0starts_bad"
        assert metrics.sanitize_name("fine_name:sub") == "fine_name:sub"
        # labels additionally exclude ':'
        assert metrics.sanitize_name("a:b", label=True) == "a_b"

    def test_format_float_canonical(self):
        assert metrics.format_float(2) == "2.0"
        assert metrics.format_float(0.001) == "0.001"
        assert metrics.format_float(float("inf")) == "+Inf"
        assert metrics.format_float(float("-inf")) == "-Inf"
        assert metrics.format_float(np.float32(1.0)) == "1.0"
        # round-trippable with float()
        for v in (2, 0.001, 0.5, 1e-9, 123456.75):
            assert float(metrics.format_float(v)) == float(v)

    def test_histogram_le_labels_roundtrip(self):
        bounds = [0.1, 0.5, 1, 5]
        h = metrics.Histogram("tele_rt_hist", "round trip",
                              boundaries=bounds, tag_keys=("source",))
        for v in (0.05, 0.3, 2.0, 100.0):
            h.observe(v, tags={"source": "t"})
        text = metrics.render_prometheus(metrics.snapshot())
        assert_prometheus_parses(text)
        pat = re.compile(
            r'^ray_tpu_tele_rt_hist_bucket\{.*le="([^"]+)".* (\d+)$')
        les, cums = [], []
        for line in text.splitlines():
            m = pat.match(line)
            if m:
                les.append(float(m.group(1)))   # must round-trip
                cums.append(int(m.group(2)))
        assert les == [0.1, 0.5, 1.0, 5.0, float("inf")]
        assert cums == sorted(cums) and cums[-1] == 4
        assert 'ray_tpu_tele_rt_hist_count{source="t"} 4' in text

    def test_weird_metric_name_renders_parseable(self):
        metrics.Counter("tele weird/name", "d").inc(2)
        text = metrics.render_prometheus(metrics.snapshot())
        assert "ray_tpu_tele_weird_name 2.0" in text
        assert_prometheus_parses(text)


# ---------------------------------------------------------------------------
# tracing ring + context propagation
# ---------------------------------------------------------------------------

class TestTracingRing:
    @pytest.fixture(autouse=True)
    def _enabled(self, monkeypatch):
        monkeypatch.setattr(tracing, "_enabled", True)
        prev_cap = tracing.max_spans()
        tracing.clear_spans()
        yield
        tracing.set_max_spans(prev_cap)
        tracing.clear_spans()

    def test_ring_cap_counts_evictions(self):
        tracing.set_max_spans(4)
        for i in range(10):
            with tracing.span(f"ring{i}"):
                pass
        spans = tracing.get_spans()
        assert len(spans) == 4
        assert [s["name"] for s in spans] == \
            ["ring6", "ring7", "ring8", "ring9"]
        assert tracing.dropped_spans() == 6

    def test_attach_context_across_thread(self):
        got = {}

        def worker(ctx):
            token = tracing.attach_context(ctx)
            try:
                with tracing.span("child") as c:
                    got["child"] = c
            finally:
                tracing.detach_context(token)

        with tracing.span("parent") as p:
            t = threading.Thread(target=worker,
                                 args=(tracing.capture_context(),))
            t.start()
            t.join()
        assert got["child"]["parent_span_id"] == p["span_id"]
        assert got["child"]["trace_id"] == p["trace_id"]
        # without attach, a fresh thread starts a fresh trace
        got.clear()
        t = threading.Thread(target=worker, args=(None,))
        t.start()
        t.join()
        assert got["child"]["parent_span_id"] is None


# ---------------------------------------------------------------------------
# flight recorder (unit: hooks driven directly)
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def _request(self, rec, rid, outcome="finished", tokens=3):
        rec.on_submit(rid, 5)
        rec.on_admit(rid, 4, True)
        rec.on_prefill_chunk(rid, 8, 8, 1e-4)
        rec.on_first_token(rid, 2e-3)
        for _ in range(tokens):
            rec.on_token(rid)
        rec.on_swap_crossing(rid)
        rec.on_finish(rid, outcome)

    def test_lifecycle_spans(self):
        rec = telemetry.FlightRecorder("recunit-a", sample=1.0,
                                       max_spans=64)
        self._request(rec, 1)
        spans = rec.get_spans()
        names = {s["name"] for s in spans}
        assert {"engine.request", "queue_wait", "prefill_chunk",
                "first_token", "swap_crossing", "decode"} <= names
        root = next(s for s in spans if s["name"] == "engine.request")
        assert root["attributes"]["outcome"] == "finished"
        assert root["attributes"]["tokens"] == 3
        assert root["attributes"]["prefix_hit_tokens"] == 4
        assert root["attributes"]["cow"] is True
        # one trace: every span shares the root's trace and parents it
        for s in spans:
            assert s["trace_id"] == root["trace_id"]
            assert s["end_ns"] >= s["start_ns"]
            if s is not root:
                assert s["parent_span_id"] == root["span_id"]
        assert rec.live_requests() == 0
        events = rec.chrome_events()
        # durations render as "X", instants (first_token/swap) as "i"
        assert {e["ph"] for e in events} == {"X", "i"}
        assert all(e["cat"] == "request" for e in events)
        inst = next(e for e in events if e["name"] == "first_token")
        assert inst["s"] == "t" and inst["tid"] == "recunit-a/r1"
        rec.check_invariants()

    def test_ring_bound_and_dropped_counter(self):
        rec = telemetry.FlightRecorder("recunit-b", sample=1.0,
                                       max_spans=8)
        for rid in range(5):
            self._request(rec, rid)
        assert len(rec.get_spans()) == 8
        assert rec.dropped_spans > 0
        rec.check_invariants()
        rec.clear()
        assert rec.get_spans() == [] and rec.dropped_spans == 0

    def test_sampling_zero_records_nothing(self):
        rec = telemetry.FlightRecorder("recunit-c", sample=0.0)
        self._request(rec, 1)
        assert rec.requests_seen == 1
        assert rec.requests_traced == 0
        assert rec.get_spans() == []

    def test_cancel_closes_open_queue_span(self):
        rec = telemetry.FlightRecorder("recunit-d", sample=1.0)
        rec.on_submit(7, 3)
        rec.on_finish(7, "cancelled")   # cancelled while still queued
        spans = rec.get_spans()
        root = next(s for s in spans if s["name"] == "engine.request")
        queue = next(s for s in spans if s["name"] == "queue_wait")
        assert root["attributes"]["outcome"] == "cancelled"
        assert queue["end_ns"] is not None
        assert "decode" not in {s["name"] for s in spans}


# ---------------------------------------------------------------------------
# retrace sentinel (unit: synthetic counters)
# ---------------------------------------------------------------------------

class TestRetraceSentinel:
    def test_cap_watch_warns_once_counts_every_excess(self, caplog):
        count = [1]
        s = telemetry.RetraceSentinel("sentunit-a")
        s.watch("decode", lambda: count[0], cap=1)
        assert s.watching()            # cap watches armed at birth
        assert s.check() == 0
        with caplog.at_level(logging.WARNING,
                             logger="ray_tpu.util.telemetry"):
            count[0] = 3
            assert s.check() == 2
            count[0] = 4
            assert s.check() == 1      # counted again...
        warns = [r for r in caplog.records
                 if "retrace sentinel" in r.message]
        assert len(warns) == 1          # ...but ONE warn per path
        assert "'decode'" in warns[0].message
        assert s.retraces_unexpected == 3
        assert len(s.events) == 2 and s.events[0]["path"] == "decode"

    def test_dynamic_watch_silent_until_armed(self):
        count = [3]
        s = telemetry.RetraceSentinel("sentunit-b")
        s.watch("prefill", lambda: count[0])     # bucket-dependent
        count[0] = 5
        assert s.check() == 0 and not s.watching()   # warmup: no limit
        s.arm()                                   # baseline = 5
        assert s.watching() and s.armed
        assert s.check() == 0
        count[0] = 7
        assert s.check() == 2
        assert s.retraces_unexpected == 2
        s.reset()
        assert s.retraces_unexpected == 0 and not s.watching()


# ---------------------------------------------------------------------------
# stats() -> metrics bridge
# ---------------------------------------------------------------------------

class _Source:
    def __init__(self):
        self.d = {"decode_tokens": 5, "occupancy": 0.5,
                  "spec": "off-string-skipped", "flag": True}

    def stats(self):
        return dict(self.d)


def _series(name):
    for m in metrics.snapshot():
        if m["name"] == name:
            return m["series"]
    return {}


class TestStatsBridge:
    def test_counter_delta_gauge_and_weakref_pruning(self):
        src = _Source()
        name = telemetry.register_stats_source("bridgeunit", src,
                                               kind="bridge")
        try:
            key = (("source", name),)
            # COUNTER_KEYS stat -> delta-tracked counter
            assert _series("bridge_decode_tokens")[key] == 5.0
            src.d["decode_tokens"] = 8
            assert _series("bridge_decode_tokens")[key] == 8.0
            src.d["decode_tokens"] = 2     # upstream reset_stats()
            assert _series("bridge_decode_tokens")[key] == 10.0
            # numeric non-counter stat -> gauge; str/bool skipped
            assert _series("bridge_occupancy")[key] == 0.5
            assert key not in _series("bridge_spec")
            assert key not in _series("bridge_flag")
            assert name in telemetry.summary()["stats_sources"]
        finally:
            del src
            gc.collect()
            metrics.snapshot()             # collect prunes dead weakref
            assert name not in telemetry.summary()["stats_sources"]

    def test_duplicate_name_uniquified(self):
        a, b = _Source(), _Source()
        na = telemetry.register_stats_source("bridgedup", a, kind="bridge")
        nb = telemetry.register_stats_source("bridgedup", b, kind="bridge")
        try:
            assert na == "bridgedup" and nb == "bridgedup-2"
        finally:
            telemetry.unregister_stats_source(na)
            telemetry.unregister_stats_source(nb)

    def test_mfu_helpers(self):
        peak = telemetry.device_peak_flops()
        assert peak > 0
        assert telemetry.mfu(peak * 4, n_devices=4) == pytest.approx(1.0)
        assert telemetry.mfu(0.0) == 0.0


# ---------------------------------------------------------------------------
# engine integration: recorder wiring, stats contract, sentinel
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine_setup():
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def traced_engine(engine_setup):
    """One engine with a streamed request through it — shared by the
    recorder-wiring, stats-contract, and dashboard-scrape tests."""
    from ray_tpu.serve.engine import InferenceEngine
    cfg, params = engine_setup
    eng = InferenceEngine(params, cfg, slots=2, max_len=32,
                          prefill_buckets=(8, 16))
    rid = eng.submit([5, 9, 3], max_new_tokens=4)
    assert len(list(eng.tokens_for(rid))) == 4   # streamed to completion
    eng.run_until_idle()
    return eng


class TestEngineTelemetry:
    def test_recorder_captures_request_lifecycle(self, traced_engine):
        spans = traced_engine._recorder.get_spans()
        names = {s["name"] for s in spans}
        assert {"engine.request", "queue_wait", "prefill_chunk",
                "first_token", "decode"} <= names
        root = next(s for s in spans if s["name"] == "engine.request")
        assert root["attributes"]["outcome"] == "finished"
        assert root["attributes"]["tokens"] == 4
        st = traced_engine.stats()
        assert st["ttft_ms_p50"] > 0
        assert st["ttft_ms_p50"] <= st["ttft_ms_p99"]
        # the recorder's histograms landed in the module registry
        hist = _series("engine_ttft_ms")
        assert any(dict(k)["source"] == traced_engine.name
                   for k in hist), hist

    def test_stats_docstring_contract(self, traced_engine):
        """Every ``key`` the stats() docstring documents exists in the
        dict, and every dict key is documented — both directions, so the
        contract can't silently rot either way."""
        from ray_tpu.serve.engine import InferenceEngine
        documented = set(re.findall(r"``([a-z0-9_]+)``",
                                    InferenceEngine.stats.__doc__))
        actual = set(traced_engine.stats().keys())
        assert documented - actual == set(), \
            f"documented but not returned: {sorted(documented - actual)}"
        assert actual - documented == set(), \
            f"returned but undocumented: {sorted(actual - documented)}"

    def test_armed_sentinel_reports_zero_on_compile_once_traffic(
            self, engine_setup):
        from ray_tpu.serve.engine import InferenceEngine
        cfg, params = engine_setup
        eng = InferenceEngine(params, cfg, slots=2, max_len=32,
                              prefill_buckets=(8, 16))
        for i, temp in enumerate((0.0, 1.0)):     # warmup: bucket 8
            eng.submit([i + 1, i + 2, i + 3], max_new_tokens=3,
                       temperature=temp)
        eng.run_until_idle()
        eng.arm_retrace_sentinel()
        for i in range(3):                        # same shapes, armed
            eng.submit([i + 2, i + 5], max_new_tokens=4,
                       temperature=0.7 * i)
        eng.run_until_idle()
        st = eng.stats()
        assert st["retraces_unexpected"] == 0
        assert st["decode_traces"] == 1

    def test_sentinel_trips_on_new_bucket_after_arm(self, engine_setup,
                                                    caplog):
        """The forced-recompile acceptance test: a prompt landing in a
        prefill bucket never compiled during warmup re-traces the jitted
        prefill AFTER arm() declared warmup over — the sentinel must
        count it and WARN exactly once."""
        from ray_tpu.serve.engine import InferenceEngine
        cfg, params = engine_setup
        eng = InferenceEngine(params, cfg, slots=2, max_len=40,
                              prefill_buckets=(8, 16, 32))
        eng.submit([1, 2, 3, 4, 5, 6], max_new_tokens=2)  # bucket 8
        eng.run_until_idle()
        eng.arm_retrace_sentinel()
        with caplog.at_level(logging.WARNING,
                             logger="ray_tpu.util.telemetry"):
            eng.submit(list(range(1, 31)), max_new_tokens=2)  # bucket 32
            eng.run_until_idle()
        st = eng.stats()
        assert st["retraces_unexpected"] > 0
        warns = [r for r in caplog.records
                 if "retrace sentinel" in r.message]
        assert len(warns) == 1 and "prefill" in warns[0].message
        tripped = st["retraces_unexpected"]
        # traffic in a bucket compiled during warmup adds nothing (the
        # big prompt is NOT re-sent: its blocks are radix-cached now, so
        # a resend would prefill only the tail — a different, smaller
        # chunk bucket, i.e. another legitimate trip)
        eng.submit([7, 8, 9], max_new_tokens=2)   # bucket 8, warmed
        eng.run_until_idle()
        assert eng.stats()["retraces_unexpected"] == tripped
        # the violation is visible in the /api/telemetry summary
        sent = next(s for s in telemetry.summary()["sentinels"]
                    if s["name"] == eng.name)
        assert sent["retraces_unexpected"] == tripped
        assert any(e["path"] == "prefill" for e in sent["events"])

    def test_telemetry_sample_zero_disables_recorder_only(
            self, engine_setup):
        from ray_tpu.serve.engine import InferenceEngine
        cfg, params = engine_setup
        eng = InferenceEngine(params, cfg, slots=2, max_len=32,
                              prefill_buckets=(8, 16),
                              telemetry_sample=0.0)
        eng.submit([4, 2], max_new_tokens=3)
        eng.run_until_idle()
        assert eng._recorder.requests_seen == 1
        assert eng._recorder.requests_traced == 0
        assert eng._recorder.get_spans() == []
        # engine-level latency stats are independent of sampling
        assert eng.stats()["ttft_ms_p50"] > 0


# ---------------------------------------------------------------------------
# train loop: step-time breakdown, MFU/goodput
# ---------------------------------------------------------------------------

class TestTrainLoopTelemetry:
    def test_breakdown_goodput_and_mfu(self):
        from ray_tpu.train import loop

        def step_fn(state, batch):
            time.sleep(1e-3)
            return state + 1, {"loss": np.float32(0.5)}

        tl = loop.TrainLoop(step_fn, metrics_interval=2,
                            flops_per_step=1e9)
        batches = iter([{"x": np.zeros(2)}] * 5)
        state, ms = tl.run(0, batches, num_steps=5)
        assert state == 5 and len(ms) == 5
        bd = tl.last_breakdown
        assert bd["steps"] == 5 and bd["total_s"] > 0
        shares = [bd[f"{k}_share"] for k in
                  ("prefetch", "dispatch", "metrics", "checkpoint",
                   "publish")]
        assert all(0.0 <= s <= 1.0 for s in shares)
        assert sum(shares) <= 1.001
        assert bd["dispatch_s"] >= 5e-3      # five 1ms steps
        assert 0.0 < tl.last_goodput <= 1.0
        assert tl.last_mfu > 0.0
        st = tl.stats()
        assert st["retraces_unexpected"] == 0
        assert st["unroll"] == 1 and st["mfu"] == tl.last_mfu
        assert st["dispatch_share"] == bd["dispatch_share"]
        # the loop registered itself: train_* series reach the registry
        key = (("source", tl.name),)
        assert _series("train_goodput")[key] == tl.last_goodput


# ---------------------------------------------------------------------------
# dashboard endpoints: /metrics scrape + merged /api/timeline
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def dashboard_port(ray_session):
    from ray_tpu.dashboard import start_dashboard
    return start_dashboard(0)   # ephemeral port


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        body = r.read().decode()
        if r.headers.get_content_type() == "application/json":
            return json.loads(body)
        return body


class TestDashboardTelemetry:
    def test_metrics_scrape_serves_engine_and_train_series(
            self, ray_session, dashboard_port, traced_engine):
        from ray_tpu.train import loop
        tl = loop.TrainLoop(lambda s, b: (s, {"loss": 0.0}),
                            flops_per_step=1e6)
        tl.run(0, iter([{"x": np.zeros(1)}] * 2), num_steps=2)
        text = _get(dashboard_port, "/metrics")
        assert_prometheus_parses(text)
        # engine series, tagged by source engine
        line = next(ln for ln in text.splitlines()
                    if ln.startswith("ray_tpu_engine_decode_tokens{"))
        assert f'source="{traced_engine.name}"' in text
        assert float(line.rsplit(" ", 1)[1]) > 0
        # recorder latency histogram made it out as buckets
        assert "ray_tpu_engine_ttft_ms_bucket{" in text
        # train series from the loop that just ran
        assert f'ray_tpu_train_goodput{{source="{tl.name}"}}' in text
        assert "ray_tpu_train_dispatch_s{" in text

    def test_timeline_interleaves_tasks_and_request_spans(
            self, ray_session, dashboard_port, traced_engine):
        import ray_tpu

        @ray_tpu.remote
        def tele_task():
            return 1

        assert ray_tpu.get(tele_task.remote()) == 1
        events = _get(dashboard_port, "/api/timeline")
        cats = {e.get("cat") for e in events}
        assert "task" in cats and "request" in cats
        assert any("tele_task" in e["name"] for e in events
                   if e.get("cat") == "task")
        roots = [e for e in events if e.get("cat") == "request"
                 and e["name"] == "engine.request"]
        assert roots and roots[0]["ph"] == "X"
        assert roots[0]["args"]["outcome"] == "finished"
        # one shared clock: both categories are epoch-µs (dividing by
        # 1e6 gives a unix time near "now"), so request spans sort in
        # among the task events instead of living on a parallel
        # timeline or in different units
        task_ts = [e["ts"] for e in events if e.get("cat") == "task"
                   and "ts" in e]
        now = time.time()
        assert abs(roots[0]["ts"] / 1e6 - now) < 86400
        assert abs(min(task_ts) / 1e6 - now) < 86400

    def test_api_telemetry_summary(self, ray_session, dashboard_port,
                                   traced_engine):
        s = _get(dashboard_port, "/api/telemetry")
        rec = next(r for r in s["recorders"]
                   if r["name"] == traced_engine.name)
        assert rec["requests_traced"] >= 1 and rec["spans"] >= 5
        sent = next(x for x in s["sentinels"]
                    if x["name"] == traced_engine.name)
        assert sent["watching"] is True
        assert s["tracing"]["max_spans"] > 0
        assert s["stats_sources"]


# ---------------------------------------------------------------------------
# self-test
# ---------------------------------------------------------------------------

class TestCheckInvariants:
    def test_passes_after_traffic(self, traced_engine):
        telemetry.check_invariants()

    def test_catches_overflowed_recorder_ring(self):
        rec = telemetry.FlightRecorder("selftest-neg", max_spans=2)
        rec._spans.extend({"name": "x"} for _ in range(5))
        with pytest.raises(AssertionError):
            telemetry.check_invariants()
        del rec
        gc.collect()            # weakset drops it; the plane is clean
        telemetry.check_invariants()
