"""Autoscaler: demand bin-packing, scale-up/down, gang (slice) handling.

Counterpart of the reference's `python/ray/tests/test_autoscaler.py` and
`test_resource_demand_scheduler.py`: pure-logic tests against the fake
provider (SURVEY.md §4.2 — no cloud needed).
"""

import time

from ray_tpu.autoscaler import (
    FakeNodeProvider,
    LoadMetrics,
    ResourceDemandScheduler,
    StandardAutoscaler,
)
from ray_tpu.autoscaler.node_provider import TAG_NODE_KIND, TAG_NODE_TYPE

CPU_TYPE = {"resources": {"CPU": 8}, "min_workers": 0, "max_workers": 10}
TPU_HOST = {"resources": {"CPU": 16, "TPU": 8}, "min_workers": 0,
            "max_workers": 4}
NODE_TYPES = {"cpu": CPU_TYPE, "tpu_v5e_8": TPU_HOST}


def make(config_extra=None, provider=None):
    provider = provider or FakeNodeProvider()
    lm = LoadMetrics()
    config = {"available_node_types": NODE_TYPES, "max_workers": 10,
              "idle_timeout_minutes": 0.001, **(config_extra or {})}
    return StandardAutoscaler(provider, config, lm), provider, lm


# -- demand scheduler (pure logic) ------------------------------------------

def test_packer_fits_on_existing_capacity():
    sched = ResourceDemandScheduler(NODE_TYPES, max_workers=10)
    launch, infeasible = sched.get_nodes_to_launch(
        {"cpu": 1}, [{"CPU": 8}], [{"CPU": 4}, {"CPU": 4}])
    assert launch == {} and not infeasible


def test_packer_launches_for_unmet_demand():
    sched = ResourceDemandScheduler(NODE_TYPES, max_workers=10)
    launch, _ = sched.get_nodes_to_launch(
        {}, [], [{"CPU": 4}] * 4)          # 16 CPUs needed
    assert launch == {"cpu": 2}


def test_packer_prefers_type_satisfying_most():
    sched = ResourceDemandScheduler(NODE_TYPES, max_workers=10)
    launch, _ = sched.get_nodes_to_launch(
        {}, [], [{"TPU": 4}, {"TPU": 4}])
    assert launch == {"tpu_v5e_8": 1}


def test_packer_honors_min_workers():
    types = {"cpu": {**CPU_TYPE, "min_workers": 2}}
    sched = ResourceDemandScheduler(types, max_workers=10)
    launch, _ = sched.get_nodes_to_launch({}, [], [])
    assert launch == {"cpu": 2}


def test_packer_honors_max_workers():
    sched = ResourceDemandScheduler(
        {"cpu": {**CPU_TYPE, "max_workers": 1}}, max_workers=1)
    launch, infeasible = sched.get_nodes_to_launch(
        {}, [], [{"CPU": 8}] * 5)
    assert launch == {"cpu": 1}
    assert len(infeasible) == 4            # capped; remainder reported


def test_gang_is_indivisible_across_hosts():
    """An SPMD gang (8 x TPU:1 bundles) must land on ONE ICI domain."""
    sched = ResourceDemandScheduler(NODE_TYPES, max_workers=10)
    gang = [{"TPU": 1}] * 8
    launch, infeasible = sched.get_nodes_to_launch({}, [], [], [gang])
    assert launch == {"tpu_v5e_8": 1} and not infeasible


def test_oversized_gang_reported_infeasible():
    sched = ResourceDemandScheduler(NODE_TYPES, max_workers=10)
    gang = [{"TPU": 1}] * 16               # no 16-chip type exists
    launch, infeasible = sched.get_nodes_to_launch({}, [], [], [gang])
    assert launch == {} and infeasible == [gang]


# -- StandardAutoscaler loop -------------------------------------------------

def test_scale_up_on_demand():
    scaler, provider, lm = make()
    lm.set_demands([{"CPU": 4}] * 4)
    scaler.update()
    assert provider.created_log == [("cpu", 2)]


def test_idle_nodes_terminated():
    scaler, provider, lm = make()
    provider.create_node({}, {TAG_NODE_KIND: "worker",
                              TAG_NODE_TYPE: "cpu"}, 2)
    (n1, n2) = provider.non_terminated_nodes({TAG_NODE_KIND: "worker"})
    lm.update_node(n1, {"CPU": 8}, {"CPU": 8}, busy=False)
    lm.update_node(n2, {"CPU": 8}, {"CPU": 8}, busy=False)
    time.sleep(0.12)
    scaler.update()
    assert provider.non_terminated_nodes({TAG_NODE_KIND: "worker"}) == []


def test_busy_nodes_not_terminated():
    scaler, provider, lm = make(
        {"idle_timeout_minutes": 60})       # long timeout
    provider.create_node({}, {TAG_NODE_KIND: "worker",
                              TAG_NODE_TYPE: "cpu"}, 1)
    nid = provider.non_terminated_nodes({TAG_NODE_KIND: "worker"})[0]
    lm.update_node(nid, {"CPU": 8}, {"CPU": 2}, busy=True)
    scaler.update()
    assert provider.non_terminated_nodes(
        {TAG_NODE_KIND: "worker"}) == [nid]


def test_min_workers_never_reaped():
    types = {"cpu": {**CPU_TYPE, "min_workers": 1}}
    scaler, provider, lm = make({"available_node_types": types})
    scaler.update()                         # brings up min_workers
    nodes = provider.non_terminated_nodes({TAG_NODE_KIND: "worker"})
    assert len(nodes) == 1
    lm.update_node(nodes[0], {"CPU": 8}, {"CPU": 8}, busy=False)
    time.sleep(0.12)
    scaler.update()
    assert provider.non_terminated_nodes(
        {TAG_NODE_KIND: "worker"}) == nodes


def test_launch_batch_cap():
    scaler, provider, lm = make({"max_launch_batch": 2})
    lm.set_demands([{"CPU": 8}] * 6)
    scaler.update()
    assert provider.created_log == [("cpu", 2)]   # capped per tick
    # next tick launches the rest
    lm.set_demands([{"CPU": 8}] * 4)
    scaler.update()
    assert provider.created_log[-1] == ("cpu", 2)


def test_gang_demand_launches_slice():
    scaler, provider, lm = make()
    lm.set_demands([], gangs=[[{"TPU": 1}] * 8])
    scaler.update()
    assert provider.created_log == [("tpu_v5e_8", 1)]
    assert scaler.infeasible_gangs == []


# -- serve-stats-driven demand + drain ordering ------------------------------

def test_scale_up_from_engine_stats():
    """Queue pressure published by InferenceEngine.stats() becomes
    replica demand: 5 queued requests at target depth 2 -> 3 synthetic
    replica demands -> the scaler launches capacity for them."""
    from ray_tpu.autoscaler.load_metrics import (
        replica_demands_from_engine_stats,
    )
    stats = [{"queue_depth": 5, "decode_tok_s": 120.0},
             {"queue_depth": 0, "decode_tok_s": 300.0}]
    demands = replica_demands_from_engine_stats(
        stats, target_queue_depth=2.0,
        resources_per_replica={"CPU": 4.0})
    assert demands == [{"CPU": 4.0}] * 3    # ceil(5/2); idle engine: 0

    scaler, provider, lm = make()
    lm.set_demands(demands)
    scaler.update()
    assert provider.created_log == [("cpu", 2)]   # 12 CPUs -> 2 nodes


def test_engine_stats_demand_empty_when_drained():
    from ray_tpu.autoscaler.load_metrics import (
        replica_demands_from_engine_stats,
    )
    assert replica_demands_from_engine_stats(
        [{"queue_depth": 0}, {}]) == []


def test_drain_precedes_terminate():
    """Every terminate_node must be preceded by a drain_node for the
    same node, in both the idle-reap and excess-workers paths."""
    scaler, provider, lm = make()
    provider.create_node({}, {TAG_NODE_KIND: "worker",
                              TAG_NODE_TYPE: "cpu"}, 2)
    for nid in provider.non_terminated_nodes({TAG_NODE_KIND: "worker"}):
        lm.update_node(nid, {"CPU": 8}, {"CPU": 8}, busy=False)
    time.sleep(0.12)
    scaler.update()                          # idle path reaps both
    assert provider.non_terminated_nodes({TAG_NODE_KIND: "worker"}) == []
    drained = [n for v, n in provider.event_log if v == "drain"]
    for verb_nid in [(v, n) for v, n in provider.event_log
                     if v == "terminate"]:
        nid = verb_nid[1]
        assert provider.event_log.index(("drain", nid)) < \
            provider.event_log.index(("terminate", nid))
    assert sorted(drained) == sorted(provider.terminated_log)

    # excess path (max_workers shrank under the live count)
    provider2 = FakeNodeProvider()
    scaler2, provider2, lm2 = make({"max_workers": 0}, provider2)
    provider2.create_node({}, {TAG_NODE_KIND: "worker",
                               TAG_NODE_TYPE: "cpu"}, 1)
    scaler2.update()
    assert provider2.event_log[0][0] == "drain"
    assert provider2.event_log[1][0] == "terminate"
    assert provider2.event_log[0][1] == provider2.event_log[1][1]


def test_idle_seconds_for_never_reported_node():
    """A node that never sent a resource report must still accrue
    idleness (from first query), else it can never be idle-reaped."""
    lm = LoadMetrics()
    first = lm.idle_seconds("ghost-node")
    assert first >= 0.0
    time.sleep(0.05)
    assert lm.idle_seconds("ghost-node") >= 0.05   # clock is anchored


# ---------------------------------------------------------------------------
# Closed loop e2e: demand flows head -> LoadMetrics -> StandardAutoscaler ->
# LocalDaemonNodeProvider -> REAL HostDaemon processes (reference:
# monitor.py:249 update_load_metrics + fake_multi_node/node_provider.py:237).
# Runs in a subprocess with its own session so the shared fixture session
# never sees autoscaled nodes.
# ---------------------------------------------------------------------------

_E2E = r"""
import time
import ray_tpu

ray_tpu.init(num_cpus=1)
c = ray_tpu._worker.get_client()
c.control("attach_autoscaler", {
    "max_workers": 3,
    "idle_timeout_minutes": 3.0 / 60.0,      # 3s idle -> drain
    "available_node_types": {
        "cpu_worker": {
            "resources": {"CPU": 2, "work": 2},
            "node_config": {"resources": {"CPU": 2, "work": 2}},
            "min_workers": 0, "max_workers": 3,
        },
    },
})

@ray_tpu.remote(resources={"work": 1})
def f(i):
    time.sleep(1.0)
    return i

# demand spike: the head has no 'work' resource at all, so these tasks are
# only runnable on autoscaled nodes
refs = [f.remote(i) for i in range(4)]
out = ray_tpu.get(refs, timeout=180)
assert sorted(out) == [0, 1, 2, 3]
grown = [n for n in c.control("list_nodes")
         if n["alive"] and not n.get("head")]
assert len(grown) >= 1, "no nodes were launched"

st = c.control("autoscaler_status")
assert st["enabled"] and sum(st["workers_by_type"].values()) >= 1, st

# an infeasible placement group becomes gang demand, not an error
pg_id = c.control("create_pg",
                  {"bundles": [{"work": 2.0}], "strategy": "STRICT_PACK"})
assert pg_id
assert c.control("remove_pg", pg_id) in (True, None)

# idle timeout: all autoscaled nodes drain away
deadline = time.time() + 90
while True:
    left = [n for n in c.control("list_nodes")
            if n["alive"] and not n.get("head")]
    if not left:
        break
    assert time.time() < deadline, f"nodes never drained: {left}"
    time.sleep(1.0)
print("AUTOSCALE-OK")
ray_tpu.shutdown()
"""


def test_autoscaler_closed_loop_e2e():
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, "-c", _E2E], cwd=repo,
                       capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, \
        f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "AUTOSCALE-OK" in r.stdout
