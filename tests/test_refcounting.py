"""Distributed reference counting and object freeing.

Counterpart of the reference's `python/ray/tests/test_reference_counting.py`
(driver refs, task-arg pinning, out-of-scope deletion) against the N5
ReferenceCounter design: objects are freed when no process holds a live
ObjectRef, no queued/running task will consume them, and they never
escaped via pickling.
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def cluster(ray_session):
    return ray_session


def _node():
    return ray_tpu._worker.get_client().node


def _wait_freed(oid, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        gc.collect()
        ray_tpu._worker._drain_decs()
        with _node().lock:
            if oid not in _node().directory:
                return True
        time.sleep(0.1)
    return False


def _wait_present(oid, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        with _node().lock:
            if oid in _node().directory:
                return True
        time.sleep(0.05)
    return False


def test_put_freed_on_ref_drop(cluster):
    arr = np.arange(100_000, dtype=np.float32)   # large -> store-backed
    ref = ray_tpu.put(arr)
    oid = ref._id
    assert _wait_present(oid)
    np.testing.assert_array_equal(ray_tpu.get(ref), arr)
    del ref
    assert _wait_freed(oid), "object not freed after last ref dropped"


def test_object_survives_while_held(cluster):
    ref = ray_tpu.put(np.ones(50_000, np.float32))
    oid = ref._id
    gc.collect()
    ray_tpu._worker._drain_decs()
    time.sleep(1.0)
    with _node().lock:
        assert oid in _node().directory
    assert float(ray_tpu.get(ref).sum()) == 50_000.0


def test_task_return_freed_after_drop(cluster):
    @ray_tpu.remote
    def make():
        return np.zeros(200_000, np.uint8)

    ref = make.remote()
    assert ray_tpu.get(ref).nbytes == 200_000
    oid = ref._id
    del ref
    assert _wait_freed(oid), "worker-origin object not freed"


def test_arg_pinned_until_consumer_done(cluster):
    """Dropping the producer ref right after submitting the consumer must
    not lose the data: the pending task pins it."""
    @ray_tpu.remote
    def slow_consume(arr):
        import time as _t
        _t.sleep(1.0)
        return float(arr.sum())

    data = ray_tpu.put(np.ones(150_000, np.float32))
    oid = data._id
    out = slow_consume.remote(data)
    del data                       # only the queued task references it now
    gc.collect()
    ray_tpu._worker._drain_decs()
    assert ray_tpu.get(out, timeout=60) == 150_000.0
    del out
    assert _wait_freed(oid), "consumed arg not freed after task finished"


def test_chain_intermediates_freed(cluster):
    @ray_tpu.remote
    def stage(x):
        return x + np.ones(120_000, np.float32)

    a = stage.remote(np.zeros(120_000, np.float32))
    b = stage.remote(a)
    a_id = a._id
    del a
    result = ray_tpu.get(b)
    assert float(result[0]) == 2.0
    assert _wait_freed(a_id), "intermediate not freed after chain consumed"


def test_escaped_ref_never_freed(cluster):
    """A ref pickled inside another object may rematerialize anywhere:
    pessimistically pinned for the session."""
    inner = ray_tpu.put(np.arange(60_000, dtype=np.int32))
    oid = inner._id
    holder = ray_tpu.put({"nested": inner})   # pickles the ObjectRef
    del inner
    gc.collect()
    ray_tpu._worker._drain_decs()
    time.sleep(1.5)
    with _node().lock:
        assert oid in _node().directory, "escaped object must not be freed"
    out = ray_tpu.get(holder)
    np.testing.assert_array_equal(ray_tpu.get(out["nested"]),
                                  np.arange(60_000, dtype=np.int32))


def test_worker_held_ref_blocks_free(cluster):
    """An actor that keeps a (nested, escaped) ref alive can still read
    it after the driver drops its copy."""
    @ray_tpu.remote
    class Keeper:
        def __init__(self):
            self.ref = None

        def keep(self, boxed):
            self.ref = boxed["r"]
            return True

        def read(self):
            return float(ray_tpu.get(self.ref).sum())

    k = Keeper.remote()
    ref = ray_tpu.put(np.ones(80_000, np.float32))
    assert ray_tpu.get(k.keep.remote({"r": ref}))   # nested -> escapes
    del ref
    gc.collect()
    ray_tpu._worker._drain_decs()
    time.sleep(1.0)
    assert ray_tpu.get(k.read.remote(), timeout=60) == 80_000.0
    ray_tpu.kill(k)


def test_refcount_bookkeeping_bounded(cluster):
    """Freed objects leave no residue in the node's ref tables."""
    node = _node()
    refs = [ray_tpu.put(np.zeros(110_000, np.uint8)) for _ in range(8)]
    oids = [r._id for r in refs]
    ray_tpu.get(refs)
    del refs
    for oid in oids:
        assert _wait_freed(oid)
    with node.lock:
        for oid in oids:
            assert oid not in node.obj_origin
            assert not node.ref_holders.get(oid)
