"""Batching and multiplexing concurrency tests: the batcher must block
(not spin) yet return a full batch immediately, errors must fan out to
every caller without killing the loop thread, and multiplexed model
loads must be deduplicated under concurrency."""

import threading
import time

import pytest

from ray_tpu.serve.batching import batch
from ray_tpu.serve.multiplex import multiplexed


def _run_threads(n, fn):
    ts = [threading.Thread(target=fn, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


class TestBatcher:
    def test_full_batch_returns_without_waiting_out_timeout(self):
        """max_batch_size arrivals dispatch immediately — the 5 s window
        must NOT be slept out."""
        @batch(max_batch_size=4, batch_wait_timeout_s=5.0)
        def double(xs):
            return [x * 2 for x in xs]

        outs = {}
        t0 = time.monotonic()
        _run_threads(4, lambda i: outs.__setitem__(i, double(i)))
        assert time.monotonic() - t0 < 2.0
        assert outs == {i: i * 2 for i in range(4)}

    def test_partial_batch_respects_deadline(self):
        """A lone caller waits ~the window (once), not forever — and the
        blocking wait means no 1 ms-spin poll while it does."""
        @batch(max_batch_size=8, batch_wait_timeout_s=0.2)
        def ident(xs):
            return xs

        t0 = time.monotonic()
        assert ident(7) == 7
        dt = time.monotonic() - t0
        assert 0.15 <= dt < 2.0

    def test_error_propagates_to_every_caller_and_thread_survives(self):
        calls = []

        @batch(max_batch_size=2, batch_wait_timeout_s=0.05)
        def flaky(xs):
            calls.append(list(xs))
            if len(calls) == 1:
                raise RuntimeError("batch boom")
            return [x + 1 for x in xs]

        errs = []

        def call(i):
            try:
                flaky(i)
            except RuntimeError as e:
                errs.append(str(e))
        _run_threads(2, call)
        assert errs == ["batch boom", "batch boom"]
        # the loop thread survived the exception and serves again
        assert flaky(10) == 11

    def test_batch_sizes_seen(self):
        sizes = []

        @batch(max_batch_size=4, batch_wait_timeout_s=0.3)
        def record(xs):
            sizes.append(len(xs))
            return xs

        _run_threads(8, lambda i: record(i))
        assert sum(sizes) == 8
        assert max(sizes) <= 4

    def test_wrong_result_count_raises_for_callers(self):
        @batch(max_batch_size=2, batch_wait_timeout_s=0.05)
        def bad(xs):
            return xs[:-1] if len(xs) > 1 else ["lonely"]

        errs = []

        def call(i):
            try:
                bad(i)
            except ValueError as e:
                errs.append("results" in str(e))
        _run_threads(2, call)
        assert errs == [True, True]


class TestMultiplex:
    def test_model_loaded_exactly_once_under_concurrency(self):
        loads = []

        class Server:
            @multiplexed(max_num_models_per_replica=2)
            def get_model(self, model_id):
                loads.append(model_id)
                time.sleep(0.1)     # wide race window
                return f"model:{model_id}"

        srv = Server()
        got = []
        _run_threads(8, lambda i: got.append(srv.get_model("m1")))
        assert loads == ["m1"]
        assert got == ["model:m1"] * 8

    def test_distinct_ids_load_independently(self):
        loads = []

        class Server:
            @multiplexed(max_num_models_per_replica=4)
            def get_model(self, model_id):
                loads.append(model_id)
                time.sleep(0.02)
                return model_id.upper()

        srv = Server()
        got = {}
        _run_threads(6, lambda i: got.__setitem__(
            i, srv.get_model(f"m{i % 3}")))
        assert sorted(loads) == ["m0", "m1", "m2"]
        assert set(got.values()) == {"M0", "M1", "M2"}

    def test_eviction_closes_lru_model(self):
        closed = []

        class Model:
            def __init__(self, mid):
                self.mid = mid

            def close(self):
                closed.append(self.mid)

        class Server:
            @multiplexed(max_num_models_per_replica=1)
            def get_model(self, model_id):
                return Model(model_id)

        srv = Server()
        a = srv.get_model("a")
        b = srv.get_model("b")
        assert closed == ["a"]
        assert (a.mid, b.mid) == ("a", "b")

    def test_failed_load_lets_waiter_retry(self):
        """The loser of a failed load becomes the new loader instead of
        hanging on a never-cached event."""
        attempts = []

        class Server:
            @multiplexed
            def get_model(self, model_id):
                attempts.append(model_id)
                if len(attempts) == 1:
                    time.sleep(0.05)
                    raise RuntimeError("load failed")
                return "ok"

        srv = Server()
        results = []

        def call(i):
            try:
                results.append(srv.get_model("x"))
            except RuntimeError:
                results.append("err")
        _run_threads(3, call)
        assert sorted(results) == ["err", "ok", "ok"]
        assert len(attempts) == 2

    def test_loads_after_failure_still_cached(self):
        n = {"calls": 0}

        class Server:
            @multiplexed
            def get_model(self, model_id):
                n["calls"] += 1
                if n["calls"] == 1:
                    raise RuntimeError("nope")
                return "fine"

        srv = Server()
        with pytest.raises(RuntimeError):
            srv.get_model("z")
        assert srv.get_model("z") == "fine"
        assert srv.get_model("z") == "fine"
        assert n["calls"] == 2
