"""Standalone head + head-restart survival.

Counterpart of the reference's GCS fault tolerance
(test_gcs_fault_tolerance.py over gcs_server.h:78 + Redis persistence
redis_store_client.h:33 + NotifyGCSRestart node_manager.proto:358):
the head runs as its OWN process (`ray_tpu._private.head_main`), gets
SIGKILLed mid-workload, restarts into the same session dir, and then

- the HostDaemon reconnects and re-registers (actors + objects intact),
- a detached NAMED actor keeps its in-memory state across the restart,
- a job submitted before the kill completes after it,
- KV entries survive.

Scenario lives in head_restart_helper.py (orchestrate/setup/check modes).
"""

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HELPER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "head_restart_helper.py")


def test_head_restart_survival(tmp_path):
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    session = str(tmp_path / "session")
    os.makedirs(session, exist_ok=True)
    r = subprocess.run(
        [sys.executable, HELPER, "orchestrate", session, str(port)],
        cwd=REPO, capture_output=True, text=True, timeout=480)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "ALL-OK" in r.stdout


def test_head_failover_from_snapshot_uri(tmp_path):
    """Head FAILOVER: a replacement head in a brand-new session dir (a
    different machine, in effect) restores cluster metadata from the
    remote snapshot mirror (reference: Redis-backed GCS lets a restarted
    GCS process recover state from outside the dead host)."""
    script = r"""
import os, sys, time
import ray_tpu
from ray_tpu._private.node import NodeServer

uri = sys.argv[1]
dir_a, dir_b = sys.argv[2], sys.argv[3]
os.environ["RAY_TPU_HEAD_SNAPSHOT_URI"] = uri
os.environ["RAY_TPU_HEAD_SNAPSHOT_INTERVAL_S"] = "0.2"

# head A: create metadata, let a snapshot mirror land, die
a = NodeServer({"CPU": 2.0}, dir_a, 0, standalone=True)
a.kv[("ns", "k")] = b"survives-machines"
a.named_actors["phoenix"] = "actor_00ff"
from ray_tpu._private.node import _ActorState
from ray_tpu._private import protocol
spec = protocol.TaskSpec(
    task_id="t1", function_id="f1", function_desc="Phoenix.__init__",
    function_blob=b"", actor_id="actor_00ff", actor_creation=True,
    actor_options={"name": "phoenix"})
a.actors["actor_00ff"] = _ActorState(
    actor_id="actor_00ff", creation_spec=spec, name="phoenix",
    node="node_far", ready=True)
time.sleep(1.0)                 # >= one snapshot tick
import os as _os
_os.kill(_os.getpid(), 0)       # (alive) — now simulate death by just
a._shutdown = True              # stopping its loops; dir_a is NOT reused

# head B: brand-new session dir, same snapshot URI
b = NodeServer({"CPU": 2.0}, dir_b, 0, standalone=True)
assert b.kv.get(("ns", "k")) == b"survives-machines", b.kv
assert b.named_actors.get("phoenix") == "actor_00ff"
st = b.actors["actor_00ff"]
assert st.node == "node_far" and not st.dead
b._shutdown = True
print("FAILOVER-OK")
"""
    import uuid
    uri = f"mem://headfail-{uuid.uuid4().hex[:8]}"
    dir_a = str(tmp_path / "session_a")
    dir_b = str(tmp_path / "session_b")
    os.makedirs(dir_a)
    os.makedirs(dir_b)
    r = subprocess.run(
        [sys.executable, "-c", script, uri, dir_a, dir_b],
        cwd=REPO, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "FAILOVER-OK" in r.stdout
