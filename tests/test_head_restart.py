"""Standalone head + head-restart survival.

Counterpart of the reference's GCS fault tolerance
(test_gcs_fault_tolerance.py over gcs_server.h:78 + Redis persistence
redis_store_client.h:33 + NotifyGCSRestart node_manager.proto:358):
the head runs as its OWN process (`ray_tpu._private.head_main`), gets
SIGKILLed mid-workload, restarts into the same session dir, and then

- the HostDaemon reconnects and re-registers (actors + objects intact),
- a detached NAMED actor keeps its in-memory state across the restart,
- a job submitted before the kill completes after it,
- KV entries survive.

Scenario lives in head_restart_helper.py (orchestrate/setup/check modes).
"""

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HELPER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "head_restart_helper.py")


def test_head_restart_survival(tmp_path):
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    session = str(tmp_path / "session")
    os.makedirs(session, exist_ok=True)
    r = subprocess.run(
        [sys.executable, HELPER, "orchestrate", session, str(port)],
        cwd=REPO, capture_output=True, text=True, timeout=480)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "ALL-OK" in r.stdout
