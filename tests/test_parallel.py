"""Parallelism layer tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel import (
    MeshSpec,
    logical_to_spec,
    pipeline_apply,
    reference_attention,
    ring_attention,
    shard_batch,
    tree_shardings,
)


def test_mesh_spec_resolution():
    sizes = MeshSpec(data=-1, tensor=2).resolve(8)
    assert sizes["data"] == 4 and sizes["tensor"] == 2

    with pytest.raises(ValueError):
        MeshSpec(data=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(data=-1, tensor=-1).resolve(8)


def test_mesh_build_axes():
    mesh = MeshSpec(data=2, tensor=4).build()
    assert mesh.shape["data"] == 2
    assert mesh.shape["tensor"] == 4
    assert mesh.shape["pipe"] == 1


def test_logical_to_spec_rules():
    spec = logical_to_spec(("batch", "length", "embed"))
    assert spec == P(("data", "fsdp"), "seq", None) or spec == P(
        ("data", "fsdp"), "seq", "fsdp")
    # embed -> fsdp, but fsdp already consumed by batch in the same spec
    assert spec[2] is None

    mesh = MeshSpec(data=2, tensor=4).build()
    spec = logical_to_spec(("mlp", "embed"), mesh=mesh)
    assert spec == P("tensor", "fsdp")


def test_shard_batch_places_on_mesh():
    mesh = MeshSpec(data=4, tensor=2).build()
    batch = {"x": np.ones((8, 3), np.float32)}
    placed = shard_batch(batch, mesh)
    shard_shapes = {s.data.shape for s in placed["x"].addressable_shards}
    assert shard_shapes == {(2, 3)}


def test_tree_shardings():
    mesh = MeshSpec(data=2, tensor=4).build()
    tree = {"w": ("embed", "mlp"), "b": ("mlp",)}
    sh = tree_shardings(mesh, tree)
    assert sh["w"].spec == P("fsdp", "tensor")
    assert sh["b"].spec == P("tensor")


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = MeshSpec(data=1, seq=8).build()
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 64, 4, 16)), jnp.float32)
               for _ in range(3))
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_grad_flows():
    mesh = MeshSpec(data=1, seq=8).build()
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.standard_normal((1, 32, 2, 8)), jnp.float32)
               for _ in range(3))

    def loss(q):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def ref_loss(q):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g = jax.grad(loss)(q)
    g_ref = jax.grad(ref_loss)(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               atol=1e-4, rtol=1e-4)


def test_pipeline_matches_sequential():
    mesh = MeshSpec(data=1, pipe=4).build(jax.devices()[:4])
    rng = np.random.default_rng(2)
    d = 16
    stage_params = [
        {"w": jnp.asarray(rng.standard_normal((d, d)) * 0.1, jnp.float32)}
        for _ in range(4)]
    x = jnp.asarray(rng.standard_normal((8, d)), jnp.float32)

    def stage_fn(params, h):
        return jnp.tanh(h @ params["w"])

    out = pipeline_apply(stage_fn, stage_params, x, mesh=mesh,
                         num_microbatches=4)
    seq = x
    for p in stage_params:
        seq = stage_fn(p, seq)
    np.testing.assert_allclose(np.asarray(out), np.asarray(seq),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_single_stage_fallback():
    mesh = MeshSpec(data=1).build(jax.devices()[:1])
    x = jnp.ones((4, 8))
    out = pipeline_apply(lambda p, h: h * p, [2.0], x, mesh=mesh,
                         num_microbatches=2)
    np.testing.assert_allclose(np.asarray(out), 2 * np.ones((4, 8)))


def test_gpt_pipeline_trainer_step():
    """Pipeline-staged GPT train step on pipe=2 x data=2: loss finite and
    decreasing, and it matches the dense trainer's loss on the same batch
    at init (same params, same math, different schedule)."""
    from ray_tpu.models import gpt
    from ray_tpu.train import spmd

    mesh = MeshSpec(data=2, pipe=2).build(jax.devices()[:4])
    cfg = gpt.small(attn_impl="xla")
    state, step_fn, shard = spmd.make_gpt_pipeline_trainer(
        cfg, mesh, num_microbatches=2)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (4, cfg.max_seq_len + 1),
                        np.int32)
    batch = shard({"inputs": toks[:, :-1].copy(),
                   "targets": toks[:, 1:].copy()})
    losses = []
    for _ in range(3):
        state, metrics = step_fn(state, batch)
        losses.append(float(jax.device_get(metrics["loss"])))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]

    # parity with the dense (non-pipelined) trainer at init
    mesh1 = MeshSpec(data=1).build(jax.devices()[:1])
    dstate, dstep, dshard = spmd.make_gpt_trainer(cfg, mesh1)
    dbatch = dshard({"inputs": toks[:, :-1].copy(),
                     "targets": toks[:, 1:].copy()})
    _, dmetrics = dstep(dstate, dbatch)
    np.testing.assert_allclose(losses[0],
                               float(jax.device_get(dmetrics["loss"])),
                               rtol=2e-2)


def test_multislice_mesh_structure_and_step():
    """DCN multi-slice mesh (SURVEY.md §5.8): the outer data factor
    spans slices, model axes stay in-slice; a full train step compiles
    and runs over it (the cross-slice edge carries only the gradient
    psum — scaling-book multi-pod layout)."""
    import jax
    import numpy as np
    from ray_tpu.models import gpt
    from ray_tpu.parallel import MeshSpec
    from ray_tpu.train import spmd

    devices = jax.devices()[:8]
    mesh = MeshSpec(data=4, seq=2).build_multislice(2, devices)
    assert mesh.shape["data"] == 4 and mesh.shape["seq"] == 2
    # slice blocks: first half of devices fills the first half of the
    # data axis (contiguous blocks under the CPU fallback)
    arr = np.asarray(mesh.devices).reshape(4, 2)
    first_slice = {d.id for d in arr[:2].ravel()}
    assert first_slice == {d.id for d in devices[:4]}

    cfg = gpt.small(attn_impl="auto")
    state, step_fn, shard = spmd.make_gpt_trainer(cfg, mesh)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (8, cfg.max_seq_len + 1),
                        np.int32)
    batch = shard({"inputs": toks[:, :-1].copy(),
                   "targets": toks[:, 1:].copy()})
    state, metrics = step_fn(state, batch)
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


def test_multislice_rejects_indivisible():
    import jax
    import pytest as _pytest
    from ray_tpu.parallel import MeshSpec
    with _pytest.raises(ValueError, match="slices"):
        MeshSpec(data=3, tensor=2).build_multislice(2, jax.devices()[:6])
