"""New algorithm families: SAC, A2C, APPO, BC/MARWIL, CQL + offline IO.

Counterpart of the reference's per-algorithm test dirs
(`rllib/algorithms/*/tests/`) and `rllib/offline/tests/`: short-budget
learning regressions with reward thresholds (SURVEY.md §4.2) and offline
round-trips through JSON shards.
"""

import numpy as np
import pytest

from ray_tpu.rllib import sample_batch as sbmod
from ray_tpu.rllib.offline import (
    JsonReader,
    JsonWriter,
    importance_sampling,
    weighted_importance_sampling,
)
from ray_tpu.rllib.sample_batch import SampleBatch

sb = sbmod


# ---------------------------------------------------------------------------
# learning regressions
# ---------------------------------------------------------------------------

def test_sac_pendulum_learns():
    """Pendulum returns start near -1400; SAC should clearly improve within
    a tiny budget (reference: sac/tests/test_sac.py learning check)."""
    from ray_tpu.rllib.algorithms.sac import SACConfig
    algo = (SACConfig().environment("Pendulum-v1")
            .training(n_updates_per_iter=256, learning_starts=500,
                      train_batch_size=128, no_done_at_end=True,
                      model={"fcnet_hiddens": (64, 64)})
            .rollouts(num_envs_per_worker=32, rollout_fragment_length=8)
            .debugging(seed=0)
            .build())
    best = -1e9
    for _ in range(70):
        r = algo.train()
        rew = r.get("episode_reward_mean")
        if rew == rew:
            best = max(best, rew)
        if best > -900:
            break
    assert best > -900, best


def test_a2c_cartpole_learns():
    from ray_tpu.rllib.algorithms.a2c import A2CConfig
    algo = (A2CConfig().environment("CartPole-v1")
            .rollouts(num_envs_per_worker=16, rollout_fragment_length=32)
            .debugging(seed=0)
            .build())
    best = 0.0
    for _ in range(150):
        r = algo.train()
        rew = r.get("episode_reward_mean")
        if rew == rew:
            best = max(best, rew)
    assert best > 60, best


def test_appo_cartpole_learns(ray_session):
    from ray_tpu.rllib.algorithms.appo import APPOConfig
    algo = (APPOConfig().environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=128)
            .training(batches_per_step=4)
            .debugging(seed=0)
            .build())
    best = 0.0
    try:
        for _ in range(40):
            r = algo.train()
            rew = r.get("episode_reward_mean")
            if rew == rew:
                best = max(best, rew)
            if best > 60:
                break
    finally:
        algo.cleanup()
    assert best > 60, best


# ---------------------------------------------------------------------------
# offline IO + estimators
# ---------------------------------------------------------------------------

def _make_episode(rng, t, obs_dim=4, ret_scale=1.0):
    return SampleBatch({
        sb.OBS: rng.normal(size=(t, obs_dim)).astype(np.float32),
        sb.ACTIONS: rng.integers(0, 2, size=t),
        sb.REWARDS: (np.ones(t) * ret_scale).astype(np.float32),
        sb.DONES: np.arange(t) == t - 1,
        sb.ACTION_LOGP: np.full(t, np.log(0.5), np.float32),
        sb.EPS_ID: np.zeros(t, np.int64),
    })


def test_json_writer_reader_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    w = JsonWriter(str(tmp_path))
    batches = []
    for i in range(3):
        b = _make_episode(rng, 5 + i)
        b[sb.EPS_ID][:] = i
        batches.append(b)
        w.write(b)
    w.close()
    r = JsonReader(str(tmp_path))
    allb = r.read_all()
    assert len(allb[sb.REWARDS]) == 5 + 6 + 7
    np.testing.assert_allclose(allb[sb.OBS][:5], batches[0][sb.OBS])
    # streaming next() cycles
    first = r.next()
    assert len(first[sb.REWARDS]) == 5


def test_is_wis_estimators_identity_policy():
    """Target == behaviour -> both estimators reproduce the behaviour
    value exactly (the reference's sanity oracle)."""
    rng = np.random.default_rng(1)
    eps = [_make_episode(rng, 10), _make_episode(rng, 10)]
    for i, e in enumerate(eps):
        e[sb.EPS_ID][:] = i
    from ray_tpu.rllib.sample_batch import concat_samples
    batch = concat_samples(eps)
    target_logp = np.asarray(batch[sb.ACTION_LOGP])
    is_res = importance_sampling(batch, target_logp, gamma=1.0)
    wis_res = weighted_importance_sampling(batch, target_logp, gamma=1.0)
    assert abs(is_res["v_target"] - is_res["v_behavior"]) < 1e-5
    assert abs(wis_res["v_target"] - wis_res["v_behavior"]) < 1e-5
    assert abs(is_res["v_behavior"] - 10.0) < 1e-6


def test_wis_prefers_better_policy():
    """A target policy likelier on high-reward episodes estimates higher."""
    rng = np.random.default_rng(2)
    good = _make_episode(rng, 10, ret_scale=2.0)
    bad = _make_episode(rng, 10, ret_scale=0.5)
    good[sb.EPS_ID][:] = 0
    bad[sb.EPS_ID][:] = 1
    from ray_tpu.rllib.sample_batch import concat_samples
    batch = concat_samples([good, bad])
    # target upweights the good episode's actions
    target_logp = np.concatenate([
        np.full(10, np.log(0.8)), np.full(10, np.log(0.2))])
    res = weighted_importance_sampling(batch, target_logp, gamma=1.0)
    assert res["v_target"] > res["v_behavior"]


# ---------------------------------------------------------------------------
# offline algorithms
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cartpole_expert_shards(tmp_path_factory):
    """Generate behaviour data on CartPole with a half-trained PPO policy
    (the reference's tuned-example pattern: train, then `output` shards)."""
    from ray_tpu.rllib.algorithms.ppo import PPOConfig
    path = str(tmp_path_factory.mktemp("shards"))
    algo = (PPOConfig().environment("CartPole-v1")
            .rollouts(num_envs_per_worker=8, rollout_fragment_length=64)
            .debugging(seed=0).build())
    for _ in range(12):
        algo.train()

    # roll out the trained policy eagerly and write shards
    from ray_tpu.rllib.env.jax_env import CartPole, EagerJaxEnv
    env = EagerJaxEnv(CartPole({}), seed=1)
    w = JsonWriter(path)
    for ep in range(12):
        obs = env.reset()
        rows = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.DONES,
                                sb.NEXT_OBS, sb.ACTION_LOGP, sb.EPS_ID)}
        for t in range(200):
            import jax.numpy as jnp
            dist, _ = algo.module.forward(algo.params,
                                          jnp.asarray(obs)[None])
            a = int(np.asarray(dist.deterministic())[0])
            logp = float(np.asarray(dist.logp(jnp.asarray([a])))[0])
            nobs, rew, done, _ = env.step(a)
            rows[sb.OBS].append(obs)
            rows[sb.ACTIONS].append(a)
            rows[sb.REWARDS].append(rew)
            rows[sb.DONES].append(done)
            rows[sb.NEXT_OBS].append(nobs)
            rows[sb.ACTION_LOGP].append(logp)
            rows[sb.EPS_ID].append(ep)
            obs = nobs
            if done:
                break
        w.write(SampleBatch({k: np.asarray(v) for k, v in rows.items()}))
    w.close()
    return path


@pytest.mark.slow
def test_bc_learns_from_expert(cartpole_expert_shards):
    """BC on decent CartPole data should act like the data policy."""
    from ray_tpu.rllib.algorithms.marwil import BCConfig
    algo = (BCConfig().environment("CartPole-v1")
            .offline_data(input_=cartpole_expert_shards)
            .training(n_updates_per_iter=32)
            .debugging(seed=0).build())
    for _ in range(10):
        r = algo.train()
    assert r["loss"] == r["loss"]   # finite

    # evaluate the cloned policy in the env
    from ray_tpu.rllib.env.jax_env import CartPole, EagerJaxEnv
    env = EagerJaxEnv(CartPole({}), seed=7)
    total = 0.0
    for _ in range(5):
        obs = env.reset()
        for t in range(300):
            a = algo.compute_single_action(obs)
            obs, rew, done, _ = env.step(int(a))
            total += rew
            if done:
                break
    assert total / 5 > 50, total / 5


@pytest.mark.slow
def test_marwil_beta_weights_run(cartpole_expert_shards):
    from ray_tpu.rllib.algorithms.marwil import MARWILConfig
    algo = (MARWILConfig().environment("CartPole-v1")
            .offline_data(input_=cartpole_expert_shards)
            .training(beta=1.0, n_updates_per_iter=8)
            .debugging(seed=0).build())
    r = algo.train()
    assert np.isfinite(r["loss"]) and np.isfinite(r["vf_loss"])


def test_cql_runs_on_offline_pendulum(tmp_path):
    """CQL trains from random Pendulum data without env interaction;
    smoke-level (full D4RL-style regression is a release test)."""
    rng = np.random.default_rng(0)
    from ray_tpu.rllib.env.jax_env import EagerJaxEnv, Pendulum
    env = EagerJaxEnv(Pendulum({}), seed=0)
    w = JsonWriter(str(tmp_path))
    for ep in range(4):
        obs = env.reset()
        rows = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS,
                                sb.DONES, sb.NEXT_OBS)}
        for t in range(80):
            a = rng.uniform(-2, 2, size=(1,)).astype(np.float32)
            nobs, rew, done, _ = env.step(a)
            rows[sb.OBS].append(obs)
            rows[sb.ACTIONS].append(a)
            rows[sb.REWARDS].append(rew)
            rows[sb.DONES].append(done or t == 79)
            rows[sb.NEXT_OBS].append(nobs)
            obs = nobs
            if done:
                break
        w.write(SampleBatch({k: np.asarray(v) for k, v in rows.items()}))
    w.close()

    from ray_tpu.rllib.algorithms.cql import CQLConfig
    algo = (CQLConfig().environment("Pendulum-v1")
            .offline_data(input_=str(tmp_path))
            .training(n_updates_per_iter=8, train_batch_size=64)
            .debugging(seed=0).build())
    r1 = algo.train()
    r2 = algo.train()
    assert np.isfinite(r1["loss"]) and np.isfinite(r2["loss"])
    a = algo.compute_single_action(np.zeros(3, np.float32))
    assert a.shape == (1,) and -2.0 <= float(a[0]) <= 2.0


def test_ppo_acrobot_tuned_regression():
    """Harder-than-CartPole learning oracle (reference pattern:
    tuned_examples reward thresholds, rllib/BUILD:152-162): Acrobot needs
    energy pumping under a -1/step sparse signal; random play scores
    -500, the threshold is -150."""
    from ray_tpu.rllib.train import list_tuned_examples, run_tuned_example
    path = [p for p in list_tuned_examples() if "acrobot" in p][0]
    result = run_tuned_example(path, verbose=False)
    assert result["passed"], result


def test_es_cartpole_learns():
    """Whole-population-in-graph ES (reference: rllib/algorithms/es/ —
    there a CPU-fleet algorithm; here one vmapped compiled program)."""
    from ray_tpu.rllib.algorithms.es import ESConfig
    algo = (ESConfig().environment("CartPole-v1")
            .training(population_size=48, noise_stdev=0.1, lr=0.05,
                      episode_horizon=200,
                      model={"fcnet_hiddens": (24,)})
            .debugging(seed=0)
            .build())
    best = 0.0
    for _ in range(25):
        r = algo.train()
        best = max(best, r["episode_reward_max"])
        if best >= 150:
            break
    # random CartPole play lasts ~20 steps; 150 needs real balancing
    assert best >= 150, best


def test_linucb_and_lints_low_regret():
    """Both bandits must drive per-step regret well under the random-
    arm baseline on a synthetic linear problem (reference:
    rllib/algorithms/bandit/ regression shape)."""
    from ray_tpu.rllib.algorithms.bandits import (
        LinTSConfig, LinUCBConfig, LinearBanditEnv)
    import jax
    import jax.numpy as jnp

    # random-arm regret baseline for this problem
    env = LinearBanditEnv({"problem_seed": 7})
    keys = jax.random.split(jax.random.PRNGKey(0), 512)
    ctxs = jnp.stack([env.reset(k)[1] for k in keys[:128]])
    rand_regret = float(jnp.mean(
        jax.vmap(env.best_reward)(ctxs)
        - jnp.mean(ctxs @ env.theta.T, axis=1)))

    for cfg_cls in (LinUCBConfig, LinTSConfig):
        algo = (cfg_cls().environment("LinearBandit",
                                      env_config={"problem_seed": 7})
                .training(steps_per_iter=256)
                .debugging(seed=1)
                .build())
        last = {}
        for _ in range(8):
            last = algo.train()
        assert last["mean_regret"] < 0.25 * rand_regret, \
            (cfg_cls.__name__, last, rand_regret)


def test_apex_dqn_cartpole_learns(ray_session):
    """Ape-X: actor fan-out with per-actor epsilons feeding SHARDED
    replay actors (reference: rllib/algorithms/apex_dqn/ ReplayActor
    fleet)."""
    from ray_tpu.rllib.algorithms.apex_dqn import ApexDQNConfig

    algo = (ApexDQNConfig().environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=128)
            .training(learning_starts=256, train_batch_size=128,
                      n_updates_per_iter=48,
                      target_network_update_freq=200,
                      model={"fcnet_hiddens": (64, 64)})
            .debugging(seed=0)
            .build())
    try:
        eps = algo._actor_epsilon
        # the paper's diversity schedule: strictly decreasing epsilons
        assert eps(0) > eps(1) > 0
        best = 0.0
        for _ in range(40):
            r = algo.train()
            rew = r.get("episode_reward_mean")
            if rew == rew:
                best = max(best, rew)
            if best > 80:
                break
        assert best > 80, best
        assert r["buffer_size"] > 0
        # replay is genuinely sharded and roughly balanced (round-robin)
        sizes = r["replay_shard_sizes"]
        assert len(sizes) == 2 and all(s > 0 for s in sizes), sizes
        assert max(sizes) < 4 * max(min(sizes), 1), sizes
    finally:
        algo.cleanup()
