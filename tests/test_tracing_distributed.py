"""Cluster-wide distributed tracing.

Counterpart of the reference's `ray.util.tracing` integration tests
(test_tracing.py: task/actor spans share one trace across processes)
plus the task-event stage pipeline (`test_task_events.py` timestamp
chains). Covers:

- cross-process propagation: one trace_id spanning >=3 processes in the
  merged `/api/timeline`, for BOTH entry paths (driver -> task -> nested
  task, and HTTP proxy -> ingress replica -> inner replica with a
  flight-recorder request span joining the same trace);
- control-plane stage attribution: per-task timestamp chain
  submitted -> queued -> dispatched -> exec_start -> exec_end ->
  result_put -> got is monotone, and the `task_stage_ms` histogram /
  `stage_breakdown()` read back per-stage quantiles;
- the span ring (deque bound + explicit dropped counter), real
  process/thread chrome lanes, context propagation helpers, and the
  tracing-off overhead probe.

The two e2e tests run subprocess-driven (their own session: tracing is
enabled cluster-wide, which must not leak into the shared fixture) and
are what `make trace-smoke` selects (`-k 'merged or proxy'`).
"""

import os
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.util import state
from ray_tpu.util import tracing


@pytest.fixture
def cluster(ray_session):
    return ray_session


def _run_e2e(script: str) -> subprocess.CompletedProcess:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, "-c", script], cwd=repo,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    return r


# ---------------------------------------------------------------------------
# e2e: driver -> task -> nested task, one merged trace
# ---------------------------------------------------------------------------

_DRIVER_CHAIN_E2E = r"""
import os
import ray_tpu
from ray_tpu.util import tracing

ray_tpu.init(num_cpus=4)
tracing.enable_tracing()

@ray_tpu.remote
def inner_leaf():
    import os
    return os.getpid()

@ray_tpu.remote
def outer_mid():
    import os
    return (os.getpid(), ray_tpu.get(inner_leaf.remote(), timeout=60))

with tracing.span("e2e.root") as root:
    assert root is not None, "enable_tracing did not arm the driver"
    trace_id = root["trace_id"]
    outer_pid, inner_pid = ray_tpu.get(outer_mid.remote(), timeout=120)
assert len({outer_pid, inner_pid, os.getpid()}) == 3

# TaskDone piggybacks the workers' span rings, so by the time get()
# returned, every task span of this trace is already in the head's ring
# -- no polling needed.
client = ray_tpu._worker.get_client()
events = client.control("timeline", {"trace": trace_id})
assert events and all(
    (e.get("args") or {}).get("trace_id") == trace_id for e in events)
names = [e["name"] for e in events]
assert "e2e.root" in names, names
assert sum(1 for n in names if n == "task.execute") >= 2, names
# ONE trace, >= 3 distinct processes: the driver's root span plus a
# task.execute span from each of the two workers
span_pids = {e["pid"] for e in events if e.get("cat") == "span"}
assert "driver" in span_pids, span_pids
assert len({p for p in span_pids
            if str(p).startswith("worker:")}) >= 2, span_pids
# task events joined the same filtered view (they carry the trace_id)
assert any(e.get("cat") == "task" for e in events), events
# the filter narrows; unfiltered merged view is a superset
assert len(client.control("timeline")) >= len(events)
print("MERGED-TRACE-OK", len(events), sorted(map(str, span_pids)))
ray_tpu.shutdown()
"""


def test_merged_trace_driver_task_nested():
    r = _run_e2e(_DRIVER_CHAIN_E2E)
    assert "MERGED-TRACE-OK" in r.stdout


# ---------------------------------------------------------------------------
# e2e: HTTP proxy -> ingress replica -> inner replica, one merged trace
# ---------------------------------------------------------------------------

_PROXY_E2E = r"""
import json, os, time, urllib.request
os.environ["RAY_TPU_TRACING"] = "1"            # every spawn inherits
os.environ["RAY_TPU_METRICS_FLUSH_PERIOD_S"] = "0.5"
import ray_tpu
from ray_tpu import serve
from ray_tpu.util import tracing

ray_tpu.init(num_cpus=6)

@serve.deployment
class Inner:
    def __init__(self):
        from ray_tpu.util import telemetry
        self.rec = telemetry.FlightRecorder("e2e_inner", sample=1.0)
        self.rid = 0

    def __call__(self, x):
        self.rid += 1
        # flight-recorder request span: parents under the propagated
        # task context, so it shares the HTTP request's trace_id
        self.rec.on_submit(self.rid, prompt_len=1)
        try:
            with tracing.span("inner.work", {"x": x}):
                return x * 2
        finally:
            self.rec.on_finish(self.rid, "finished")

@serve.deployment
class Ingress:
    def __init__(self, inner):
        self.inner = inner

    def __call__(self, req):
        return {"y": self.inner.call(int(req.query["x"]))}

serve.run(Ingress.bind(Inner.bind()), name="t_trace")
proxy = serve.start(http_options={"port": 0})
info = ray_tpu.get(proxy.ready.remote(), timeout=60)
serve.set_route("/trace", "Ingress", "t_trace")

url = f"http://127.0.0.1:{info['port']}/trace?x=21"
resp = urllib.request.urlopen(url, timeout=60)
assert json.loads(resp.read()) == {"y": 42}

# Replica spans rode their tasks' TaskDone; the proxy's own spans
# (http.request / handle.call) arrive on its metrics-flush heartbeat ->
# poll the merged timeline until the trace is complete.
client = ray_tpu._worker.get_client()
deadline = time.time() + 60
events, procs = [], set()
while time.time() < deadline:
    all_events = client.control("timeline")
    roots = [e for e in all_events if e["name"] == "http.request"]
    if roots:
        trace_id = roots[0]["args"]["trace_id"]
        events = [e for e in all_events
                  if (e.get("args") or {}).get("trace_id") == trace_id]
        names = {e["name"] for e in events}
        procs = {e["pid"] for e in events
                 if str(e["pid"]).startswith("worker:")}
        if (len(procs) >= 3 and "inner.work" in names
                and any(e.get("cat") == "request" for e in events)):
            break
    time.sleep(0.3)

names = {e["name"] for e in events}
assert {"http.request", "handle.call", "task.execute",
        "inner.work"} <= names, (names, procs)
# flight-recorder request span joined the same trace
assert any(e.get("cat") == "request" for e in events), names
# ONE trace_id across >= 3 worker processes: proxy, Ingress replica,
# Inner replica
assert len(procs) >= 3, (procs, names)
# the server-side --trace filter returns the same view
filtered = client.control("timeline", {"trace": trace_id})
assert {e["name"] for e in filtered} == names
print("PROXY-TRACE-OK", len(events), sorted(map(str, procs)))
serve.shutdown()
ray_tpu.shutdown()
"""


def test_merged_trace_proxy_to_replicas():
    r = _run_e2e(_PROXY_E2E)
    assert "PROXY-TRACE-OK" in r.stdout


# ---------------------------------------------------------------------------
# stage attribution (shared session: no tracing needed, stages always on)
# ---------------------------------------------------------------------------

def test_stage_timestamps_monotonic(cluster):
    @ray_tpu.remote
    def stage_probe_task():
        time.sleep(0.02)
        return 7

    assert ray_tpu.get(stage_probe_task.remote(), timeout=60) == 7
    recs = [t for t in state.list_tasks()
            if "stage_probe_task" in t["name"]]
    assert recs, "task record missing"
    r = recs[0]
    chain = ("submitted_ts", "queued_ts", "dispatched_ts",
             "exec_start_ts", "exec_end_ts", "result_put_ts", "got_ts")
    vals = [r[k] for k in chain]
    assert all(v is not None for v in vals), r
    for (ka, a), (kb, b) in zip(zip(chain, vals), list(zip(chain, vals))[1:]):
        assert a <= b, f"{ka}={a} > {kb}={b} in {r}"
    # the execute stage really brackets the user function
    assert r["exec_end_ts"] - r["exec_start_ts"] >= 0.02


def test_stage_histogram_and_breakdown(cluster):
    from ray_tpu._private.events import STAGES

    @ray_tpu.remote
    def stage_hist_task(i):
        return i

    assert ray_tpu.get([stage_hist_task.remote(i) for i in range(3)],
                       timeout=60) == [0, 1, 2]

    snap = {m["name"]: m for m in state.get_metrics()}
    assert "task_stage_ms" in snap, sorted(snap)
    hist = snap["task_stage_ms"]
    assert hist["type"] == "histogram"
    # after a full submit -> ... -> get cycle every stage has samples
    assert {(("stage", s),) for s in STAGES} <= set(hist["series"]), \
        sorted(hist["series"])
    for key in hist["series"]:
        buckets, total, count = hist["series"][key]
        assert count >= 1 and total >= 0.0

    text = state.prometheus_metrics()
    assert "ray_tpu_task_stage_ms_bucket" in text
    assert 'stage="execute"' in text and 'stage="got"' in text
    # satellite: the tracing ring's drop counter is scrapeable
    assert "ray_tpu_tracing_dropped_spans" in text

    bd = state.stage_breakdown()
    assert set(bd) == set(STAGES)
    for s in STAGES:
        assert bd[s]["count"] >= 1, (s, bd)
        assert 0.0 <= bd[s]["p50_ms"] <= bd[s]["p99_ms"] <= bd[s]["max_ms"]

    # summary() carries the same breakdown under its reserved key
    summary = state.summarize_tasks()
    assert set(summary["__stages__"]) == set(STAGES)


# ---------------------------------------------------------------------------
# span ring / lanes / context / overhead (pure units)
# ---------------------------------------------------------------------------

def test_span_ring_bound_and_dropped_counter(monkeypatch):
    saved_spans = tracing.get_spans()
    saved_cap = tracing.max_spans()
    monkeypatch.setattr(tracing, "_enabled", True)
    tracing.clear_spans()
    tracing.set_max_spans(4)
    try:
        for i in range(10):
            with tracing.span(f"ring-{i}") as s:
                assert s is not None
        assert len(tracing.get_spans()) == 4          # bound honored
        assert tracing.dropped_spans() == 6           # evictions counted
        assert [s["name"] for s in tracing.get_spans()] == \
            ["ring-6", "ring-7", "ring-8", "ring-9"]  # oldest evicted
        drained = tracing.drain_spans()
        assert len(drained) == 4 and tracing.get_spans() == []
        # ingest() applies the same cap + accounting
        assert tracing.ingest(drained * 3) == 12
        assert len(tracing.get_spans()) == 4
        assert tracing.dropped_spans() == 6 + 8
    finally:
        tracing.clear_spans()
        tracing.set_max_spans(saved_cap)
        tracing.ingest(saved_spans)


def test_chrome_trace_real_lanes():
    spans = [
        {"name": "a", "trace_id": "t1", "span_id": "s1",
         "parent_span_id": None, "start_ns": 1_000, "end_ns": 2_000,
         "attributes": {"k": "v"}, "status": "OK",
         "process": 4242, "proc": "worker:w-7", "thread": "MainThread"},
        {"name": "b", "trace_id": "t1", "span_id": "s2",
         "parent_span_id": "s1", "start_ns": 1_500, "end_ns": None,
         "attributes": {}, "status": "OK",
         "process": 4243, "proc": None, "thread": None,
         "cat": "request", "lane": "engine/r3"},
    ]
    ev = tracing.spans_to_chrome_trace(spans)
    # lanes are real process identities, not trace ids
    assert ev[0]["pid"] == "worker:w-7" and ev[0]["tid"] == "MainThread"
    assert ev[0]["cat"] == "span" and ev[0]["dur"] == 1.0   # us
    assert ev[0]["args"]["trace_id"] == "t1"
    assert ev[0]["args"]["span_id"] == "s1"
    assert ev[0]["args"]["k"] == "v"
    assert ev[1]["pid"] == 4243                  # label fallback: real pid
    assert ev[1]["tid"] == "engine/r3"           # recorder-supplied lane
    assert ev[1]["cat"] == "request"
    assert ev[1]["dur"] > 0                      # open span closed at export


def test_propagation_context_roundtrip():
    assert tracing.propagation_context() is None
    ctx = {"trace_id": "t" * 32, "span_id": "p" * 16}
    s, token = tracing.start_span("child", parent=ctx)
    assert s["trace_id"] == ctx["trace_id"]
    assert s["parent_span_id"] == ctx["span_id"]
    assert tracing.propagation_context() == \
        {"trace_id": s["trace_id"], "span_id": s["span_id"]}
    tracing.end_span(s, token)
    assert tracing.propagation_context() is None
    tok = tracing.attach_context(ctx)
    assert tracing.propagation_context() == ctx
    tracing.detach_context(tok)
    assert tracing.propagation_context() is None


def test_disabled_overhead_probe():
    if not tracing.tracing_enabled():
        with tracing.span("not-recorded") as s:
            assert s is None
    per_call = tracing.probe_disabled_overhead_ns(iters=5_000)
    # the off path is one enabled-check; 20us/call would already be a
    # plumbing regression (scale_bench asserts the real <1% bound)
    assert 0 < per_call < 20_000, per_call
