"""Helper for test_head_restart.py — run as a subprocess in three modes:

  orchestrate SESSION PORT   start head + daemon, run setup, SIGKILL the
                             head, restart it, run check
  setup SESSION              driver 1: named actor + kv + job
  check SESSION JOB_ID       driver 2: assert everything survived
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
os.environ["PYTHONPATH"] = REPO + os.pathsep + \
    os.environ.get("PYTHONPATH", "")


def start_head(session, port):
    return subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.head_main",
         "--session-dir", session, "--port", str(port),
         "--bind-host", "127.0.0.1", "--num-cpus", "2"],
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)


def orchestrate(session, port):
    head = daemon = None
    try:
        head = start_head(session, port)
        deadline = time.time() + 60
        addr_file = os.path.join(session, "head_address")
        while not os.path.exists(addr_file):
            assert time.time() < deadline, "head never came up"
            assert head.poll() is None, "head died at startup"
            time.sleep(0.2)
        with open(os.path.join(session, "authkey"), "rb") as f:
            authkey = f.read().hex()
        with open(addr_file) as f:
            head_addr = f.read().strip()

        # join one worker machine (a daemon over TCP, as `ray_tpu start
        # --address HOST:PORT` would)
        denv = dict(os.environ)
        denv["RAY_TPU_AUTHKEY"] = authkey
        denv["RAY_TPU_DAEMON_RECONNECT_GRACE_S"] = "60"
        daemon = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.daemon", head_addr,
             "node_worker1", json.dumps({"CPU": 2.0, "side": 2.0}), "0"],
            env=denv, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)

        setup = subprocess.run(
            [sys.executable, __file__, "setup", session],
            capture_output=True, text=True, timeout=120)
        sys.stderr.write(setup.stdout + setup.stderr)
        assert setup.returncode == 0, "setup driver failed"
        job_id = [ln.split()[1] for ln in setup.stdout.splitlines()
                  if ln.startswith("JOB_ID")][0]

        # SIGKILL the head mid-workload, then restart into the session
        head.kill()
        head.wait()
        time.sleep(1.0)
        head = start_head(session, port)

        check = subprocess.run(
            [sys.executable, __file__, "check", session, job_id],
            capture_output=True, text=True, timeout=240)
        sys.stderr.write(check.stdout + check.stderr)
        assert check.returncode == 0, "post-restart driver failed"
        assert "RESTART-OK" in check.stdout

        head.terminate()
        head.wait(timeout=30)
        daemon.wait(timeout=30)
        print("ALL-OK")
    finally:
        for p in (head, daemon):
            if p is not None and p.poll() is None:
                p.kill()


def setup(session):
    import ray_tpu
    ray_tpu.init(address=session)

    @ray_tpu.remote(resources={"side": 1}, name="keeper")
    class Keeper:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    k = Keeper.remote()
    assert ray_tpu.get(k.bump.remote(), timeout=60) == 1
    assert ray_tpu.get(k.bump.remote(), timeout=60) == 2

    c = ray_tpu._worker.get_client()
    c.control("kv_put", ("ns", "survives", b"yes"))

    from ray_tpu.job_submission import JobSubmissionClient
    jid = JobSubmissionClient().submit_job(
        entrypoint="sleep 4; echo job-finished")
    print("JOB_ID", jid)
    time.sleep(2.5)   # let a head snapshot land


def check(session, job_id):
    import ray_tpu

    deadline = time.time() + 60
    while True:
        try:
            ray_tpu.init(address=session)
            break
        except (ConnectionError, OSError):
            assert time.time() < deadline, "head never came back"
            time.sleep(0.5)

    # the daemon must re-register within its reconnect grace
    c = ray_tpu._worker.get_client()
    deadline = time.time() + 90
    while True:
        nodes = c.control("list_nodes")
        if any(n["node_id"] == "node_worker1" and n["alive"]
               for n in nodes):
            break
        assert time.time() < deadline, \
            f"daemon never re-registered: {nodes}"
        time.sleep(0.5)

    # detached named actor kept its in-memory state (n == 2 -> bump == 3)
    k = ray_tpu.get_actor("keeper")
    deadline = time.time() + 60
    while True:
        try:
            n = ray_tpu.get(k.bump.remote(), timeout=30)
            break
        except Exception:
            if time.time() >= deadline:
                raise
            time.sleep(0.5)
    assert n == 3, f"actor lost its state: bump() -> {n}"

    assert c.control("kv_get", ("ns", "survives")) == b"yes"

    from ray_tpu.job_submission import JobSubmissionClient
    st = JobSubmissionClient().wait_until_finished(job_id, timeout=120)
    assert st == "SUCCEEDED", st
    logs = JobSubmissionClient().get_job_logs(job_id)
    assert "job-finished" in logs, logs
    print("RESTART-OK")


if __name__ == "__main__":
    mode = sys.argv[1]
    if mode == "orchestrate":
        orchestrate(sys.argv[2], int(sys.argv[3]))
    elif mode == "setup":
        setup(sys.argv[2])
    elif mode == "check":
        check(sys.argv[2], sys.argv[3])
    else:
        raise SystemExit(f"unknown mode {mode}")
