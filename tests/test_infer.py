"""Inference-engine tests: decode-attention kernel parity, KV-cache
prefill/decode vs. full forward, cache donation, compile-once semantics,
and continuous batching (slot reuse / late join) through the engine and
through Serve streaming."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt
from ray_tpu.ops import decode_attention as da


def tiny_cfg(**kw):
    return gpt.GPTConfig(**{**dict(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=64, dtype="float32"), **kw})


def rollout_reference(params, prompt, cfg, steps):
    """Greedy generation via repeated FULL forward passes — the
    O(T^2)-per-token baseline the cache path must match exactly."""
    toks = list(prompt)
    for _ in range(steps):
        logits = gpt.forward(params, jnp.asarray([toks]), cfg)[0, -1]
        toks.append(int(jnp.argmax(logits)))
    return toks[len(prompt):]


# ---------------------------------------------------------------------------
# decode-attention op
# ---------------------------------------------------------------------------

class TestDecodeAttention:
    def _rand(self, b, s, h, d, dtype=jnp.float32):
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (b, h, d), dtype)
        k = jax.random.normal(ks[1], (b, s, h, d), dtype)
        v = jax.random.normal(ks[2], (b, s, h, d), dtype)
        return q, k, v

    def test_reference_masks_positions(self):
        """Entries past pos[b] must not contribute: corrupting them
        leaves the output bit-identical."""
        q, k, v = self._rand(2, 16, 2, 8)
        pos = jnp.array([3, 15], jnp.int32)
        out = da.reference_decode_attention(q, k, v, pos)
        k2 = k.at[0, 4:].set(1e4)
        v2 = v.at[0, 4:].set(-1e4)
        out2 = da.reference_decode_attention(q, k2, v2, pos)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))

    def test_pallas_matches_reference_f32(self):
        q, k, v = self._rand(2, 256, 2, 64)
        pos = jnp.array([0, 200], jnp.int32)
        ref = da.decode_attention(q, k, v, pos, impl="jax")
        out = da.decode_attention(q, k, v, pos, impl="pallas")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_pallas_matches_reference_bf16(self):
        q, k, v = self._rand(1, 128, 2, 64, jnp.bfloat16)
        pos = jnp.array([77], jnp.int32)
        ref = da.decode_attention(q, k, v, pos, impl="jax")
        out = da.decode_attention(q, k, v, pos, impl="pallas")
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=2e-2, rtol=2e-2)

    def test_pallas_padded_head_dim(self):
        """head_dim not a multiple of 8 goes through _pad_heads."""
        q, k, v = self._rand(1, 128, 2, 20)
        pos = jnp.array([64], jnp.int32)
        ref = da.decode_attention(q, k, v, pos, impl="jax")
        out = da.decode_attention(q, k, v, pos, impl="pallas")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_auto_on_cpu_is_jax(self):
        q, k, v = self._rand(1, 64, 2, 16)
        pos = jnp.array([10], jnp.int32)
        auto = da.decode_attention(q, k, v, pos, impl="auto")
        ref = da.decode_attention(q, k, v, pos, impl="jax")
        np.testing.assert_array_equal(np.asarray(auto), np.asarray(ref))

    def test_bad_impl_and_shapes(self):
        q, k, v = self._rand(1, 16, 2, 8)
        pos = jnp.array([1], jnp.int32)
        with pytest.raises(ValueError, match="unknown"):
            da.decode_attention(q, k, v, pos, impl="nope")
        with pytest.raises(ValueError, match="wants q"):
            da.decode_attention(k, k, v, pos)


# ---------------------------------------------------------------------------
# KV-cache model path
# ---------------------------------------------------------------------------

class TestPrefillDecode:
    @pytest.mark.parametrize("dtype,atol", [("float32", 1e-4),
                                            ("bfloat16", 5e-2)])
    def test_matches_full_forward_token_for_token(self, dtype, atol):
        """prefill(prompt) + decode_step per token reproduces the
        full-forward logits at every position."""
        cfg = tiny_cfg(dtype=dtype)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        B, T, P = 2, 10, 4
        toks = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0,
                                  cfg.vocab_size)
        full = gpt.forward(params, toks, cfg)          # [B, T, V]
        cache = gpt.init_kv_cache(cfg, B, 16)
        logits, cache = gpt.prefill(params, toks[:, :P], cache, cfg)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, P - 1], np.float32),
                                   atol=atol, rtol=atol)
        for t in range(P, T):
            pos = jnp.full((B,), t, jnp.int32)
            logits, cache = gpt.decode_step(params, toks[:, t], cache,
                                            pos, cfg)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(full[:, t], np.float32),
                atol=atol, rtol=atol)

    def test_prefill_ragged_lengths(self):
        """lengths= picks each row's own last-token logits; the padded
        tail cannot leak into them (causal masking)."""
        cfg = tiny_cfg()
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(2), (2, 9), 0,
                                  cfg.vocab_size)
        full = gpt.forward(params, toks, cfg)
        cache = gpt.init_kv_cache(cfg, 2, 16)
        lens = jnp.array([5, 9], jnp.int32)
        logits, _ = gpt.prefill(params, toks, cache, cfg, lengths=lens)
        ref = jnp.stack([full[0, 4], full[1, 8]])
        np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_slot_targeted_prefill(self):
        """slot= lands a [1, T] prompt in one cache row and decode picks
        it up there, ignoring garbage in other slots."""
        cfg = tiny_cfg()
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(3), (1, 6), 0,
                                  cfg.vocab_size)
        full = gpt.forward(params, toks, cfg)
        cache = gpt.init_kv_cache(cfg, 4, 16)
        logits, cache = gpt.prefill(params, toks, cache, cfg,
                                    slot=np.int32(2))
        np.testing.assert_allclose(np.asarray(logits[0]),
                                   np.asarray(full[0, -1]),
                                   atol=1e-5, rtol=1e-5)
        nxt = jnp.argmax(full[0, -1]).astype(jnp.int32)
        ext = gpt.forward(
            params, jnp.concatenate([toks, nxt[None, None]], 1), cfg)
        dtoks = jnp.zeros((4,), jnp.int32).at[2].set(nxt)
        dpos = jnp.zeros((4,), jnp.int32).at[2].set(6)
        dl, _ = gpt.decode_step(params, dtoks, cache, dpos, cfg)
        np.testing.assert_allclose(np.asarray(dl[2]),
                                   np.asarray(ext[0, -1]),
                                   atol=1e-4, rtol=1e-4)

    def test_validation_errors(self):
        cfg = tiny_cfg()
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        with pytest.raises(ValueError, match="max_seq_len"):
            gpt.init_kv_cache(cfg, 2, cfg.max_seq_len + 1)
        cache = gpt.init_kv_cache(cfg, 2, 8)
        toks = jnp.zeros((2, 9), jnp.int32)
        with pytest.raises(ValueError, match="exceeds cache"):
            gpt.prefill(params, toks, cache, cfg)
        with pytest.raises(ValueError, match="pass slot"):
            gpt.prefill(params, jnp.zeros((3, 4), jnp.int32), cache, cfg)
        with pytest.raises(ValueError, match="tokens \\[1, T\\]"):
            gpt.prefill(params, toks, cache, cfg, slot=np.int32(0))

    def test_decode_step_cache_donation(self):
        """Under jit(donate_argnums=cache) the compiled step aliases the
        cache input to its output (in-place HBM update) and the donated
        buffers are consumed."""
        cfg = tiny_cfg()
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        cache = gpt.init_kv_cache(cfg, 2, 16)
        toks = jnp.array([3, 5], jnp.int32)
        pos = jnp.array([0, 0], jnp.int32)

        step = jax.jit(
            lambda p, t, c, q: gpt.decode_step(p, t, c, q, cfg),
            donate_argnums=(2,))
        hlo = step.lower(params, toks, cache, pos).compile().as_text()
        assert "input_output_alias" in hlo
        _, new_cache = step(params, toks, cache, pos)
        assert cache["k"].is_deleted() and cache["v"].is_deleted()
        assert not new_cache["k"].is_deleted()

    def test_cache_sharding_specs(self):
        from ray_tpu.parallel import MeshSpec
        from ray_tpu.parallel.sharding import kv_cache_specs
        mesh = MeshSpec(data=-1).build(jax.devices())
        specs = kv_cache_specs(mesh)
        assert set(specs) == {"k", "v"}
        cfg = tiny_cfg(n_layers=1)
        cache = gpt.init_kv_cache(cfg, 8, 8, mesh=mesh)
        assert cache["k"].sharding.spec == specs["k"]


# ---------------------------------------------------------------------------
# continuous-batching engine
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine_setup():
    cfg = tiny_cfg()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def solo(engine_setup):
    """One shared single-request reference engine: its compiled
    prefill/decode are reused by every test that needs 'what would this
    prompt generate alone' (each request runs to completion before the
    next, so runs can't interact)."""
    from ray_tpu.serve.engine import InferenceEngine
    cfg, params = engine_setup
    return InferenceEngine(params, cfg, slots=2, max_len=32,
                           prefill_buckets=(8, 16))


class TestInferenceEngine:
    def _engine(self, cfg, params, **kw):
        from ray_tpu.serve.engine import InferenceEngine
        kw.setdefault("slots", 2)
        kw.setdefault("max_len", 32)
        kw.setdefault("prefill_buckets", (8, 16))
        return InferenceEngine(params, cfg, **kw)

    def test_greedy_matches_full_forward_rollout(self, engine_setup,
                                                 solo):
        cfg, params = engine_setup
        prompt = [5, 9, 3, 7]
        assert solo.generate(prompt, max_new_tokens=6) == \
            rollout_reference(params, prompt, cfg, 6)

    def test_decode_compiles_exactly_once_across_requests(
            self, engine_setup):
        """The acceptance criterion: one decode executable for the
        engine's whole life — across admissions, evictions, bucket
        changes, and temperature/greedy mixes."""
        cfg, params = engine_setup
        eng = self._engine(cfg, params)
        for i, (n, temp) in enumerate([(4, 0.0), (7, 0.0), (3, 1.0),
                                       (12, 0.7), (2, 0.0)]):
            eng.submit([i + 1, i + 2, i + 3], max_new_tokens=n,
                       temperature=temp)
        eng.run_until_idle()
        assert eng.decode_traces == 1
        assert eng.prefill_traces == 1      # every prompt fit bucket 8
        eng.submit(list(range(1, 12)), max_new_tokens=3)  # bucket 16
        eng.run_until_idle()
        assert eng.decode_traces == 1
        assert eng.prefill_traces == 2      # one more bucket, no more

    def test_late_join_does_not_perturb_resident(self, engine_setup,
                                                 solo):
        """A request admitted mid-flight shares decode steps with the
        resident sequence; greedy decode is row-independent, so the
        resident's tokens must be EXACTLY its solo tokens."""
        cfg, params = engine_setup
        want_a = solo.generate([5, 9, 3, 7], max_new_tokens=10)
        want_b = solo.generate([2, 4], max_new_tokens=4)

        eng = self._engine(cfg, params)
        ra = eng.submit([5, 9, 3, 7], max_new_tokens=10)
        ga = eng.tokens_for(ra)
        got_a = [next(ga) for _ in range(3)]      # resident mid-flight
        rb = eng.submit([2, 4], max_new_tokens=4)  # late join
        got_b = list(eng.tokens_for(rb))
        got_a += list(ga)
        assert got_a == want_a
        assert got_b == want_b
        assert eng.decode_traces == 1

    def test_slot_reuse_and_occupancy(self, engine_setup, solo):
        """More requests than slots: retired slots are re-admitted into
        and every request still completes correctly."""
        cfg, params = engine_setup
        eng = self._engine(cfg, params, slots=2)
        prompts = [[i + 1, i + 2] for i in range(5)]
        rids = [eng.submit(p, max_new_tokens=4) for p in prompts]
        eng.run_until_idle()
        for p, rid in zip(prompts, rids):
            assert list(eng.tokens_for(rid)) == \
                solo.generate(p, max_new_tokens=4)
        s = eng.stats()
        assert s["decode_traces"] == 1
        assert 0 < s["slot_occupancy"] <= 1.0
        assert s["active"] == 0 and s["pending"] == 0

    def test_temperature_sampling(self, engine_setup):
        cfg, params = engine_setup
        eng = self._engine(cfg, params)
        out = eng.generate([1, 2, 3], max_new_tokens=8, temperature=1.0)
        assert len(out) == 8
        assert all(0 <= t < cfg.vocab_size for t in out)
        assert eng.decode_traces == 1      # sampling is not a recompile

    def test_eos_stops_early(self, engine_setup, solo):
        cfg, params = engine_setup
        toks = solo.generate([5, 9, 3, 7], max_new_tokens=8)
        eos = toks[2]
        got = solo.generate([5, 9, 3, 7], max_new_tokens=8, eos_id=eos)
        assert got == toks[:3]             # emits eos, then stops

    def test_concurrent_consumers(self, engine_setup, solo):
        """N threads each pumping their own request drive one shared
        continuously-batched loop without deadlock or cross-talk."""
        cfg, params = engine_setup
        eng = self._engine(cfg, params, slots=3)
        prompts = {i: [i + 1, i + 2] for i in range(6)}
        want = {i: solo.generate(p, max_new_tokens=5)
                for i, p in prompts.items()}
        got = {}

        def worker(i):
            got[i] = eng.generate(prompts[i], max_new_tokens=5)
        ts = [threading.Thread(target=worker, args=(i,))
              for i in prompts]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert got == want
        assert eng.decode_traces == 1

    def test_submit_validation(self, engine_setup):
        cfg, params = engine_setup
        eng = self._engine(cfg, params)
        with pytest.raises(ValueError, match="empty"):
            eng.submit([])
        # chunked prefill removed the old bucket-length limit: a prompt
        # longer than the largest prefill bucket is fine as long as it
        # fits the cache.
        assert len(eng.generate(list(range(1, 18)),
                                max_new_tokens=4)) == 4
        with pytest.raises(ValueError, match="max_len"):
            eng.submit([1, 2], max_new_tokens=31)
        tiny = self._engine(cfg, params, cache_blocks=1)
        with pytest.raises(ValueError, match="blocks"):
            tiny.submit(list(range(1, 18)), max_new_tokens=4)


# ---------------------------------------------------------------------------
# through Serve
# ---------------------------------------------------------------------------

@pytest.fixture
def serve_session(ray_session):
    from ray_tpu import serve
    yield serve
    serve.shutdown()


def test_inference_replica_streams_through_serve(serve_session):
    """End-to-end: InferenceReplica deployed through Serve, tokens
    streamed back via the replica's generator/next_chunks machinery, and
    concurrent requests continuously batch into one engine."""
    import concurrent.futures

    from ray_tpu import serve
    from ray_tpu.serve.engine import InferenceReplica

    app = serve.deployment(InferenceReplica).bind(
        dict(vocab_size=128, d_model=32, n_layers=1, n_heads=2,
             d_ff=64, max_seq_len=64, dtype="float32"),
        slots=2, max_len=32)
    h = serve.run(app, name="infer")

    toks = list(h.stream([5, 9, 3], 6))
    assert len(toks) == 6 and all(isinstance(t, int) for t in toks)

    # same prompt, same engine -> same greedy tokens; concurrent
    # requests share the resident engine's slots
    with concurrent.futures.ThreadPoolExecutor(4) as pool:
        outs = list(pool.map(
            lambda _: list(h.stream([5, 9, 3], 6)), range(4)))
    assert all(o == toks for o in outs)

    stats = h.stats.remote()
    import ray_tpu
    s = ray_tpu.get(stats)
    assert s["decode_traces"] == 1
