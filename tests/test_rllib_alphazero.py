"""AlphaZero — self-play MCTS + policy/value net (reference:
rllib/algorithms/alpha_zero/)."""

import numpy as np


def _play_vs_random(algo, rng, az_first: bool) -> float:
    """One TicTacToe game AlphaZero vs random; returns reward from
    AlphaZero's perspective (+1 win, 0 draw, -1 loss)."""
    game = algo.game
    board = game.initial()
    az_turn = az_first
    while True:
        if az_turn:
            a = algo.compute_single_action(board)
        else:
            legal = np.nonzero(game.legal(board))[0]
            a = int(rng.choice(legal))
        board, reward, done = game.step(board, a)
        if done:
            return reward if az_turn else -reward
        az_turn = not az_turn


def test_alphazero_tictactoe_tactics_and_strength():
    from ray_tpu.rllib.algorithms.alpha_zero import AlphaZeroConfig

    cfg = AlphaZeroConfig()
    cfg.seed = 0
    cfg.games_per_iter = 20
    cfg.num_sims = 48
    cfg.n_updates_per_iter = 24
    algo = cfg.build()
    for _ in range(8):
        res = algo.train()
    assert res["replay_positions"] > 200
    assert np.isfinite(res["loss"])

    # tactical probes (board from the CURRENT player's perspective):
    # finish an immediate win...
    win_now = np.array([1, 1, 0,
                        -1, -1, 0,
                        0, 0, 0], np.float32)
    assert algo.compute_single_action(win_now) == 2
    # ...and block the opponent's immediate win when none of ours exists
    block = np.array([-1, -1, 0,
                      1, 0, 0,
                      0, 0, 1], np.float32)
    assert algo.compute_single_action(block) == 2

    # strength: never lose to a random player, win most games
    rng = np.random.default_rng(1)
    results = [_play_vs_random(algo, rng, az_first=(i % 2 == 0))
               for i in range(20)]
    losses = sum(1 for r in results if r < 0)
    wins = sum(1 for r in results if r > 0)
    assert losses == 0, results
    assert wins >= 14, results
