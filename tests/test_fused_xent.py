"""Fused chunked cross-entropy (ops/fused_xent.py): parity with the
dense path, odd shapes, vocab-sharded TP composition, and the no-logits
memory claim."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt
from ray_tpu.ops.fused_xent import fused_softmax_xent
from ray_tpu.parallel import MeshSpec, tree_shardings
from ray_tpu.train import spmd


def _dense_nll(x, emb, tgt):
    logits = jnp.einsum("btd,vd->btv", x, emb,
                        preferred_element_type=jnp.float32)
    picked = jnp.take_along_axis(logits, tgt[..., None], -1)[..., 0]
    return jax.scipy.special.logsumexp(logits, -1) - picked


def _rand(v, shape=(2, 16, 64), seed=0):
    k = jax.random.split(jax.random.key(seed), 3)
    x = jax.random.normal(k[0], shape, jnp.float32)
    emb = jax.random.normal(k[1], (v, shape[-1]), jnp.float32) * 0.1
    tgt = jax.random.randint(k[2], shape[:2], 0, v)
    return x, emb, tgt


@pytest.mark.parametrize("v", [512, 517, 130, 96])
def test_fused_matches_dense_any_vocab(v):
    """Value <= 1e-4 and grads <= 1e-3 vs dense, including vocab sizes
    not divisible by (or smaller than) the chunk."""
    x, emb, tgt = _rand(v)
    ref = _dense_nll(x, emb, tgt)
    out = fused_softmax_xent(x, emb, tgt, vocab_chunk=128, impl="scan")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4)
    gd = jax.grad(lambda x, e: _dense_nll(x, e, tgt).mean(),
                  argnums=(0, 1))(x, emb)
    gf = jax.grad(
        lambda x, e: fused_softmax_xent(
            x, e, tgt, vocab_chunk=128, impl="scan").mean(),
        argnums=(0, 1))(x, emb)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-3)


def test_pallas_kernels_match_dense():
    """The TPU kernels (forward + both backward kernels), via interpret
    mode on CPU."""
    x, emb, tgt = _rand(512)
    ref = _dense_nll(x, emb, tgt)
    out = fused_softmax_xent(x, emb, tgt, vocab_chunk=128, impl="pallas")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4)
    gd = jax.grad(lambda x, e: _dense_nll(x, e, tgt).mean(),
                  argnums=(0, 1))(x, emb)
    gp = jax.grad(
        lambda x, e: fused_softmax_xent(
            x, e, tgt, vocab_chunk=128, impl="pallas").mean(),
        argnums=(0, 1))(x, emb)
    for a, b in zip(gp, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-3)


@pytest.mark.parametrize("mesh_kw", [dict(tensor=8),
                                     dict(data=2, tensor=4),
                                     dict(data=2, fsdp=2, tensor=2)])
def test_vocab_sharded_tp_matches_dense(mesh_kw):
    """Vocab-sharded embed: per-shard partial logsumexp psum'd over the
    tensor axis reproduces the unsharded loss AND both grads — dembed's
    batch reduction and dx's vocab reduction each cross different mesh
    axes, so every composition here exercises a distinct collective."""
    mesh = MeshSpec(**mesh_kw).build()
    x, emb, tgt = _rand(512, shape=(4, 16, 64), seed=1)
    ref = _dense_nll(x, emb, tgt)
    gd = jax.grad(lambda x, e: _dense_nll(x, e, tgt).mean(),
                  argnums=(0, 1))(x, emb)
    out = jax.jit(lambda x, e: fused_softmax_xent(
        x, e, tgt, vocab_chunk=128, mesh=mesh))(x, emb)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4)
    gf = jax.jit(jax.grad(
        lambda x, e: fused_softmax_xent(
            x, e, tgt, vocab_chunk=128, mesh=mesh).mean(),
        argnums=(0, 1)))(x, emb)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-3)


@pytest.mark.parametrize("dtype,val_tol,grad_tol", [
    ("float32", 1e-4, 1e-3),
    # bf16 activations: the fused path keeps f32 accumulators while the
    # dense path quantizes grads through the bf16 logits cotangent, so
    # they differ by ~one bf16 ulp (2^-9 relative)
    ("bfloat16", 1e-4, 4e-3),
])
def test_gpt_loss_impl_parity(dtype, val_tol, grad_tol):
    """cfg.loss_impl="fused" reproduces the dense GPT loss and all
    parameter gradients, for f32 and bf16 activation configs."""
    cfg_d = gpt.small(dtype=dtype, attn_impl="xla")
    cfg_f = dataclasses.replace(cfg_d, loss_impl="fused")
    params = gpt.init_params(jax.random.PRNGKey(0), cfg_d)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, cfg_d.vocab_size, (2, 33)),
        jnp.int32)
    ld, gd = jax.value_and_grad(gpt.loss_fn)(
        params, {"tokens": tokens}, cfg_d)
    lf, gf = jax.value_and_grad(gpt.loss_fn)(
        params, {"tokens": tokens}, cfg_f)
    assert abs(float(ld) - float(lf)) < val_tol
    for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gd)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=grad_tol)


def test_gpt_trainer_fused_tp_matches_dense():
    """make_gpt_trainer with loss_impl="fused" on a dp x tensor mesh:
    first-step loss and grad norm match the dense trainer."""
    mesh = MeshSpec(data=2, tensor=4).build()
    cfg_d = gpt.small(dtype="float32", attn_impl="xla")
    cfg_f = dataclasses.replace(cfg_d, loss_impl="fused")
    tok = np.random.default_rng(4).integers(
        0, cfg_d.vocab_size, (4, 33), np.int32)
    out = {}
    for name, cfg in [("dense", cfg_d), ("fused", cfg_f)]:
        state, step_fn, shard_tokens = spmd.make_gpt_trainer(cfg, mesh)
        batch = shard_tokens({"inputs": tok[:, :-1].copy(),
                              "targets": tok[:, 1:].copy()})
        _, metrics = step_fn(state, batch)
        out[name] = (float(metrics["loss"]), float(metrics["grad_norm"]))
    assert abs(out["fused"][0] - out["dense"][0]) < 1e-4
    assert abs(out["fused"][1] - out["dense"][1]) < 1e-3


def test_fused_loss_never_materializes_logits():
    """The memory claim: the fused forward+backward graph contains no
    [B, T, vocab] tensor — peak loss activation is O(B*T*chunk). Checked
    on the lowered HLO of value_and_grad (the dense graph is the
    positive control for the shape probe)."""
    # vocab 768 so the [B, T, V] probe can't collide with the MLP hidden
    # [B, T, d_ff=512]; chunk < vocab, or the single "chunk" IS the
    # logits tensor
    cfg_d = gpt.small(dtype="float32", attn_impl="xla", vocab_size=768)
    cfg_f = dataclasses.replace(cfg_d, loss_impl="fused", loss_chunk=128)
    tokens = jnp.zeros((2, 33), jnp.int32)
    b, t, v = 2, 32, cfg_d.vocab_size
    logits_shape = f"{b}x{t}x{v}"

    def lowered(cfg):
        f = jax.jit(lambda p, b: jax.value_and_grad(gpt.loss_fn)(
            p, b, cfg))
        params = jax.eval_shape(
            lambda: gpt.init_params(jax.random.PRNGKey(0), cfg))
        return f.lower(params, {"tokens": tokens}).as_text()

    assert logits_shape in lowered(cfg_d)        # probe sanity
    assert logits_shape not in lowered(cfg_f)


def test_gpt_trainer_fused_keeps_donation():
    """Buffer donation on the train step survives the fused loss: the
    pre-step param buffer is invalidated and the compiled module aliases
    inputs to outputs."""
    mesh = MeshSpec(data=1).build(jax.devices()[:1])
    cfg = gpt.small(dtype="float32", attn_impl="xla", loss_impl="fused")
    state, step_fn, shard_tokens = spmd.make_gpt_trainer(cfg, mesh)
    tok = np.random.default_rng(5).integers(
        0, cfg.vocab_size, (2, 33), np.int32)
    batch = shard_tokens({"inputs": tok[:, :-1].copy(),
                          "targets": tok[:, 1:].copy()})
    assert "input_output_alias" in step_fn.lower(
        state, batch).compile().as_text()
    old_embed = state.params["embed"]
    state, _ = step_fn(state, batch)
    assert old_embed.is_deleted()


def test_loss_impl_validated_at_trace_time():
    cfg = gpt.small(attn_impl="xla", loss_impl="dense_v2")
    params = jax.eval_shape(
        lambda: gpt.init_params(jax.random.PRNGKey(0), cfg))
    with pytest.raises(ValueError, match="loss_impl"):
        gpt.loss_fn(params, {"tokens": jnp.zeros((2, 9), jnp.int32)}, cfg)
    with pytest.raises(ValueError, match="loss_impl"):
        spmd.gpt_loss_fn(
            params, {"inputs": jnp.zeros((2, 8), jnp.int32),
                     "targets": jnp.zeros((2, 8), jnp.int32)}, cfg)
    with pytest.raises(ValueError, match="impl"):
        x, emb, tgt = _rand(512)
        fused_softmax_xent(x, emb, tgt, impl="tensorcore")
