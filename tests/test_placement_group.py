"""Placement group reservation tests (reference:
`python/ray/tests/test_placement_group.py`)."""

import pytest

import ray_tpu
from ray_tpu.exceptions import PlacementGroupError
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


def test_pg_reserves_resources(ray_session):
    before = ray_tpu.available_resources()["CPU"]
    pg = placement_group([{"CPU": 1}, {"CPU": 1}])
    assert ray_tpu.available_resources()["CPU"] == before - 2
    remove_placement_group(pg)
    assert ray_tpu.available_resources()["CPU"] == before


def test_pg_infeasible(ray_session):
    with pytest.raises(PlacementGroupError):
        placement_group([{"CPU": 1000}])


def test_task_in_pg(ray_session):
    pg = placement_group([{"CPU": 2}])

    @ray_tpu.remote
    def where():
        return "in-pg"

    strategy = PlacementGroupSchedulingStrategy(placement_group=pg)
    ref = where.options(num_cpus=1, scheduling_strategy=strategy).remote()
    assert ray_tpu.get(ref, timeout=60) == "in-pg"
    remove_placement_group(pg)


def test_pg_ready(ray_session):
    pg = placement_group([{"CPU": 1}])
    assert ray_tpu.get(pg.ready(), timeout=10) is True
    remove_placement_group(pg)
