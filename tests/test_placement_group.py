"""Placement group reservation tests (reference:
`python/ray/tests/test_placement_group.py`)."""

import pytest

import ray_tpu
from ray_tpu.exceptions import PlacementGroupError
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


def test_pg_reserves_resources(ray_session):
    before = ray_tpu.available_resources()["CPU"]
    pg = placement_group([{"CPU": 1}, {"CPU": 1}])
    assert ray_tpu.available_resources()["CPU"] == before - 2
    remove_placement_group(pg)
    assert ray_tpu.available_resources()["CPU"] == before


def test_pg_infeasible(ray_session):
    with pytest.raises(PlacementGroupError):
        placement_group([{"CPU": 1000}])


def test_task_in_pg(ray_session):
    pg = placement_group([{"CPU": 2}])

    @ray_tpu.remote
    def where():
        return "in-pg"

    strategy = PlacementGroupSchedulingStrategy(placement_group=pg)
    ref = where.options(num_cpus=1, scheduling_strategy=strategy).remote()
    assert ray_tpu.get(ref, timeout=60) == "in-pg"
    remove_placement_group(pg)


def test_pg_ready(ray_session):
    pg = placement_group([{"CPU": 1}])
    assert ray_tpu.get(pg.ready(), timeout=10) is True
    remove_placement_group(pg)


# ---------------------------------------------------------------------------
# contention-aware gang placement (pure planner, 2207.07817's link model)
# ---------------------------------------------------------------------------

from ray_tpu._private.node import plan_gang_placement


def _two_link_topology():
    """Four equal nodes, two per interconnect link group."""
    pools = [(n, {"CPU": 2.0}) for n in ("n1", "n2", "n3", "n4")]
    links = {"n1": ("ici0",), "n2": ("ici0",),
             "n3": ("ici1",), "n4": ("ici1",)}
    return pools, links


def test_spread_tagged_gangs_get_disjoint_links():
    pools, links = _two_link_topology()
    gang = [{"CPU": 1.0}, {"CPU": 1.0}]
    first = plan_gang_placement(pools, gang, "SPREAD", links=links,
                                link_load={}, bandwidth=10.0)
    assert first == ["n1", "n2"]
    # first gang now loads ici0; the second tagged gang must steer to
    # the other link entirely
    load = {"ici0": 1}
    second = plan_gang_placement(pools, gang, "SPREAD", links=links,
                                 link_load=load, bandwidth=10.0)
    assert second == ["n3", "n4"]
    first_links = {l for n in first for l in links[n]}
    second_links = {l for n in second for l in links[n]}
    assert first_links.isdisjoint(second_links)


def test_untagged_gang_ignores_link_load():
    pools, links = _two_link_topology()
    gang = [{"CPU": 1.0}, {"CPU": 1.0}]
    # heavy load on ici0 — an untagged gang must keep the legacy
    # (bundle-count, arrival-order) placement regardless
    got = plan_gang_placement(pools, gang, "SPREAD", links=links,
                              link_load={"ici0": 7}, bandwidth=0.0)
    assert got == ["n1", "n2"]


def test_pack_tagged_gang_prefers_quiet_link():
    pools, links = _two_link_topology()
    gang = [{"CPU": 1.0}, {"CPU": 1.0}]
    # PACK with no tag: first-fit in arrival order
    assert plan_gang_placement(pools, gang, "PACK", links=links,
                               link_load={"ici0": 1}) == ["n1", "n1"]
    # tagged: the quiet link's first node wins, and PACK still packs
    got = plan_gang_placement(pools, gang, "PACK", links=links,
                              link_load={"ici0": 1}, bandwidth=2.0)
    assert got == ["n3", "n3"]


def test_strict_spread_tagged_ranks_by_contention():
    pools, links = _two_link_topology()
    gang = [{"CPU": 1.0}, {"CPU": 1.0}]
    got = plan_gang_placement(pools, gang, "STRICT_SPREAD", links=links,
                              link_load={"ici0": 3, "ici1": 1},
                              bandwidth=1.0)
    assert got == ["n3", "n4"]


def test_contention_scoring_is_deterministic():
    pools, links = _two_link_topology()
    gang = [{"CPU": 1.0}] * 3
    load = {"ici0": 2, "ici1": 1}
    runs = [plan_gang_placement(pools, gang, strat, links=links,
                                link_load=dict(load), bandwidth=4.0)
            for strat in ("SPREAD", "PACK", "STRICT_SPREAD")
            for _ in range(3)]
    assert runs[0:3] == [runs[0]] * 3
    assert runs[3:6] == [runs[3]] * 3
    assert runs[6:9] == [runs[6]] * 3
    # ties (equal contention) break on arrival order, never dict order
    even = plan_gang_placement(pools, [{"CPU": 1.0}], "PACK", links=links,
                               link_load={"ici0": 1, "ici1": 1},
                               bandwidth=1.0)
    assert even == ["n1"]


def test_planner_infeasible_returns_none():
    pools, links = _two_link_topology()
    assert plan_gang_placement(pools, [{"CPU": 99.0}], "SPREAD",
                               links=links, bandwidth=1.0) is None


def test_bandwidth_tag_via_public_api(ray_session):
    pg = placement_group([{"CPU": 1}], bandwidth=12.5)
    assert pg.bandwidth == 12.5
    from ray_tpu._private import worker as _worker
    rows = _worker.get_client().control("list_placement_groups", {})
    mine = [r for r in rows if r["placement_group_id"] == pg.id]
    assert mine and mine[0]["bandwidth"] == 12.5
    remove_placement_group(pg)


def test_bandwidth_rejects_negative(ray_session):
    with pytest.raises(ValueError):
        placement_group([{"CPU": 1}], bandwidth=-1.0)
