"""Multi-agent env + MAPPO, DDPG/TD3, and the tuned-example regression
harness (reference: rllib/env/multi_agent_env.py tests, td3 tests,
rllib/tests/run_regression_tests.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.rllib.env.multi_agent import CoopMatch
from ray_tpu.rllib.train import (
    list_tuned_examples,
    run_experiment,
    run_tuned_example,
)


def test_multi_agent_env_contract():
    env = CoopMatch({"n_agents": 3, "n_tokens": 4, "episode_len": 5})
    assert env.agent_ids == ("agent_0", "agent_1", "agent_2")
    key = jax.random.PRNGKey(0)
    state, obs = env.reset(key)
    assert set(obs) == set(env.agent_ids)
    assert obs["agent_0"].shape == (4,)
    acts = {aid: jnp.argmax(obs[aid]) for aid in env.agent_ids}
    state, obs2, rew, done, _ = env.step(state, acts, key)
    # all actions matched their tokens -> shared reward 1.0 for everyone
    for aid in env.agent_ids:
        assert float(rew[aid]) == pytest.approx(1.0)
    assert not bool(done)

    # vmap over a batch of envs (the in-graph vector path)
    keys = jax.random.split(key, 4)
    bstate, bobs = jax.vmap(env.reset)(keys)
    assert bobs["agent_1"].shape == (4, 4)
    bacts = {aid: jnp.zeros(4, jnp.int32) for aid in env.agent_ids}
    _, _, brew, bdone, _ = jax.vmap(env.step)(bstate, bacts, keys)
    assert brew["agent_2"].shape == (4,)


def test_mappo_learns_cooperative_toy():
    """Shared-reward coordination: MAPPO with per-agent policies reaches
    >=12 of the optimal 16 episode reward (the VERDICT acceptance
    criterion: multi-agent PPO learns a cooperative toy env)."""
    result = run_tuned_example(
        [p for p in list_tuned_examples() if "coopmatch-mappo" in p][0],
        verbose=False)
    assert result["passed"], result
    assert result["best_reward"] >= 12, result


def test_mappo_per_agent_policies():
    from ray_tpu.rllib.algorithms.ma_ppo import MAPPOConfig
    algo = (MAPPOConfig().environment("CoopMatch")
            .training(model={"fcnet_hiddens": (16, 16)})
            .rollouts(num_envs_per_worker=8, rollout_fragment_length=16)
            .debugging(seed=1)
            .multi_agent(policies={"p0", "p1"},
                         policy_mapping_fn=lambda aid: "p" + aid[-1])
            .build())
    r = algo.train()
    assert "p0/policy_loss" in r and "p1/policy_loss" in r
    # distinct parameter trees per policy
    assert set(algo.params) == {"p0", "p1"}
    acts = algo.compute_actions(
        {"agent_0": np.eye(3)[0], "agent_1": np.eye(3)[2]})
    assert set(acts) == {"agent_0", "agent_1"}
    # checkpoint roundtrip
    state = algo.get_state()
    algo.set_state(state)


@pytest.mark.slow
def test_td3_pendulum_improves():
    """TD3 clearly improves from the ~-1400 random-policy floor within a
    small budget (full -900 threshold lives in pendulum-td3.yaml)."""
    from ray_tpu.rllib.algorithms.ddpg import TD3Config
    algo = (TD3Config().environment("Pendulum-v1")
            .training(n_updates_per_iter=256, learning_starts=500,
                      train_batch_size=128, no_done_at_end=True,
                      exploration_noise=0.15,
                      model={"fcnet_hiddens": (64, 64)})
            .rollouts(num_envs_per_worker=32, rollout_fragment_length=8)
            .debugging(seed=0)
            .build())
    best = -1e9
    for _ in range(55):
        r = algo.train()
        rew = r.get("episode_reward_mean")
        if rew == rew:
            best = max(best, rew)
        if best > -950:
            break
    assert best > -950, best


def test_ddpg_td3_config_flags():
    from ray_tpu.rllib.algorithms.ddpg import DDPGConfig, TD3Config
    d, t = DDPGConfig(), TD3Config()
    assert not d.twin_q and d.policy_delay == 1 and d.target_noise == 0.0
    assert t.twin_q and t.policy_delay == 2 and t.target_noise == 0.2


def test_tuned_examples_parse_and_resolve():
    """Every shipped YAML names a registered algorithm and an env that
    make_env can resolve, and carries a reward-threshold stop."""
    import yaml

    from ray_tpu.rllib.algorithms import get_algorithm_class
    from ray_tpu.rllib.env.jax_env import _ENV_REGISTRY

    paths = list_tuned_examples()
    assert len(paths) >= 4
    for p in paths:
        with open(p) as f:
            spec = yaml.safe_load(f)
        _, body = next(iter(spec.items()))
        assert get_algorithm_class(body["run"]) is not None
        assert body["env"] in _ENV_REGISTRY
        assert "episode_reward_mean" in body["stop"]


def test_cli_runs_without_reward_target(capsys):
    from ray_tpu.rllib.train import main
    rc = main(["--algo", "A2C", "--env", "CartPole-v1",
               "--stop-iters", "2",
               "--config", '{"num_envs_per_worker": 4, '
                           '"rollout_fragment_length": 16}'])
    assert rc == 0
    out = capsys.readouterr().out
    assert '"passed": true' in out


def test_run_experiment_reports_failure():
    out = run_experiment(
        "A2C", "CartPole-v1",
        config={"num_envs_per_worker": 4, "rollout_fragment_length": 16},
        stop={"episode_reward_mean": 1e9, "training_iteration": 2},
        verbose=False)
    assert not out["passed"]
    assert out["iterations"] == 2
