"""MADDPG / ARS / CRR — the round-5 algorithm additions.

References: `rllib/algorithms/maddpg/` (centralized critics,
decentralized actors), `rllib/algorithms/ars/` (top-b direction search
with obs whitening), `rllib/algorithms/crr/` (offline critic-regularized
regression). Each validated the way the reference validates them:
tuned-config learning regressions with reward thresholds, plus
mechanism-level unit checks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.env.jax_env import JaxEnv
from ray_tpu.rllib.env.spaces import Box
from ray_tpu.rllib.sample_batch import SampleBatch
from ray_tpu.rllib.train import list_tuned_examples, run_tuned_example


def _run_yaml(substr: str) -> dict:
    path = [p for p in list_tuned_examples() if substr in p]
    assert path, f"tuned example {substr} missing"
    return run_tuned_example(path[0], verbose=False)


def test_maddpg_coopmatch_regression():
    out = _run_yaml("coopmatch-maddpg")
    assert out["passed"], out


def test_maddpg_decentralized_execution():
    """After centralized training, each actor solves its token from its
    LOCAL observation alone."""
    from ray_tpu.rllib.algorithms.maddpg import MADDPGConfig
    algo = (MADDPGConfig()
            .environment("CoopMatch",
                         env_config={"n_agents": 2, "n_tokens": 3,
                                     "episode_len": 8})
            .rollouts(num_envs_per_worker=32, rollout_fragment_length=16)
            .training(learning_starts=500, n_updates_per_iter=16)
            .debugging(seed=0).build())
    for _ in range(25):
        r = algo.train()
    assert r["episode_reward_mean"] > 7.0, r
    eye = np.eye(3, dtype=np.float32)
    for t0 in range(3):
        for t1 in range(3):
            joint = algo.compute_joint_action(
                {"agent_0": eye[t0], "agent_1": eye[t1]})
            assert joint == {"agent_0": t0, "agent_1": t1}, (t0, t1, joint)


def test_ars_cartpole_regression():
    out = _run_yaml("cartpole-ars")
    assert out["passed"], out


def test_ars_observation_filter_updates():
    """The V2 whitening stats converge to the visited-state moments."""
    from ray_tpu.rllib.algorithms.ars import ARSConfig
    algo = (ARSConfig().environment("CartPole-v1")
            .training(num_directions=8, top_directions=4,
                      episode_horizon=50,
                      model={"fcnet_hiddens": (8,)})
            .debugging(seed=0).build())
    algo.train()
    cnt, mu, m2 = algo._obs_stats
    assert float(cnt) > 100               # many steps observed
    assert np.all(np.isfinite(np.asarray(mu)))
    sigma = np.sqrt(np.asarray(m2) / float(cnt))
    assert np.all(sigma > 0) and np.all(np.isfinite(sigma))


# ---------------------------------------------------------------------------
# CRR: offline continuous control with a known-optimal synthetic task
# ---------------------------------------------------------------------------


class _ContBandit(JaxEnv):
    """One-step continuous task used for spaces only (CRR never rolls
    out). Optimal action a*(s) = (0.5*s0, -0.5*s1)."""

    def __init__(self, env_config=None):
        self.observation_space = Box(-1.0, 1.0, (2,))
        self.action_space = Box(-1.0, 1.0, (2,))


def _optimal(obs):
    return np.stack([0.5 * obs[:, 0], -0.5 * obs[:, 1]], axis=-1)


def _crr_dataset(n=3000, noise=0.5, seed=0):
    rng = np.random.default_rng(seed)
    obs = rng.uniform(-1, 1, size=(n, 2)).astype(np.float32)
    a_star = _optimal(obs)
    act = np.clip(a_star + rng.normal(0, noise, a_star.shape), -1, 1)
    rew = 2.0 - np.sum(np.square(act - a_star), axis=-1)
    return SampleBatch({
        sb.OBS: obs,
        sb.ACTIONS: act.astype(np.float32),
        sb.REWARDS: rew.astype(np.float32),
        sb.DONES: np.ones(n, bool),
        sb.NEXT_OBS: obs,           # unused: every row terminal
    })


@pytest.mark.parametrize("mode", ["exp", "binary"])
def test_crr_recovers_optimal_from_noisy_data(tmp_path, mode):
    """Advantage-weighted regression must pull the policy from the noisy
    behaviour toward the high-advantage actions: the learned mean action
    lands far closer to a*(s) than the behaviour data."""
    from ray_tpu.rllib.algorithms.crr import CRRConfig
    from ray_tpu.rllib.offline import JsonWriter

    data = _crr_dataset()
    w = JsonWriter(str(tmp_path))
    w.write(data)
    w.close()

    algo = (CRRConfig().environment(_ContBandit)
            .offline_data(input_=str(tmp_path))
            .training(weight_mode=mode, n_updates_per_iter=128,
                      train_batch_size=256, lr=1e-3, gamma=0.0)
            .debugging(seed=0).build())
    for _ in range(6):
        r = algo.train()
    assert np.isfinite(r["critic_loss"]) and np.isfinite(r["actor_loss"])

    rng = np.random.default_rng(1)
    test_obs = rng.uniform(-1, 1, size=(256, 2)).astype(np.float32)
    a_star = _optimal(test_obs)
    learned = np.stack([algo.compute_single_action(o) for o in test_obs])
    mse_learned = float(np.mean(np.square(learned - a_star)))
    # behaviour noise sigma=0.5 -> clipped MSE ~0.4 over 2 dims
    behav = np.clip(a_star + rng.normal(0, 0.5, a_star.shape), -1, 1)
    mse_behaviour = float(np.mean(np.square(behav - a_star)))
    assert mse_learned < mse_behaviour / 3, (mse_learned, mse_behaviour)
    assert r["advantage_mean"] == pytest.approx(0.0, abs=1.0)


def test_crr_requires_offline_input():
    from ray_tpu.rllib.algorithms.crr import CRRConfig
    with pytest.raises(ValueError, match="OFFLINE"):
        CRRConfig().environment(_ContBandit).build()


def test_es_fitness_masks_after_first_done():
    """ES/ARS fitness is the FIRST episode's return — a policy that dies
    immediately must score near zero even though the auto-resetting env
    pays +1 every step (regression for the vacuous-fitness bug)."""
    from ray_tpu.rllib.algorithms.es import ESConfig
    algo = (ESConfig().environment("CartPole-v1")
            .training(population_size=8, episode_horizon=200,
                      model={"fcnet_hiddens": (8,)})
            .debugging(seed=0).build())
    r = algo.train()
    # untrained population: mean first-episode return is ~10-40 steps,
    # nowhere near the 200-step horizon
    assert r["episode_reward_mean"] < 150, r
