"""Test harness configuration.

SPMD tests run on a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count`` — the counterpart of the
reference's one-host multi-raylet ``Cluster`` fixture trick
(`python/ray/cluster_utils.py:99`): fake resources let a laptop test
multi-device logic (SURVEY.md §4.2).
"""

import os

# Must happen before any jax import anywhere in the test session.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The image's sitecustomize registers the TPU PJRT plugin and overrides the
# platform even when JAX_PLATFORMS=cpu is in the env; the config knob wins.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test (full tuned regressions)")


@pytest.fixture(scope="session")
def ray_session():
    """One shared local session for all tests (worker spawn is ~2s on the
    1-CPU CI box, so tests share a pool like the reference's
    ray_start_regular fixture, conftest.py:410)."""
    import ray_tpu
    # num_tpus=2 fakes two chips (resources are scheduler numbers, like the
    # reference's Cluster.add_node(num_gpus=8) on a laptop, SURVEY.md §4).
    ray_tpu.init(num_cpus=4, num_tpus=2, ignore_reinit_error=True)
    yield ray_tpu
    # Telemetry-plane self-test before teardown: the whole session's
    # metric registry must still render parseable Prometheus, every
    # span ring must honor its bound, and every retrace sentinel must
    # still be watching its pinned paths.
    from ray_tpu.util import telemetry
    telemetry.check_invariants()
    ray_tpu.shutdown()
