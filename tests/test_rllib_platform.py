"""RLlib platform seams: connectors, external-env policy server, and the
IMPALA async-learner throughput floor.

References: `rllib/connectors/connector.py` (+agent/action pipelines),
`rllib/env/policy_server_input.py` + `policy_client.py` (client-server
RL), and the tuned-example throughput oracles
(`tuned_examples/impala/pong-impala-fast.yaml:1-4` — time-to-result
floors as regressions).
"""

import time

import numpy as np
import pytest

from ray_tpu.rllib.connectors import (
    ClipActions,
    ClipObs,
    ConnectorPipeline,
    FlattenObs,
    NormalizeObs,
    UnsquashActions,
    default_action_pipeline,
)


# ---------------------------------------------------------------------------
# connectors
# ---------------------------------------------------------------------------

def test_flatten_obs_dict_and_nested():
    f = FlattenObs()
    out = f({"b": np.ones((2, 2)), "a": np.zeros(3)})
    assert out.shape == (7,)
    # sorted key order: 'a' zeros first
    assert np.array_equal(out[:3], np.zeros(3))
    assert np.array_equal(f((np.zeros(2), np.ones(2))),
                          np.array([0, 0, 1, 1], np.float32))


def test_clip_obs_and_actions():
    assert np.array_equal(
        ClipObs(-1, 1)(np.array([-5.0, 0.5, 9.0])),
        np.array([-1.0, 0.5, 1.0]))
    clip = ClipActions(low=np.array([-2.0]), high=np.array([2.0]))
    assert clip(np.array([3.5]))[0] == 2.0


def test_unsquash_actions():
    un = UnsquashActions(low=np.array([0.0]), high=np.array([10.0]))
    assert un(np.array([-1.0]))[0] == 0.0
    assert un(np.array([1.0]))[0] == 10.0
    assert un(np.array([0.0]))[0] == 5.0


def test_normalize_obs_running_stats_and_state_sync():
    rng = np.random.default_rng(0)
    data = rng.normal(5.0, 2.0, size=(500, 3))
    learner = NormalizeObs()
    learner.update(data)
    out = learner(data)
    assert abs(out.mean()) < 0.1 and abs(out.std() - 1.0) < 0.1
    # worker applies a FROZEN copy synced via state()
    worker = NormalizeObs()
    worker.set_state(learner.state())
    x = data[0]
    assert np.allclose(worker(x), learner(x))


def test_pipeline_composition_and_state():
    norm = NormalizeObs()
    norm.update(np.arange(30.0).reshape(10, 3))
    pipe = ConnectorPipeline([FlattenObs(), norm, ClipObs(-2, 2)])
    out = pipe({"x": np.array([100.0, 0.0, -100.0])})
    assert out.max() <= 2.0 and out.min() >= -2.0
    clone = ConnectorPipeline([FlattenObs(), NormalizeObs(),
                               ClipObs(-2, 2)])
    clone.set_state(pipe.state())
    assert np.allclose(clone({"x": np.array([1.0, 2.0, 3.0])}),
                       pipe({"x": np.array([1.0, 2.0, 3.0])}))


def test_default_action_pipeline_spaces():
    from ray_tpu.rllib.env.spaces import Box, Discrete
    assert len(default_action_pipeline(Discrete(3)).connectors) == 0
    box = Box(-2.0, 2.0, (1,))
    pipe = default_action_pipeline(box)
    assert pipe(np.array([99.0]))[0] == 2.0


# ---------------------------------------------------------------------------
# external-env policy server (client-server RL)
# ---------------------------------------------------------------------------

def test_policy_server_external_env_training():
    """An external simulator (PolicyClient around an eager CartPole)
    drives episodes against a DQN policy served by PolicyServerInput;
    the server's batches feed DQN through the offline-input seam and
    training runs on purely external experience."""
    from ray_tpu.rllib.algorithms.dqn import DQNConfig
    from ray_tpu.rllib.env.jax_env import CartPole, EagerJaxEnv
    from ray_tpu.rllib.env.policy_server import (
        PolicyClient, PolicyServerInput)

    server_box = {}

    algo = (DQNConfig().environment("CartPole-v1")
            .training(learning_starts=64, train_batch_size=64,
                      n_updates_per_iter=16,
                      model={"fcnet_hiddens": (32, 32)})
            .offline_data(input_=lambda: server_box["s"].next_batch(
                min_steps=1, timeout=60))
            .debugging(seed=0)
            .build())

    server = PolicyServerInput(
        lambda obs: algo.compute_single_action(obs, explore=True))
    server_box["s"] = server
    try:
        client = PolicyClient(server.address, server.authkey)
        env = EagerJaxEnv(CartPole({}), seed=1)

        total_external_steps = 0
        for _ in range(6):
            # the EXTERNAL side plays a few episodes...
            for _ep in range(3):
                eid = client.start_episode()
                obs = env.reset()
                for _step in range(60):
                    action = client.get_action(eid, obs)
                    obs, r, done, _ = env.step(action)
                    client.log_returns(eid, r)
                    total_external_steps += 1
                    if done:
                        break
                client.end_episode(eid, obs)
            # ...and the learner trains on what arrived
            result = algo.train()

        assert result["num_env_steps_sampled"] == total_external_steps
        assert result["buffer_size"] == total_external_steps
        assert result["episode_reward_mean"] > 0
        assert np.isfinite(result["loss"])
        # greedy serving still works after training
        a = client.get_action(client.start_episode(), env.reset())
        assert a in (0, 1)
        client.close()
    finally:
        server.stop()


def test_policy_server_log_action_offpolicy():
    """log_action records experience the CLIENT chose (human/legacy
    controller) — the off-policy recording path."""
    from ray_tpu.rllib.env.policy_server import (
        PolicyClient, PolicyServerInput)

    server = PolicyServerInput(lambda obs: 0)
    try:
        client = PolicyClient(server.address, server.authkey)
        eid = client.start_episode()
        for i in range(5):
            client.log_action(eid, np.ones(4) * i, i % 2)
            client.log_returns(eid, 1.0)
        client.end_episode(eid, np.ones(4) * 5)
        batch = server.next_batch(min_steps=5, timeout=10)
        assert len(batch) == 5
        assert batch["actions"].tolist() == [0, 1, 0, 1, 0]
        assert batch["rewards"].sum() == 5.0
        assert batch["dones"][-1] and not batch["dones"][:-1].any()
        # new_obs shifted by one, closed by the terminal observation
        assert np.array_equal(batch["new_obs"][-1], np.ones(4) * 5)
        client.close()
    finally:
        server.stop()


def test_policy_server_connectors_applied():
    from ray_tpu.rllib.env.policy_server import (
        PolicyClient, PolicyServerInput)

    seen = []
    server = PolicyServerInput(
        lambda obs: seen.append(np.asarray(obs)) or 0,
        obs_connectors=ConnectorPipeline([FlattenObs(), ClipObs(-1, 1)]))
    try:
        client = PolicyClient(server.address, server.authkey)
        eid = client.start_episode()
        client.get_action(eid, {"a": np.array([5.0, -5.0])})
        assert seen[0].tolist() == [1.0, -1.0]
        client.close()
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# IMPALA async-learner throughput regression
# ---------------------------------------------------------------------------

# Floor chosen at roughly half the measured steady-state rate on the
# 1-core CI box (~1040 env-steps/s with 2 rollout actors contending for
# the single core), so real regressions trip it but scheduler noise
# doesn't.
IMPALA_STEPS_PER_S_FLOOR = 500.0


def test_impala_throughput_floor(ray_session):
    from ray_tpu.rllib.algorithms.impala import IMPALAConfig
    algo = (IMPALAConfig().environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=16,
                      rollout_fragment_length=64)
            .training(batches_per_step=4)
            .debugging(seed=0)
            .build())
    try:
        first = algo.train()              # warm-up: compile + spawn
        t0 = time.perf_counter()
        steps0 = first["num_env_steps_trained"]
        last = {}
        for _ in range(5):
            last = algo.train()
        dt = time.perf_counter() - t0
        steps = last["num_env_steps_trained"] - steps0
        rate = steps / dt
        assert rate >= IMPALA_STEPS_PER_S_FLOOR, \
            f"IMPALA env-steps/s regressed: {rate:.0f} < " \
            f"{IMPALA_STEPS_PER_S_FLOOR}"
    finally:
        algo.cleanup()


def test_connectors_in_rollout_path(ray_session):
    """Connectors wired through AlgorithmConfig.rollouts: obs are
    transformed before the policy on the actor sampling path, and
    training still learns (reference: connector placement in
    RolloutWorker, rllib/connectors/)."""
    from ray_tpu.rllib.algorithms.appo import APPOConfig

    algo = (APPOConfig().environment("CartPole-v1")
            .rollouts(num_rollout_workers=1, rollout_fragment_length=64,
                      observation_connectors=ConnectorPipeline(
                          [ClipObs(-5.0, 5.0)]))
            .training(batches_per_step=2)
            .debugging(seed=0)
            .build())
    try:
        result = algo.train()
        # sampling + learning ran through the connector path
        assert result.get("num_env_steps_trained", 0) > 0 or \
            result.get("episodes_this_iter") is not None
    finally:
        algo.cleanup()


def test_connector_state_syncs_to_workers(ray_session):
    """A learner-side NormalizeObs filter's state pushed through
    WorkerSet.sync_connector_states actually lands in the workers'
    pipelines and changes what the policy sees."""
    from ray_tpu.rllib.core.rl_module import RLModule
    from ray_tpu.rllib.env.jax_env import CartPole
    from ray_tpu.rllib.worker_set import WorkerSet

    norm = NormalizeObs()
    pipe = ConnectorPipeline([norm])
    ws = WorkerSet(
        1, lambda i: CartPole({}),
        lambda env: RLModule(env.observation_space, env.action_space,
                             {"fcnet_hiddens": (16,)}),
        rollout_length=8, connectors={"obs": ConnectorPipeline(
            [NormalizeObs()])})
    try:
        learner_side = NormalizeObs()
        learner_side.update(np.full((100, 4), 3.0)
                            + np.random.default_rng(0).normal(
                                0, 1.0, (100, 4)))
        ws.sync_connector_states({"obs": ConnectorPipeline(
            [learner_side]).state()})
        # the worker's sampled obs are now normalized: with mean ~3
        # subtracted, raw CartPole obs (|x| <= ~0.05 at reset) map far
        # below zero
        import ray_tpu
        from ray_tpu.rllib.core.rl_module import RLModule as _RM
        mod = _RM(CartPole({}).observation_space,
                  CartPole({}).action_space, {"fcnet_hiddens": (16,)})
        import jax
        params = mod.init(jax.random.PRNGKey(0))
        batches, _, _ = ws.sample_all(params)
        obs = np.asarray(batches[0]["obs"])
        assert obs.mean() < -1.0, obs.mean()
    finally:
        ws.stop()


# ---------------------------------------------------------------------------
# model catalog: conv encoders for image observations
# (reference: rllib/models/catalog.py picks the net from the obs space)
# ---------------------------------------------------------------------------

def test_catalog_builds_conv_for_image_obs():
    import jax
    import jax.numpy as jnp
    from ray_tpu.rllib.core.rl_module import QModule, RLModule
    from ray_tpu.rllib.env.spaces import Box, Discrete

    obs_space = Box(0.0, 1.0, (12, 12, 3))
    mod = RLModule(obs_space, Discrete(4), {})
    params = mod.init(jax.random.PRNGKey(0))
    # conv kernels exist (catalog chose the conv torso, not an fcnet)
    flat = jax.tree_util.tree_leaves_with_path(params)
    assert any("Conv" in jax.tree_util.keystr(p) for p, _ in flat), \
        [jax.tree_util.keystr(p) for p, _ in flat][:6]
    obs = jnp.ones((5, 12, 12, 3))
    actions, logp, value = mod.compute_actions(
        params, obs, jax.random.PRNGKey(1))
    assert actions.shape == (5,) and value.shape == (5,)

    q = QModule(obs_space, Discrete(4), {})
    qp = q.init(jax.random.PRNGKey(0))
    assert q.q_values(qp, obs).shape == (5, 4)


class _ImageSeek:
    """Tiny image env: the agent's pixel must reach the corner; obs is a
    [8, 8, 1] grid. Exercises the conv path end-to-end in PPO's
    in-graph sampler."""

    def __init__(self, cfg=None):
        import jax.numpy as jnp
        from ray_tpu.rllib.env.spaces import Box, Discrete
        self.observation_space = Box(0.0, 1.0, (8, 8, 1))
        self.action_space = Discrete(4)
        self._jnp = jnp

    def _obs(self, pos):
        jnp = self._jnp
        grid = jnp.zeros((8, 8, 1))
        return grid.at[pos[0], pos[1], 0].set(1.0)

    def reset(self, key):
        import jax
        pos = jax.random.randint(key, (2,), 0, 8)
        state = {"pos": pos, "t": self._jnp.asarray(0, "int32")}
        return state, self._obs(pos)

    def step(self, state, action, key):
        jnp = self._jnp
        delta = jnp.asarray([[0, 1], [0, -1], [1, 0], [-1, 0]])[action]
        pos = jnp.clip(state["pos"] + delta, 0, 7)
        t = state["t"] + 1
        reached = (pos[0] == 7) & (pos[1] == 7)
        done = reached | (t >= 32)
        reward = jnp.where(reached, 1.0, -0.01)
        reset_state, reset_obs = self.reset(key)
        new = {"pos": jnp.where(done, reset_state["pos"], pos),
               "t": jnp.where(done, reset_state["t"], t)}
        obs = jnp.where(done, reset_obs, self._obs(pos))
        return new, obs, reward, done, {}


def test_ppo_conv_in_graph_smoke():
    from ray_tpu.rllib.algorithms.ppo import PPOConfig
    from ray_tpu.rllib.env.jax_env import JaxEnv

    class Env(_ImageSeek, JaxEnv):
        pass

    algo = (PPOConfig().environment(Env)
            .rollouts(num_envs_per_worker=8, rollout_fragment_length=32)
            .training(train_batch_size=256, sgd_minibatch_size=128,
                      num_sgd_iter=2)
            .debugging(seed=0)
            .build())
    r = algo.train()
    assert "episode_reward_mean" in r
    import numpy as np
    assert np.isfinite(r.get("policy_loss", 0.0))
