"""In-process fake of the TPU-VM REST surface for provider tests.

Emulates the subset of https://tpu.googleapis.com/v2 the provider uses:
node create (async long-running operation), list (with paging), get,
delete, and operation polling — plus failure injection (transient 503s,
operation-level errors) so retry and gang-atomicity behavior can be
tested without a cloud. Reference counterpart: the recorded-API unit
tests around `autoscaler/_private/gcp/node_provider.py`.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class FakeTpuApi:
    """State + behavior; serve() binds an ephemeral HTTP port."""

    def __init__(self, create_delay_s: float = 0.0,
                 fail_creates: int = 0,
                 fail_create_operation: bool = False,
                 page_size: int = 2):
        self.lock = threading.Lock()
        self.nodes: dict[str, dict] = {}          # nodeId -> node body
        self.operations: dict[str, dict] = {}     # opId -> op
        self.create_delay_s = create_delay_s
        self.fail_creates = fail_creates          # N leading 503s
        self.fail_create_operation = fail_create_operation
        self.page_size = page_size
        self.requests: list[tuple] = []           # (method, path)
        self._op_counter = 0
        self._server: ThreadingHTTPServer | None = None

    # ---- REST behavior -----------------------------------------------

    def handle(self, method: str, path: str, body: dict):
        with self.lock:
            self.requests.append((method, path))
        m = re.match(r".*/locations/[^/]+/nodes(.*)$", path)
        if m:
            rest = m.group(1)
            if method == "POST":
                return self._create(rest, body)
            if method == "GET" and rest.startswith("/"):
                return self._get(rest[1:])
            if method == "GET":
                return self._list(path)
            if method == "DELETE":
                return self._delete(rest[1:])
        m = re.match(r".*/(operations/[^/?]+)$", path)
        if m and method == "GET":
            return self._get_op(m.group(1).split("/")[-1])
        return 404, {"error": f"unhandled {method} {path}"}

    def _create(self, rest: str, body: dict):
        qm = re.search(r"nodeId=([^&]+)", rest)
        node_id = qm.group(1) if qm else f"node-{len(self.nodes)}"
        with self.lock:
            if self.fail_creates > 0:
                self.fail_creates -= 1
                return 503, {"error": "transient unavailability"}
            self._op_counter += 1
            op_id = f"op-{self._op_counter}"
            if self.fail_create_operation:
                # the async op fails: gang atomicity means NO node exists
                self.operations[op_id] = {
                    "name": f"projects/p/locations/z/operations/{op_id}",
                    "done": True,
                    "error": {"message": "no capacity for slice"},
                }
                return 200, self.operations[op_id]
            ready_at = time.time() + self.create_delay_s
            node = dict(body)
            node["name"] = f"projects/p/locations/z/nodes/{node_id}"
            node["state"] = "CREATING"
            node["_ready_at"] = ready_at
            node["networkEndpoints"] = [{"ipAddress": "10.0.0.%d"
                                         % (len(self.nodes) + 2)}]
            self.nodes[node_id] = node
            self.operations[op_id] = {
                "name": f"projects/p/locations/z/operations/{op_id}",
                "done": self.create_delay_s <= 0,
                "_node_id": node_id,
                "_ready_at": ready_at,
            }
            return 200, self._op_view(op_id)

    def _tick(self):
        now = time.time()
        for node in self.nodes.values():
            if node["state"] == "CREATING" and now >= node["_ready_at"]:
                node["state"] = "READY"
        for op in self.operations.values():
            if not op.get("done") and now >= op.get("_ready_at", 0):
                op["done"] = True

    def _op_view(self, op_id: str):
        op = self.operations[op_id]
        return {k: v for k, v in op.items() if not k.startswith("_")}

    def _node_view(self, node: dict):
        return {k: v for k, v in node.items() if not k.startswith("_")}

    def _get_op(self, op_id: str):
        with self.lock:
            self._tick()
            if op_id not in self.operations:
                return 404, {}
            return 200, self._op_view(op_id)

    def _get(self, node_id: str):
        node_id = node_id.split("?")[0]
        with self.lock:
            self._tick()
            node = self.nodes.get(node_id)
            if node is None:
                return 404, {}
            return 200, self._node_view(node)

    def _list(self, path: str):
        qm = re.search(r"pageToken=(\d+)", path)
        start = int(qm.group(1)) if qm else 0
        with self.lock:
            self._tick()
            items = [self._node_view(n) for n in self.nodes.values()]
        page = items[start:start + self.page_size]
        out = {"nodes": page}
        if start + self.page_size < len(items):
            out["nextPageToken"] = str(start + self.page_size)
        return 200, out

    def _delete(self, node_id: str):
        node_id = node_id.split("?")[0]
        with self.lock:
            node = self.nodes.pop(node_id, None)
            if node is None:
                return 404, {}
            self._op_counter += 1
            op_id = f"op-{self._op_counter}"
            self.operations[op_id] = {
                "name": f"projects/p/locations/z/operations/{op_id}",
                "done": True,
            }
            return 200, self._op_view(op_id)

    # ---- HTTP plumbing ------------------------------------------------

    def serve(self) -> str:
        api = self

        class Handler(BaseHTTPRequestHandler):
            def _go(self, method):
                length = int(self.headers.get("Content-Length") or 0)
                body = {}
                if length:
                    body = json.loads(self.rfile.read(length))
                status, payload = api.handle(method, self.path, body)
                data = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._go("GET")

            def do_POST(self):
                self._go("POST")

            def do_DELETE(self):
                self._go("DELETE")

            def log_message(self, *a):
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        host, port = self._server.server_address
        return f"http://{host}:{port}"

    def close(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
