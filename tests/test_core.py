"""Core task/object API tests.

Modeled on the reference's `python/ray/tests/test_basic.py` /
`test_advanced.py` coverage: put/get roundtrips, task graphs, error
propagation, multiple returns, nested tasks, wait semantics.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError, TaskCancelledError


def test_put_get_roundtrip(ray_session):
    for value in [1, "x", None, {"a": [1, 2]}, (1, 2), b"bytes", 3.5,
                  {1, 2, 3}]:
        assert ray_tpu.get(ray_tpu.put(value)) == value


def test_put_get_numpy_zero_copy(ray_session):
    arr = np.arange(500_000, dtype=np.float64)
    out = ray_tpu.get(ray_tpu.put(arr))
    np.testing.assert_array_equal(arr, out)
    # Large arrays come back as read-only views over shared memory,
    # like the reference's plasma-backed arrays.
    assert not out.flags.writeable


def test_simple_task(ray_session):
    @ray_tpu.remote
    def f(x):
        return x * 2

    assert ray_tpu.get(f.remote(21)) == 42


def test_task_kwargs_and_defaults(ray_session):
    @ray_tpu.remote
    def f(a, b=10, *, c=100):
        return a + b + c

    assert ray_tpu.get(f.remote(1)) == 111
    assert ray_tpu.get(f.remote(1, b=2, c=3)) == 6


def test_task_dependency_chain(ray_session):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(10):
        ref = inc.remote(ref)
    assert ray_tpu.get(ref) == 11


def test_task_fanout_fanin(ray_session):
    @ray_tpu.remote
    def sq(x):
        return x * x

    @ray_tpu.remote
    def total(*xs):
        return sum(xs)

    refs = [sq.remote(i) for i in range(10)]
    assert ray_tpu.get(total.remote(*refs)) == sum(i * i for i in range(10))


def test_num_returns(ray_session):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_error_propagation_type_preserved(ray_session):
    @ray_tpu.remote
    def boom():
        raise KeyError("missing")

    with pytest.raises(KeyError):
        ray_tpu.get(boom.remote())


def test_error_poisons_downstream(ray_session):
    @ray_tpu.remote
    def boom():
        raise ValueError("root cause")

    @ray_tpu.remote
    def consume(x):
        return x

    with pytest.raises(ValueError, match="root cause"):
        ray_tpu.get(consume.remote(boom.remote()))


def test_large_arg_promoted_to_store(ray_session):
    payload = np.random.default_rng(0).standard_normal(300_000)

    @ray_tpu.remote
    def total(x):
        return float(np.sum(x))

    assert ray_tpu.get(total.remote(payload)) == pytest.approx(
        float(np.sum(payload)))


def test_nested_task_submission(ray_session):
    @ray_tpu.remote
    def child(x):
        return x + 1

    @ray_tpu.remote
    def parent(x):
        return ray_tpu.get(child.remote(x)) + 100

    assert ray_tpu.get(parent.remote(1)) == 102


def test_get_timeout(ray_session):
    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return 1

    with pytest.raises(GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.2)


def test_wait_basic(ray_session):
    @ray_tpu.remote
    def fast():
        return 1

    @ray_tpu.remote
    def slow():
        time.sleep(3)
        return 2

    f, s = fast.remote(), slow.remote()
    ready, not_ready = ray_tpu.wait([f, s], num_returns=1, timeout=2)
    assert ready == [f] and not_ready == [s]


def test_wait_rejects_duplicates(ray_session):
    r = ray_tpu.put(1)
    with pytest.raises(ValueError):
        ray_tpu.wait([r, r])


def test_max_retries_on_crash(ray_session):
    import os as _os

    @ray_tpu.remote(max_retries=2)
    def flaky(marker_dir):
        # die the first time, succeed on retry (crash, not exception)
        import os
        marker = os.path.join(marker_dir, "attempted")
        if not os.path.exists(marker):
            open(marker, "w").close()
            os._exit(1)
        return "recovered"

    import tempfile
    with tempfile.TemporaryDirectory() as d:
        assert ray_tpu.get(flaky.remote(d), timeout=60) == "recovered"


def test_retry_exceptions(ray_session):
    @ray_tpu.remote(max_retries=3, retry_exceptions=True)
    def sometimes(marker_dir):
        import os
        marker = os.path.join(marker_dir, "n")
        n = len(os.listdir(marker_dir))
        open(marker + str(n), "w").close()
        if n < 2:
            raise RuntimeError("transient")
        return n

    import tempfile
    with tempfile.TemporaryDirectory() as d:
        assert ray_tpu.get(sometimes.remote(d), timeout=60) == 2


def test_cancel_pending(ray_session):
    @ray_tpu.remote
    def blocked(x):
        return x

    dep = ray_tpu.ObjectRef("obj_never_materializes")
    ref = blocked.remote(dep)
    assert ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=5)


def test_cluster_resources(ray_session):
    total = ray_tpu.cluster_resources()
    assert total["CPU"] == 4.0


def test_tpu_task_gets_chips(ray_session):
    @ray_tpu.remote(num_tpus=1)
    def which_chips():
        import os
        return os.environ.get("TPU_VISIBLE_CHIPS")

    chips = ray_tpu.get(which_chips.remote(), timeout=120)
    assert chips is not None and chips != ""
    # chip + TPU resource return to the pool afterwards
    deadline = time.time() + 30
    while time.time() < deadline:
        if ray_tpu.available_resources().get("TPU") == 2.0:
            break
        time.sleep(0.2)
    assert ray_tpu.available_resources()["TPU"] == 2.0


def test_object_ref_future(ray_session):
    @ray_tpu.remote
    def v():
        return 7

    assert v.remote().future().result(timeout=30) == 7


def test_config_table():
    """Typed option table: every RAY_TPU_ knob is declared once with
    type/default/doc, env overrides parse per type, and the CLI renderer
    sees them (reference: ray_config_def.h + ReadEnv)."""
    import os

    from ray_tpu._private import constants  # noqa: F401  (registers opts)
    from ray_tpu._private.config import OPTIONS, describe, get

    assert len(OPTIONS) >= 15
    rows = describe()
    assert all(r["doc"] for r in rows)
    assert get("SPILL_HIGH_WATER") == constants.SPILL_HIGH_WATER
    os.environ["RAY_TPU_SPILL_HIGH_WATER"] = "0.66"
    try:
        assert get("SPILL_HIGH_WATER") == 0.66
        assert any(r["name"] == "SPILL_HIGH_WATER" and r["overridden"]
                   for r in describe())
    finally:
        del os.environ["RAY_TPU_SPILL_HIGH_WATER"]
    os.environ["RAY_TPU_MAX_WORKERS_CAP"] = "notanint"
    try:
        import pytest
        with pytest.raises(ValueError):
            get("MAX_WORKERS_CAP")
    finally:
        del os.environ["RAY_TPU_MAX_WORKERS_CAP"]


def test_independent_task_not_stalled_by_blocked_backlog(ray_session):
    """A deep backlog of dep-BLOCKED tasks must not delay an
    independent task's dispatch (the pure-enqueue submit path still
    signals the scheduler)."""
    import time
    import ray_tpu

    @ray_tpu.remote
    def slow():
        import time as _t
        _t.sleep(3.0)
        return 1

    @ray_tpu.remote
    def dependent(x):
        return x

    @ray_tpu.remote
    def quick():
        return "now"

    gate = slow.remote()
    blocked = [dependent.remote(gate) for _ in range(64)]
    t0 = time.perf_counter()
    out = ray_tpu.get(quick.remote(), timeout=60)
    dt = time.perf_counter() - t0
    assert out == "now"
    assert dt < 2.0, f"independent task stalled {dt:.2f}s behind a " \
                     "blocked backlog"
    ray_tpu.get(blocked, timeout=120)


def test_blocked_worker_does_not_pin_pool_cap():
    """A worker blocked in get() has released its lease, so it must not
    count against MAX_WORKERS_CAP. With a cap of 1, every level of a
    nested-get chain needs a replacement worker while its parent sits
    blocked — if blocked workers held their pool slot the leaf task
    could never run (regression: push-based shuffle deadlocked once all
    32 slots held reduce tasks blocked on their mergers)."""
    import os
    import subprocess
    import sys
    import textwrap

    child = textwrap.dedent("""
        import ray_tpu
        ray_tpu.init(num_cpus=4)

        @ray_tpu.remote
        def leaf():
            return 1

        @ray_tpu.remote
        def mid():
            return ray_tpu.get(leaf.remote()) + 1

        @ray_tpu.remote
        def top():
            return ray_tpu.get(mid.remote()) + 1

        print("RESULT", ray_tpu.get(top.remote(), timeout=90))

        # replacement workers spawned past the cap while their peers
        # were blocked must retire once the pool goes idle again
        import time
        from ray_tpu._private import worker as worker_mod
        node = worker_mod.get_client().node
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            n = sum(1 for w in node.workers.values()
                    if w.kind == "generic" and w.alive)
            if n <= 1:
                break
            time.sleep(0.5)
        assert n <= 1, f"pool did not shrink back to cap: {n}"
        print("RESULT2", ray_tpu.get(leaf.remote(), timeout=60))
        ray_tpu.shutdown()
    """)
    env = dict(os.environ, RAY_TPU_MAX_WORKERS_CAP="1")
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    assert "RESULT 3" in r.stdout
    assert "RESULT2 1" in r.stdout
