"""End-to-end serve autoscaling driven by inference-engine load stats:
queue pressure published by `InferenceEngine.stats()` rides through
`Replica.stats` into the controller's demand calculation, a replica is
added under load, and scale-down drains in-flight token streams before
terminating — no stream is ever truncated by a scaling event."""

import concurrent.futures
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.engine import InferenceReplica

CFG = dict(vocab_size=128, d_model=32, n_layers=1, n_heads=2,
           d_ff=64, max_seq_len=64, dtype="float32")


class ThrottledReplica(InferenceReplica):
    """InferenceReplica whose engine tick is slowed to hardware-ish
    latency: the CPU toy model otherwise drains any queue in well under
    one controller reconcile period, so queue pressure would never be
    observable, let alone actionable."""

    def __init__(self, *args, step_sleep: float = 0.015, **kwargs):
        super().__init__(*args, **kwargs)
        orig = self.engine.step

        def slow_step():
            time.sleep(step_sleep)
            return orig()

        self.engine.step = slow_step


@pytest.fixture
def serve_session(ray_session):
    yield serve
    serve.shutdown()


def _status(name):
    return serve.status()[f"{name}:ThrottledReplica"]


def test_engine_stats_drive_replica_autoscaling(serve_session):
    app = serve.deployment(
        ThrottledReplica,
        autoscaling_config={
            "min_replicas": 1, "max_replicas": 2,
            "target_num_ongoing_requests_per_replica": 2,
            "downscale_delay_s": 1.5,
        },
    ).bind(CFG, slots=1, max_len=64)
    h = serve.run(app, name="t_iauto")
    assert _status("t_iauto")["replicas"] == 1

    # warm the engine (compile prefill/decode once) before the ramp
    warm = list(h.stream([5, 9, 3], 4))
    assert len(warm) == 4

    # ---- ramp: 8 concurrent streams against a 1-slot engine ----------
    # 7 requests queue behind the slot and the throttled engine holds
    # them there across reconcile ticks; inflight + queue_depth blows
    # past target_per=2, so the controller must add the second replica.
    n_tok = 32
    grew = False

    def one_stream(_):
        return list(h.stream([5, 9, 3], n_tok))

    with concurrent.futures.ThreadPoolExecutor(8) as pool:
        futs = [pool.submit(one_stream, i) for i in range(8)]
        deadline = time.time() + 60
        while time.time() < deadline:
            st = _status("t_iauto")
            if st["target_replicas"] >= 2 and st["replicas"] >= 2:
                grew = True
                break
            time.sleep(0.2)
        outs = [f.result(timeout=120) for f in futs]
    assert grew, f"never scaled up: {_status('t_iauto')}"
    # zero dropped/truncated streams through the scale-up, and both
    # replicas decode greedily from the same seed -> identical tokens
    assert all(len(o) == n_tok for o in outs)
    assert all(o == outs[0] for o in outs)

    # engine load stats are visible through the replica handle
    s = ray_tpu.get(h.stats.remote(), timeout=30)
    for key in ("queue_depth", "decode_tok_s", "queue_wait_ms_p50",
                "queue_wait_ms_p99", "tokens_per_step"):
        assert key in s, s

    # ---- drop: one straggler stream straddles the scale-down ---------
    # Load falls to a single stream; after downscale_delay_s the
    # controller retires a replica, draining it first. The straggler
    # must still receive every one of its tokens.
    it = h.stream([5, 9, 3], n_tok)
    straggler = [next(it) for _ in range(2)]
    deadline = time.time() + 90
    while time.time() < deadline:
        st = _status("t_iauto")
        if st["target_replicas"] == 1 and st["replicas"] == 1:
            break
        time.sleep(0.25)
    else:
        pytest.fail(f"never scaled back down: {_status('t_iauto')}")
    straggler.extend(it)
    assert straggler == outs[0], "scale-down truncated a live stream"

    # traffic still flows after the scaling cycle (possibly on the
    # surviving, freshly-compiled replica)
    assert list(h.stream([5, 9, 3], 4)) == warm
