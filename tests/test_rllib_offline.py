"""Off-policy estimation: IS / WIS / DM / DR against a known-policy
synthetic MDP, plus offline input through ray_tpu.data datasets.

References: `rllib/offline/estimators/{importance_sampling,
weighted_importance_sampling,direct_method,doubly_robust}.py` (the
reference validates the same way: estimators on batches whose true
target-policy value is known), `rllib/offline/dataset_reader.py`.
"""

import numpy as np
import pytest

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.offline import (
    DatasetReader,
    FittedQEvaluation,
    JsonReader,
    JsonWriter,
    direct_method,
    doubly_robust,
    importance_sampling,
    weighted_importance_sampling,
)
from ray_tpu.rllib.sample_batch import SampleBatch

# Synthetic MDP: T-step chain, 2 actions; action 1 pays 1, action 0 pays
# 0; obs = [t/T, 1]. A policy with P(a=1) = p has true value T*p
# (gamma=1) — analytic ground truth for every estimator.
T = 3
P_BEHAVIOR = 0.5
P_TARGET = 0.9
TRUE_V_TARGET = T * P_TARGET
TRUE_V_BEHAVIOR = T * P_BEHAVIOR


def _gen_batch(n_episodes: int, seed: int = 0) -> SampleBatch:
    rng = np.random.default_rng(seed)
    rows = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.DONES,
                            sb.ACTION_LOGP, sb.NEXT_OBS, sb.EPS_ID)}
    for ep in range(n_episodes):
        for t in range(T):
            a = int(rng.random() < P_BEHAVIOR)
            rows[sb.OBS].append([t / T, 1.0])
            rows[sb.NEXT_OBS].append([(t + 1) / T, 1.0])
            rows[sb.ACTIONS].append(a)
            rows[sb.REWARDS].append(float(a))
            rows[sb.DONES].append(t == T - 1)
            rows[sb.ACTION_LOGP].append(
                np.log(P_BEHAVIOR if a else 1 - P_BEHAVIOR))
            rows[sb.EPS_ID].append(ep)
    return SampleBatch({
        sb.OBS: np.asarray(rows[sb.OBS], np.float32),
        sb.NEXT_OBS: np.asarray(rows[sb.NEXT_OBS], np.float32),
        sb.ACTIONS: np.asarray(rows[sb.ACTIONS], np.int32),
        sb.REWARDS: np.asarray(rows[sb.REWARDS], np.float32),
        sb.DONES: np.asarray(rows[sb.DONES]),
        sb.ACTION_LOGP: np.asarray(rows[sb.ACTION_LOGP], np.float32),
        sb.EPS_ID: np.asarray(rows[sb.EPS_ID], np.int64),
    })


def _target_logp_probs(batch):
    a = np.asarray(batch[sb.ACTIONS])
    logp = np.where(a == 1, np.log(P_TARGET), np.log(1 - P_TARGET))
    probs = np.tile([1 - P_TARGET, P_TARGET], (len(a), 1))
    return logp.astype(np.float32), probs.astype(np.float32)


@pytest.fixture(scope="module")
def batch():
    return _gen_batch(400)


@pytest.fixture(scope="module")
def fitted_q(batch):
    _, probs = _target_logp_probs(batch)
    q = FittedQEvaluation(obs_shape=(2,), num_actions=2, gamma=1.0,
                          n_iters=30, sgd_steps_per_iter=20, lr=3e-2,
                          seed=0)
    # state-independent target policy: probs on s' equal probs on s
    out = q.fit(batch, probs, target_probs_next=probs)
    assert np.isfinite(out["loss"])
    return q


def test_is_recovers_target_value(batch):
    logp, _ = _target_logp_probs(batch)
    est = importance_sampling(batch, logp, gamma=1.0)
    assert est["v_behavior"] == pytest.approx(TRUE_V_BEHAVIOR, abs=0.15)
    assert est["v_target"] == pytest.approx(TRUE_V_TARGET, abs=0.45)


def test_wis_recovers_target_value_lower_variance(batch):
    logp, _ = _target_logp_probs(batch)
    est = weighted_importance_sampling(batch, logp, gamma=1.0)
    assert est["v_target"] == pytest.approx(TRUE_V_TARGET, abs=0.35)
    # WIS should sit closer to truth than IS on small resamples
    errs_is, errs_wis = [], []
    for seed in range(4):
        small = _gen_batch(40, seed=seed + 10)
        lp, _ = _target_logp_probs(small)
        errs_is.append(abs(importance_sampling(
            small, lp, 1.0)["v_target"] - TRUE_V_TARGET))
        errs_wis.append(abs(weighted_importance_sampling(
            small, lp, 1.0)["v_target"] - TRUE_V_TARGET))
    assert np.mean(errs_wis) <= np.mean(errs_is) + 0.05


def test_fqe_learns_q(batch, fitted_q):
    """Q^π(s, a) = a + (T - 1 - t) * p for t < T-1; spot-check t=0."""
    q0 = fitted_q.q_values(np.asarray([[0.0, 1.0]], np.float32))[0]
    assert q0[1] == pytest.approx(1 + 2 * P_TARGET, abs=0.3)
    assert q0[0] == pytest.approx(0 + 2 * P_TARGET, abs=0.3)


def test_dm_recovers_target_value(batch, fitted_q):
    _, probs = _target_logp_probs(batch)
    est = direct_method(batch, probs, fitted_q, gamma=1.0)
    assert est["v_target"] == pytest.approx(TRUE_V_TARGET, abs=0.3)
    assert est["v_behavior"] == pytest.approx(TRUE_V_BEHAVIOR, abs=0.15)
    assert est["v_gain"] > 1.0


def test_dr_recovers_target_value(batch, fitted_q):
    logp, probs = _target_logp_probs(batch)
    est = doubly_robust(batch, logp, probs, fitted_q, gamma=1.0)
    assert est["v_target"] == pytest.approx(TRUE_V_TARGET, abs=0.3)
    # DR with a WRONG model must still be consistent (weights correct):
    bad_q = FittedQEvaluation(obs_shape=(2,), num_actions=2, gamma=1.0,
                              n_iters=0, seed=1)    # unfitted network
    out = bad_q.fit(batch, probs)       # n_iters=0: no-op, must not crash
    assert out["losses"] == []
    est_bad = doubly_robust(batch, logp, probs, bad_q, gamma=1.0)
    assert est_bad["v_target"] == pytest.approx(TRUE_V_TARGET, abs=0.5)


def test_json_roundtrip_feeds_estimators(tmp_path, batch):
    w = JsonWriter(str(tmp_path))
    w.write(batch)
    w.close()
    back = JsonReader(str(tmp_path)).read_all()
    logp, _ = _target_logp_probs(back)
    est = importance_sampling(back, logp, gamma=1.0)
    assert est["v_target"] == pytest.approx(TRUE_V_TARGET, abs=0.45)


def test_dqn_offline_input_from_dataset(ray_session, batch):
    """An algorithm's offline_data(input_=...) accepts a
    ray_tpu.data.Dataset directly (reference: rllib reads offline data
    through Ray Data, rllib/offline/dataset_reader.py)."""
    from ray_tpu import data as rdata
    from ray_tpu.rllib.algorithms.dqn import DQNConfig

    rng = np.random.default_rng(0)
    items = []
    for i in range(256):
        items.append({
            sb.OBS: rng.normal(size=4).tolist(),
            sb.NEXT_OBS: rng.normal(size=4).tolist(),
            sb.ACTIONS: int(rng.integers(0, 2)),
            sb.REWARDS: 1.0,
            sb.DONES: bool(i % 32 == 31),
        })
    ds = rdata.from_items(items)
    algo = (DQNConfig().environment("CartPole-v1")
            .training(learning_starts=64, train_batch_size=64,
                      n_updates_per_iter=4,
                      model={"fcnet_hiddens": (16,)})
            .offline_data(input_=ds)
            .debugging(seed=0).build())
    r = algo.train()
    assert r["num_env_steps_sampled"] > 0
    assert np.isfinite(r["loss"])


def test_dataset_reader_parquet_roundtrip(ray_session, tmp_path, batch):
    """Offline data through the Data library: SampleBatch columns →
    parquet → ray_tpu.data.read_parquet → DatasetReader → estimators
    (reference: rllib/offline/dataset_reader.py)."""
    from ray_tpu import data as rdata

    items = [
        {k: (batch[k][i].tolist()
             if getattr(batch[k][i], "ndim", 0) else batch[k][i].item())
         for k in batch.keys()}
        for i in range(len(batch))
    ]
    ds = rdata.from_items(items)
    pq_dir = str(tmp_path / "pq")
    ds.write_parquet(pq_dir)
    ds2 = rdata.read_parquet(pq_dir)

    reader = DatasetReader(ds2, batch_size=128)
    mini = reader.next()
    assert isinstance(mini, SampleBatch) and len(mini) == 128

    full = reader.read_all()
    assert len(full) == len(batch)
    # row order survives the roundtrip => episode structure intact
    order = np.argsort(np.asarray(full[sb.EPS_ID]), kind="stable")
    full = SampleBatch({k: np.asarray(full[k])[order]
                        for k in full.keys()})
    logp, _ = _target_logp_probs(full)
    est = importance_sampling(full, logp, gamma=1.0)
    assert est["v_target"] == pytest.approx(TRUE_V_TARGET, abs=0.45)
