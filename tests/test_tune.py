"""Tune-equivalent tests, modeled on the reference's `tune/tests/`
(test_tune_run, test_trial_scheduler, test_searchers)."""

import os

import pytest

from ray_tpu import tune
from ray_tpu.train.config import CheckpointConfig, RunConfig
from ray_tpu.tune.schedulers import AsyncHyperBandScheduler
from ray_tpu.tune.search import count_variants, generate_variants


# ---------------------------------------------------------------------------
# Search-space unit tests (no cluster needed)
# ---------------------------------------------------------------------------


def test_generate_variants_grid_and_samples():
    space = {
        "lr": tune.grid_search([0.1, 0.01]),
        "wd": tune.uniform(0.0, 1.0),
        "nested": {"units": tune.choice([32, 64])},
        "const": "adam",
    }
    variants = list(generate_variants(space, num_samples=3, seed=0))
    assert len(variants) == 6
    assert count_variants(space, 3) == 6
    for v in variants:
        assert v["lr"] in (0.1, 0.01)
        assert 0.0 <= v["wd"] <= 1.0
        assert v["nested"]["units"] in (32, 64)
        assert v["const"] == "adam"


def test_sample_domains():
    import random
    rng = random.Random(0)
    for _ in range(50):
        assert 1 <= tune.randint(1, 10).sample(rng) < 10
        v = tune.loguniform(1e-4, 1e-1).sample(rng)
        assert 1e-4 <= v <= 1e-1
        q = tune.quniform(0, 1, 0.25).sample(rng)
        assert q in (0.0, 0.25, 0.5, 0.75, 1.0)


def test_sample_from_sees_spec():
    space = {"a": 4, "b": tune.sample_from(lambda spec: spec["a"] * 2)}
    (v,) = generate_variants(space, 1, seed=0)
    assert v["b"] == 8


# ---------------------------------------------------------------------------
# Scheduler unit tests (pure logic, mirrors scheduler tests in the ref)
# ---------------------------------------------------------------------------


class _T:
    def __init__(self, tid, config=None):
        self.trial_id = tid
        self.config = config or {}


def test_asha_stops_bad_trials():
    sched = AsyncHyperBandScheduler(metric="acc", mode="max", max_t=100,
                                    grace_period=1, reduction_factor=2)
    good, bad = _T("good"), _T("bad")
    sched.on_trial_add(good)
    sched.on_trial_add(bad)
    # Feed diverging curves; the bad trial must be stopped at some rung.
    decisions = []
    for it in range(1, 50):
        sched.on_trial_result(good, {"training_iteration": it,
                                     "acc": 0.9 + it * 0.001})
        decisions.append(
            sched.on_trial_result(bad, {"training_iteration": it,
                                        "acc": 0.1}))
    assert "STOP" in decisions


def test_median_stopping():
    from ray_tpu.tune.schedulers import MedianStoppingRule
    sched = MedianStoppingRule(metric="loss", mode="min", grace_period=2,
                               min_samples_required=2)
    trials = [_T(f"t{i}") for i in range(4)]
    for it in range(1, 6):
        for t in trials[:-1]:
            assert sched.on_trial_result(
                t, {"training_iteration": it, "loss": 0.1}) == "CONTINUE"
    # last trial is much worse than the median → stopped
    d = None
    for it in range(1, 6):
        d = sched.on_trial_result(
            trials[-1], {"training_iteration": it, "loss": 100.0})
    assert d == "STOP"


# ---------------------------------------------------------------------------
# End-to-end runs on the shared local cluster
# ---------------------------------------------------------------------------


def _trainable(config):
    for it in range(5):
        tune.report({"score": config["x"] * (it + 1)})


def test_tuner_function_trainable(ray_session, tmp_path):
    tuner = tune.Tuner(
        _trainable,
        param_space={"x": tune.grid_search([1.0, 2.0, 3.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="grid", storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert len(grid) == 3
    best = grid.get_best_result("score", "max")
    assert best.metrics["score"] == pytest.approx(15.0)
    assert not grid.errors


def test_tune_run_stop_criteria(ray_session, tmp_path):
    def forever(config):
        it = 0
        while True:
            it += 1
            tune.report({"v": it})

    grid = tune.run(forever, config={"x": tune.choice([1])},
                    num_samples=2, metric="v", mode="max",
                    stop={"training_iteration": 4},
                    storage_path=str(tmp_path), name="stopme")
    for r in grid:
        assert r.metrics["training_iteration"] == 4


class _Counter(tune.Trainable):
    def setup(self, config):
        self.count = config.get("start", 0)

    def step(self):
        self.count += 1
        return {"count": self.count}

    def save_checkpoint(self, d):
        return {"count": self.count}

    def load_checkpoint(self, data):
        self.count = data["count"]


def test_class_trainable_with_checkpointing(ray_session, tmp_path):
    grid = tune.run(_Counter, config={"start": 10}, num_samples=1,
                    stop={"training_iteration": 3},
                    checkpoint_freq=1,
                    storage_path=str(tmp_path), name="cls")
    r = grid[0]
    assert r.metrics["count"] == 13
    assert r.checkpoint is not None
    assert r.checkpoint.to_dict()["count"] == 13


def test_trainable_error_is_reported(ray_session, tmp_path):
    def boom(config):
        tune.report({"ok": 1})
        raise ValueError("kaput")

    grid = tune.run(boom, num_samples=1, storage_path=str(tmp_path),
                    name="boom")
    assert len(grid.errors) == 1
    assert "kaput" in grid.errors[0]


def test_experiment_state_persisted(ray_session, tmp_path):
    tuner = tune.Tuner(
        _trainable,
        param_space={"x": tune.grid_search([1.0, 2.0])},
        run_config=RunConfig(name="persist", storage_path=str(tmp_path)))
    grid = tuner.fit()
    state_file = os.path.join(grid.experiment_path,
                              "experiment_state.json")
    assert os.path.exists(state_file)
    # Restore sees the terminated trials and does not rerun them.
    grid2 = tune.Tuner.restore(grid.experiment_path, _trainable).fit()
    assert len(grid2) == 2


def test_asha_end_to_end(ray_session, tmp_path):
    def trainable(config):
        for it in range(20):
            tune.report({"acc": config["lr"] * (it + 1)})

    # Sequential + weakest trial last: its rung cutoffs are fully
    # populated by the stronger earlier trials, so the early stop is
    # deterministic (parallel arrival order would make it racy).
    grid = tune.run(trainable,
                    config={"lr": tune.grid_search([2.0, 1.0, 0.5, 0.1])},
                    metric="acc", mode="max",
                    max_concurrent_trials=1,
                    scheduler=tune.ASHAScheduler(
                        metric="acc", mode="max", max_t=20,
                        grace_period=2, reduction_factor=2),
                    storage_path=str(tmp_path), name="asha")
    best = grid.get_best_result("acc", "max")
    assert best.metrics["acc"] == pytest.approx(40.0)
    # at least one weaker trial should have been cut early
    iters = [r.metrics.get("training_iteration", 0) for r in grid]
    assert min(iters) < 20


def _tiny_train_loop(config):
    from ray_tpu.train import session
    for i in range(3):
        session.report({"loss": config["lr"] * (3 - i)})


def test_tuner_over_jax_trainer(ray_session, tmp_path):
    """Tune sweeps a JaxTrainer's train_loop_config (the reference's
    Trainer-as-Trainable path, base_trainer.py:829 — but one-way here)."""
    from ray_tpu.train import JaxTrainer, ScalingConfig

    trainer = JaxTrainer(
        _tiny_train_loop,
        train_loop_config={"lr": 0.0},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="inner", storage_path=str(tmp_path)))
    grid = tune.Tuner(
        trainer,
        param_space={"lr": tune.grid_search([0.1, 0.2])},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    max_concurrent_trials=1),
        run_config=RunConfig(name="sweep", storage_path=str(tmp_path))).fit()
    assert len(grid) == 2
    assert not grid.errors
    best = grid.get_best_result("loss", "min")
    assert best.metrics["loss"] == pytest.approx(0.1)


def test_trial_gang_reservation_serializes(ray_session, tmp_path):
    """Two multi-worker trainer trials on a just-big-enough cluster must
    SERIALIZE through whole-gang placement-group reservations instead of
    each grabbing part of its worker group (reference:
    tune/execution/placement_groups.py:9 — every trial reserves through a
    PlacementGroupFactory). On a 4-CPU cluster, each trial needs
    1 (executor) + 2 (workers) = 3 CPUs, so the second trial's gang only
    fits after the first finishes; without atomic reservation the second
    trainer's inner placement_group() call would fail and error the
    trial."""
    import ray_tpu
    from ray_tpu.train import JaxTrainer, ScalingConfig

    events = []

    class _Recorder:
        def on_trial_start(self, trial):
            events.append(("start", trial.trial_id))

        def on_trial_complete(self, trial, result):
            events.append(("complete", trial.trial_id))

    cpus_before = ray_tpu.available_resources()["CPU"]
    trainer = JaxTrainer(
        _tiny_train_loop,
        train_loop_config={"lr": 0.0},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="inner", storage_path=str(tmp_path)))
    grid = tune.Tuner(
        trainer,
        param_space={"lr": tune.grid_search([0.1, 0.2])},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(name="gang", storage_path=str(tmp_path),
                             callbacks=[_Recorder()])).fit()
    assert len(grid) == 2
    assert not grid.errors, grid.errors
    # serialized: first trial completed before the second started
    assert [e[0] for e in events] == [
        "start", "complete", "start", "complete"], events
    # no leaked reservations
    assert ray_tpu.available_resources()["CPU"] == cpus_before
    from ray_tpu.util.state import list_placement_groups
    assert list_placement_groups() == []


def test_trial_pg_reserved_and_released(ray_session, tmp_path):
    """Every trial (even a plain function trainable) runs inside its own
    placement-group reservation, released at trial end."""
    import ray_tpu

    def probe(config):
        from ray_tpu.tune.trainable import report
        report({"score": 1.0})

    cpus_before = ray_tpu.available_resources()["CPU"]
    grid = tune.run(probe, config={}, num_samples=2, metric="score",
                    mode="max", storage_path=str(tmp_path), name="pgres")
    assert len(grid) == 2 and not grid.errors
    assert ray_tpu.available_resources()["CPU"] == cpus_before
    from ray_tpu.util.state import list_placement_groups
    assert list_placement_groups() == []


def test_infeasible_gang_errors_instead_of_hanging(ray_session, tmp_path):
    """A trial whose gang can never fit the cluster fails fast with a
    placement error instead of spinning the controller forever."""
    from ray_tpu.tune.trainable import with_resources

    def probe(config):
        from ray_tpu.tune.trainable import report
        report({"score": 1.0})

    big = with_resources(probe, {"bundles": [{"CPU": 1000}],
                                 "strategy": "PACK"})
    grid = tune.run(big, config={}, num_samples=1, metric="score",
                    mode="max", storage_path=str(tmp_path), name="infeas")
    assert grid.errors and "placement group infeasible" in grid.errors[0]


def test_concurrency_limiter_runs_all_samples(ray_session, tmp_path):
    """A ConcurrencyLimiter caps parallelism, not the trial count."""
    from ray_tpu.tune.search import BasicVariantGenerator, ConcurrencyLimiter

    searcher = ConcurrencyLimiter(
        BasicVariantGenerator({"x": tune.uniform(0, 1)}, num_samples=5),
        max_concurrent=2)
    grid = tune.Tuner(
        _trainable,
        tune_config=tune.TuneConfig(num_samples=5, search_alg=searcher,
                                    metric="score", mode="max"),
        run_config=RunConfig(name="limiter",
                             storage_path=str(tmp_path))).fit()
    assert len(grid) == 5
    assert not grid.errors
