"""Trainer child for the kill/resume chaos suite (tests/test_chaos.py).

Run as __main__ in a fresh subprocess so a SIGKILL takes out a real
trainer process (not a thread) and so the resumed run can pick its own
device count. All configuration rides in env vars:

  FT_ROOT     checkpoint root directory (required)
  FT_OUT      where to write the result JSON
              {"start": s, "steps": [...], "losses": [...]}
  FT_MODE     "train" (default) | "resume"
  FT_STEPS    total global steps to train through (default 12)
  FT_EVERY    snapshot cadence; 0 disables checkpointing (default 0)
  FT_UNROLL   steps fused per dispatch (default 2)
  FT_DEVICES  CPU device count for this process (default 8)
  FT_CRASH_AT SIGKILL self once the host feed reaches this batch index
              AND at least one checkpoint has committed (default: never)

The data stream is deterministic per global step index, so a resumed
run that fast-forwards past the restored step replays exactly the
batches the killed run would have consumed.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

import numpy as np

BATCH = 8           # divisible across 8/4/2/1-device data sharding
SEQ = 16
VOCAB = 128


def make_cfg():
    from ray_tpu.models import gpt
    return gpt.small(vocab_size=VOCAB, d_model=32, n_layers=1,
                     n_heads=2, d_ff=64, max_seq_len=SEQ)


def host_batches(start: int = 0):
    """Deterministic stream: batch for global step i is a pure function
    of i (rng seeded per step), so kill/resume replays identically."""
    idx = start
    while True:
        rng = np.random.default_rng(1234 + idx)
        toks = rng.integers(0, VOCAB, (BATCH, SEQ + 1), np.int32)
        yield {"inputs": toks[:, :-1], "targets": toks[:, 1:]}
        idx += 1


def _killing_feed(inner, ckpt, crash_at: int):
    """Pass batches through until the feed reaches `crash_at`, then wait
    for the first committed checkpoint and SIGKILL the whole process —
    the hard host loss the chaos test is about."""
    for idx, batch in enumerate(inner):
        if idx >= crash_at:
            deadline = time.time() + 120
            while ckpt.commits < 1 and time.time() < deadline:
                time.sleep(0.01)
            os.kill(os.getpid(), signal.SIGKILL)
        yield batch


def main() -> None:
    devices = int(os.environ.get("FT_DEVICES", "8"))
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices}")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from ray_tpu.parallel import MeshSpec
    from ray_tpu.train import ft, loop, spmd

    root = os.environ["FT_ROOT"]
    out = os.environ.get("FT_OUT")
    mode = os.environ.get("FT_MODE", "train")
    steps = int(os.environ.get("FT_STEPS", "12"))
    every = int(os.environ.get("FT_EVERY", "0"))
    unroll = int(os.environ.get("FT_UNROLL", "2"))
    crash_at = int(os.environ.get("FT_CRASH_AT", "-1"))

    import jax
    cfg = make_cfg()
    mesh = MeshSpec(data=-1).build(jax.devices())

    if mode == "resume":
        _, step_fn, _ = spmd.make_gpt_trainer(cfg, mesh, init_state=False)
        state, start = ft.restore_resharded(root, mesh)
        host = ft.fast_forward(host_batches(), start)
    else:
        state, step_fn, _ = spmd.make_gpt_trainer(cfg, mesh)
        start, host = 0, host_batches()

    ckpt = None
    if every > 0:
        ckpt = ft.AsyncCheckpointer(root, every=every, max_in_flight=2,
                                    keep=2)
    if crash_at >= 0:
        assert ckpt is not None, "FT_CRASH_AT needs FT_EVERY > 0"
        host = _killing_feed(host, ckpt, crash_at)

    place = loop.make_placer(mesh, stacked=unroll > 1)
    batches = loop.DevicePrefetcher(host, place, depth=2, group=unroll)
    train = loop.TrainLoop(step_fn, unroll=unroll, metrics_interval=4,
                           checkpointer=ckpt)
    state, metrics = train.run(state, batches, num_steps=steps,
                               start_step=start)

    if ckpt is not None:
        ckpt.check_invariants()
        ckpt.close()
    if out:
        record = {
            "start": int(start),
            "steps": [int(m["step"]) for m in metrics],
            "losses": [float(m["loss"]) for m in metrics],
        }
        with open(out, "w") as f:
            json.dump(record, f)


if __name__ == "__main__":
    main()
