"""QMIX — cooperative value factorization (reference:
rllib/algorithms/qmix/)."""

import numpy as np


def test_qmix_monotonic_mixer():
    """The mixer's Q_tot must be monotone in every agent's Q (the QMIX
    constraint that makes decentralized argmax team-optimal)."""
    import jax
    import jax.numpy as jnp
    from ray_tpu.rllib.algorithms.qmix import _MonotonicMixer

    mixer = _MonotonicMixer(n_agents=3, embed=16)
    state = jax.random.normal(jax.random.PRNGKey(0), (5, 9))
    qs = jax.random.normal(jax.random.PRNGKey(1), (5, 3))
    params = mixer.init(jax.random.PRNGKey(2), state, qs)

    grad = jax.grad(
        lambda q: mixer.apply(params, state, q).sum())(qs)
    assert np.all(np.asarray(grad) >= -1e-6), \
        "mixer is not monotone in agent Qs"


def test_qmix_learns_shared_reward_coop():
    """QMIX solves CoopMatch (shared team reward, per-agent private
    observations): monotonic mixing must route the shared-scalar credit
    back to each agent's own Q. Team optimum = 8."""
    from ray_tpu.rllib.train import list_tuned_examples, run_tuned_example
    path = [p for p in list_tuned_examples() if "coopmatch-qmix" in p][0]
    res = run_tuned_example(path, verbose=False)
    assert res["best_reward"] >= 6.5, res
