"""Conda + container runtime environments and cache eviction
(reference: `_private/runtime_env/conda.py`, `container.py`,
`uri_cache.py`).

Neither conda nor docker exists in this image, so the tests drive the
REAL code paths through fake binaries on PATH: the fake conda builds a
working env dir (bin/python -> the real interpreter) and records each
invocation, proving cache reuse; the fake docker strips the `run`
wrapper and execs the worker command locally, proving the wrapped
worker actually registers and runs tasks.
"""

import os
import stat
import sys
import textwrap

import numpy as np
import pytest

import ray_tpu


def _write_exe(path, body):
    with open(path, "w") as f:
        f.write(body)
    os.chmod(path, os.stat(path).st_mode | stat.S_IEXEC)


@pytest.fixture()
def fake_bin(tmp_path, monkeypatch):
    d = tmp_path / "bin"
    d.mkdir()
    monkeypatch.setenv("PATH", f"{d}:{os.environ['PATH']}")
    return d


def test_conda_env_cached_and_used(tmp_path, fake_bin, monkeypatch):
    calls = tmp_path / "conda_calls"
    _write_exe(fake_bin / "conda", textwrap.dedent(f"""\
        #!/bin/bash
        # fake `conda env create --yes -p DEST -f SPEC`
        echo "$@" >> {calls}
        while [ $# -gt 0 ]; do
          if [ "$1" = "-p" ]; then DEST="$2"; fi
          shift
        done
        mkdir -p "$DEST/bin"
        ln -s "{sys.executable}" "$DEST/bin/python"
        """))
    cache = tmp_path / "cache"
    monkeypatch.setenv("RAY_TPU_RUNTIME_ENV_CACHE", str(cache))
    from ray_tpu._private.runtime_env import RuntimeEnvManager
    mgr = RuntimeEnvManager(str(cache))
    spec = {"name": "t", "dependencies": ["python=3.12"]}
    env, cwd, python_exe, prefix = mgr.setup({"conda": spec})
    assert prefix is None
    assert python_exe and os.path.exists(python_exe)
    assert "conda_" in python_exe
    # second setup of the SAME spec: cache hit, conda NOT re-invoked
    _, _, python_exe2, _ = mgr.setup({"conda": spec})
    assert python_exe2 == python_exe
    assert len(calls.read_text().splitlines()) == 1
    # a different spec builds a different env
    _, _, python_exe3, _ = mgr.setup(
        {"conda": {"name": "u", "dependencies": ["python=3.12"]}})
    assert python_exe3 != python_exe
    assert len(calls.read_text().splitlines()) == 2


def test_conda_missing_binary_errors(tmp_path, monkeypatch):
    from ray_tpu._private.runtime_env import RuntimeEnvManager
    from ray_tpu.exceptions import RuntimeEnvSetupError
    monkeypatch.setenv("RAY_TPU_CONDA_BINARY", "definitely-not-conda")
    mgr = RuntimeEnvManager(str(tmp_path / "c"))
    with pytest.raises(RuntimeEnvSetupError, match="conda"):
        mgr.setup({"conda": {"name": "x"}})


def test_container_prefix_shape(fake_bin, monkeypatch):
    _write_exe(fake_bin / "docker", "#!/bin/bash\nexit 0\n")
    monkeypatch.delenv("RAY_TPU_CONTAINER_RUNTIME", raising=False)
    from ray_tpu._private.runtime_env import RuntimeEnvManager
    mgr = RuntimeEnvManager()
    _, _, _, prefix = mgr.setup(
        {"container": {"image": "img:1", "run_options": ["--gpus=all"]}})
    assert prefix[0].endswith("docker") or prefix[0] == "docker"
    assert prefix[1] == "run"
    assert "/dev/shm:/dev/shm" in prefix        # shm arena reachable
    assert "--gpus=all" in prefix
    assert prefix[-1] == "img:1"


def test_container_task_runs_via_runtime(ray_session, fake_bin,
                                         monkeypatch, tmp_path):
    """End-to-end: a task with runtime_env={'container': ...} launches
    through the container runtime's `run` command. The fake docker
    records the invocation then execs the wrapped worker locally, so
    the worker genuinely registers and executes the task."""
    calls = tmp_path / "docker_calls"
    _write_exe(fake_bin / "docker", textwrap.dedent(f"""\
        #!/bin/bash
        echo "$@" >> {calls}
        # drop everything through the image name, then exec the worker
        args=("$@")
        for i in "${{!args[@]}}"; do
          if [ "${{args[$i]}}" = "test-image:v1" ]; then
            rest=("${{args[@]:$((i+1))}}")
            # host-side fake: the host interpreter stands in for the
            # image's python3
            exec "{sys.executable}" "${{rest[@]:1}}"
          fi
        done
        exit 64
        """))
    monkeypatch.setenv("RAY_TPU_CONTAINER_RUNTIME", str(fake_bin / "docker"))

    @ray_tpu.remote(runtime_env={"container": {"image": "test-image:v1"}})
    def inside():
        return "ran-in-container"

    assert ray_tpu.get(inside.remote(), timeout=120) == "ran-in-container"
    logged = calls.read_text()
    assert "run" in logged and "test-image:v1" in logged
    assert "/dev/shm:/dev/shm" in logged


def test_cache_byte_eviction(tmp_path, monkeypatch):
    """LRU entries are evicted when the cache exceeds the byte budget
    (uri_cache.py behavior), not just the entry-count cap."""
    monkeypatch.setenv("RAY_TPU_RUNTIME_ENV_CACHE_BYTES", "8192")
    from ray_tpu._private.runtime_env import RuntimeEnvManager
    cache = tmp_path / "cache"
    mgr = RuntimeEnvManager(str(cache))
    import time as _time
    srcs = []
    for i in range(4):
        src = tmp_path / f"wd{i}"
        src.mkdir()
        # distinct SIZES: the working-dir fingerprint is size+mtime
        # based, so same-size trees within one mtime second would
        # collapse to one cache entry
        (src / "blob.bin").write_bytes(bytes(4096 + i * 16))
        srcs.append(src)
    staged = []
    for src in srcs:
        _, cwd, _, _ = mgr.setup({"working_dir": str(src)})
        staged.append(cwd)
        _time.sleep(0.05)      # distinct mtimes for LRU order
    # 4 x ~4KB > 8KB budget: the OLDEST entries are gone, newest remain
    assert not os.path.isdir(staged[0])
    assert not os.path.isdir(staged[1])
    assert os.path.isdir(staged[-1])
